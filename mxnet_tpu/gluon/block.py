"""Block / HybridBlock — the neural-network module system.

Reference parity (leezu/mxnet): ``python/mxnet/gluon/block.py`` — ``Block``
(child/param registry via ``__setattr__``), ``HybridBlock`` (hybridize →
CachedOp; export), ``SymbolBlock`` analog via :func:`load_export`.

Design (tpu-first): ``hybridize()`` replaces the reference's
NNVM-trace-to-CachedOp (``src/imperative/cached_op.cc``) with a
``jax.jit``-compiled executable cached per input signature
(shapes/dtypes/train-flag). One trace captures forward; backward comes for
free through ``jax.vjp`` of the compiled callable, so a hybridized training
step runs as ONE fused XLA program each for fwd and bwd — the analog of
CachedOp's full fwd+bwd graph with op bulking, with XLA doing the fusion.
PRNG: the trace threads a threefry key argument so dropout stays pure
(``ndarray/random.py trace_key_scope``). ``static_alloc`` maps to buffer
donation, which XLA applies automatically where legal.
"""
from __future__ import annotations

import contextlib
import re
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as _np

from .. import base, engine
from .._tape import is_recording, is_training, set_training
from ..base import MXNetError, getenv, register_env
from ..context import Context, cpu, current_context
from ..ndarray.ndarray import NDArray, from_jax
from ..ndarray.register import invoke
from ..ndarray import random as _random
from .parameter import Constant, DeferredInitializationError, Parameter

__all__ = ["Block", "HybridBlock", "SymbolBlock", "nn_block_summary",
           "remat_call", "remat_stack"]

register_env(
    "MXNET_REMAT", 0,
    "Rematerialize (activation-checkpoint) transformer layers: forward "
    "saves only each layer's INPUT and backward recomputes its "
    "interior, cutting live-activation memory ~L-fold for ~1 extra "
    "forward of compute (jax.checkpoint per layer — the TPU-native "
    "memory/FLOPs trade). Engaged by the model-zoo encoder stacks "
    "(BERT, GPT) when set.")

_REMAT_LAST: List[Optional[bool]] = [None]

def _remat_enabled() -> bool:
    cur = bool(getenv("MXNET_REMAT", 0))
    if _REMAT_LAST[0] is None:
        _REMAT_LAST[0] = cur
    elif _REMAT_LAST[0] != cur:
        # toggling after a program compiled must re-trace, not replay
        # the stale executable (the same invariant the flash knobs keep
        # by resolving env outside the cached closure)
        _REMAT_LAST[0] = cur
        invalidate_cached_graphs()
    return cur


def remat_call(block, *args, key=None):
    """Run ``block(*args)`` under ``jax.checkpoint``: backward recomputes
    the block's interior from its inputs instead of saving every
    intermediate. ``args`` are NDArrays (or None placeholders, which
    pass through). ``key``: an explicit PRNG key scoped around the call
    so in-block dropout draws IDENTICAL randomness in the recompute —
    ambient stateful key draws would advance again and silently corrupt
    gradients, so callers with dropout must pass one."""
    present = [a is not None for a in args]
    arrays = [a._data for a in args if a is not None]

    def body(*arrs):
        it = iter(arrs)
        nd_args = [from_jax(next(it)) if p else None for p in present]
        if key is not None:
            with _random.trace_key_scope(key):
                out = block(*nd_args)
        else:
            out = block(*nd_args)
        return out._data

    return from_jax(jax.checkpoint(body)(*arrays))


def remat_stack(layers, x, *extra, dropout: float = 0.0):
    """Apply ``layers`` sequentially, each under :func:`remat_call` when
    ``MXNET_REMAT`` is set (plain loop otherwise). ``extra`` args (an
    attention mask, say) pass to every layer. ``dropout``: the layers'
    dropout rate — when active in training, each layer gets a
    deterministic folded key so the backward recompute draws identical
    masks. The single shared implementation behind the model-zoo
    encoder stacks."""
    if not _remat_enabled():
        for layer in layers:
            x = layer(x, *extra)
        return x
    base = (_random.split_key()
            if dropout and is_training() else None)
    for i, layer in enumerate(layers):
        key = jax.random.fold_in(base, i) if base is not None else None
        x = remat_call(layer, x, *extra, key=key)
    return x


class _ParamDict(OrderedDict):
    """Dict of name->Parameter with batch operations (reference:
    ``ParameterDict`` semantics on ``collect_params()`` result)."""

    def initialize(self, init: Any = None, ctx: Any = None,
                   force_reinit: bool = False, verbose: bool = False) -> None:
        for p in self.values():
            p.initialize(init=init, ctx=ctx, force_reinit=force_reinit)

    def zero_grad(self) -> None:
        for p in self.values():
            p.zero_grad()

    def setattr(self, name: str, value: Any) -> None:
        for p in self.values():
            setattr(p, name, value)

    def reset_ctx(self, ctx: Context) -> None:
        for p in self.values():
            p.reset_ctx(ctx)

    def save(self, filename: str) -> None:
        from ..ndarray_io import save_params
        save_params(filename, {k: v.data() for k, v in self.items()
                               if v.is_initialized
                               and getattr(v, "persistent", True)})

    def load(self, filename: str, ctx: Any = None,
             allow_missing: bool = False,
             ignore_extra: bool = False) -> None:
        from ..ndarray_io import load_params
        loaded = load_params(filename, ctx=ctx)
        for k, p in self.items():
            if k in loaded:
                p.set_data(loaded[k])
            elif not allow_missing and getattr(p, "persistent", True):
                raise MXNetError(f"Parameter {k} missing in file {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(self)
            if extra:
                raise MXNetError(
                    f"File {filename} contains extra parameters: {sorted(extra)}")


class Block:
    """Base class for all neural network layers and models.

    Children and parameters register automatically on attribute assignment,
    mirroring the reference's ``Block.__setattr__`` registry.
    """

    def __init__(self, prefix: Optional[str] = None, params: Any = None) -> None:
        self._children: "OrderedDict[str, Block]" = OrderedDict()
        self._reg_params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._forward_hooks: List[Callable] = []
        self._forward_pre_hooks: List[Callable] = []
        self._prefix = prefix or ""

    # -- registry ----------------------------------------------------------
    def __setattr__(self, name: str, value: Any) -> None:
        if isinstance(value, Block):
            self.__dict__.setdefault("_children", OrderedDict())[name] = value
        elif isinstance(value, Parameter):
            self.__dict__.setdefault("_reg_params", OrderedDict())[name] = value
        super().__setattr__(name, value)

    def register_child(self, block: "Block", name: Optional[str] = None) -> None:
        self._children[name or str(len(self._children))] = block

    def register_parameter(self, name: str, param: Parameter) -> Parameter:
        self._reg_params[name] = param
        super().__setattr__(name, param)
        return param

    @property
    def params(self) -> _ParamDict:
        """This block's direct parameters (no children)."""
        return _ParamDict((n, p) for n, p in self._reg_params.items())

    def collect_params(self, select: Optional[str] = None) -> _ParamDict:
        """All parameters of self and descendants, keyed by attribute path
        (reference: ``Block.collect_params`` with regex select)."""
        out = _ParamDict()
        self._collect_params(out, prefix="")
        if select is not None:
            pat = re.compile(select)
            out = _ParamDict((k, v) for k, v in out.items() if pat.match(k))
        return out

    def _collect_params(self, out: _ParamDict, prefix: str) -> None:
        for name, p in self._reg_params.items():
            out[prefix + name] = p
        for cname, child in self._children.items():
            child._collect_params(out, prefix=f"{prefix}{cname}.")

    # -- lifecycle ---------------------------------------------------------
    def initialize(self, init: Any = None, ctx: Any = None,
                   verbose: bool = False, force_reinit: bool = False) -> None:
        self.collect_params().initialize(init=init, ctx=ctx,
                                         force_reinit=force_reinit)

    def cast(self, dtype: Any) -> None:
        for p in self.collect_params().values():
            p.cast(dtype)
        for child in self._children.values():
            pass  # params already covered by collect_params
        self._on_cast(dtype)

    def _on_cast(self, dtype: Any) -> None:
        for child in self._children.values():
            child._on_cast(dtype)

    def reset_ctx(self, ctx: Context) -> None:
        self.collect_params().reset_ctx(ctx)

    # -- persistence (format details in ndarray_io.py) ---------------------
    def save_parameters(self, filename: str, deduplicate: bool = False) -> None:
        """Save parameters by attribute path (reference:
        ``Block.save_parameters`` → .params file)."""
        self.collect_params().save(filename)

    def load_parameters(self, filename: str, ctx: Any = None,
                        allow_missing: bool = False,
                        ignore_extra: bool = False,
                        cast_dtype: bool = False) -> None:
        self.collect_params().load(filename, ctx=ctx,
                                   allow_missing=allow_missing,
                                   ignore_extra=ignore_extra)

    # -- hooks -------------------------------------------------------------
    def register_forward_hook(self, hook: Callable) -> None:
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook: Callable) -> None:
        self._forward_pre_hooks.append(hook)

    def apply(self, fn: Callable[["Block"], None]) -> "Block":
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def _epoch_sensitive(self) -> bool:
        """Does this block tree contain a layer whose host-side state can
        change the traced program (BatchNorm's virgin-stats flag)? Used
        to scope graph-epoch invalidation: blocks without such layers
        keep their compiled executables. Cached after the first walk."""
        cached = getattr(self, "_epoch_sensitive_cache", None)
        if cached is None:
            def walk(b) -> bool:
                if hasattr(b, "_stats_virgin"):
                    return True
                return any(walk(c) for c in b._children.values())
            cached = walk(self)
            self._epoch_sensitive_cache = cached
        return cached

    # -- execution ---------------------------------------------------------
    def __call__(self, *args: Any) -> Any:
        if args and isinstance(args[0], PreActivation) \
                and not getattr(type(self), "_consumes_preactivation", False):
            args = (args[0].materialize(),) + args[1:]
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args: Any) -> Any:
        raise NotImplementedError

    def summary(self, *inputs: Any) -> str:
        return nn_block_summary(self, *inputs)

    def __repr__(self) -> str:
        s = f"{type(self).__name__}("
        for name, child in self._children.items():
            child_repr = repr(child).replace("\n", "\n  ")
            s += f"\n  ({name}): {child_repr}"
        return s + ("\n)" if self._children else ")")


# bumped by layers whose HOST-side state changes the traced program
# (BatchNorm cold-start bootstrap): cached executables fold the epoch
# into their key, so the next call re-traces instead of replaying a
# stale graph
_GRAPH_EPOCH = [0]


def graph_epoch() -> int:
    # poll env-dependent trace knobs: a toggle between calls must bump
    # the epoch even though no trace (where the knob is read) has run
    _remat_enabled()
    base.poll_graph_knobs()
    return _GRAPH_EPOCH[0]


def invalidate_cached_graphs() -> None:
    _GRAPH_EPOCH[0] += 1


@contextlib.contextmanager
def _bind_params(params: Sequence[Parameter], arrays: Sequence[Any]):
    """Temporarily swap parameter buffers for traced arrays during jit
    tracing (how one forward implementation serves both runtimes).

    The concrete buffer is kept reachable as ``_concrete_shadow`` so
    host-side layer logic that must inspect actual VALUES mid-trace
    (BatchNorm virgin-stats resolution) can still see them."""
    saved = []
    for p, a in zip(params, arrays):
        saved.append(p._data._data)
        p._data._concrete_shadow = p._data._data
        p._data._data = a
    try:
        yield
    finally:
        for p, s in zip(params, saved):
            p._data._data = s
            try:
                del p._data._concrete_shadow
            except AttributeError:
                pass


def _collect_mutated(params: Sequence[Parameter],
                     bound_arrays: Sequence[Any]) -> List[Tuple[int, Any]]:
    """In-trace writes to parameter state (BatchNorm running stats) as
    ``(index, new_array)`` pairs — identity-compared against the arrays
    `_bind_params` bound, so it MUST run inside the ``_bind_params``
    scope, before the saved buffers are restored."""
    return [(i, p._data._data) for i, p in enumerate(params)
            if p._data._data is not bound_arrays[i]]


class HybridBlock(Block):
    """A Block that can be compiled to a single XLA executable.

    ``hybridize()`` turns subsequent calls into cached compiled programs
    keyed by input signature — the CachedOp analog. ``export()`` saves
    architecture + params for deployment.
    """

    def __init__(self, prefix: Optional[str] = None, params: Any = None) -> None:
        super().__init__(prefix, params)
        self._active = False
        self._cached_graph: Dict[tuple, Any] = {}
        self._flags: Dict[str, Any] = {}

    def hybridize(self, active: bool = True, static_alloc: bool = False,
                  static_shape: bool = False, **kwargs: Any) -> None:
        """Enable compiled execution (reference: ``HybridBlock.hybridize``;
        static_alloc ≙ XLA buffer donation, applied automatically).

        Note: hybridized calls rebind the buffers of input NDArrays (and
        parameters) in place to accelerator-resident copies the first time
        each is seen, so later consuming jit calls skip the host->device
        transfer; values are unchanged and later eager use stays valid.
        """
        self._active = active
        self._flags = dict(static_alloc=static_alloc,
                           static_shape=static_shape, **kwargs)
        self._cached_graph.clear()
        for child in self._children.values():
            if isinstance(child, HybridBlock):
                # children run inside the parent's trace; they stay eager
                # when called directly
                child._cached_graph.clear()

    def _ensure_shapes(self, *args: Any) -> None:
        """Run deferred shape inference by executing forward eagerly once
        if any parameter is still deferred."""
        deferred = [p for p in self.collect_params().values()
                    if not p.is_initialized and p._deferred_init is not None]
        if not deferred:
            return
        # A single eager forward resolves all deferred shapes via each
        # layer's infer-shape hooks.
        was = self._active
        self._active = False
        try:
            self.forward(*args)
        finally:
            self._active = was

    def optimize_for(self, x: Any, *args: Any,
                     backend: Optional[str] = None,
                     **kwargs: Any) -> "HybridBlock":
        """Apply a subgraph accelerator backend and warm-compile
        (reference ``optimize_for(backend)`` / ``MXNET_SUBGRAPH_BACKEND``).

        Built-in backends: 'xla' (default — hybridize + jit warm),
        'int8' (post-training quantization calibrated on ``x``), 'bf16'
        (AMP cast policy); more via ``mxnet_tpu.subgraph.register_backend``.
        Returns the optimized block (usually ``self``, mutated in place).
        """
        from ..subgraph import get_backend
        return get_backend(backend)(self, (x,) + args, **kwargs)

    def _make_traced(self, params: List[Parameter], train: bool,
                     cell: Dict[str, Any]) -> Callable:
        """Build the jittable closure shared by _call_cached and export:
        (rng_key, param_arrays, *inputs) -> flat output leaves, recording
        the output treedef into ``cell``."""
        block = self

        def traced(rng_key, param_arrays, *input_arrays):
            prev = set_training(train)
            try:
                with _bind_params(params, param_arrays), \
                        _random.trace_key_scope(rng_key):
                    inputs = [from_jax(a) for a in input_arrays]
                    out = block.forward(*inputs)
                    # BatchNorm running stats etc.: the reference updates
                    # them as a side effect of the cached graph
                    # (src/operator/nn/batch_norm); here they ride out as
                    # extra outputs and are written back by the caller
                    mutated = _collect_mutated(params, param_arrays)
            finally:
                set_training(prev)
            raw = jax.tree_util.tree_map(
                lambda o: o._data if isinstance(o, NDArray) else o, out,
                is_leaf=lambda o: isinstance(o, NDArray))
            leaves, treedef = jax.tree_util.tree_flatten(raw)
            cell["treedef"] = treedef
            cell["mutated_idx"] = [i for i, _ in mutated]
            return tuple(leaves) + tuple(a for _, a in mutated)

        return traced

    def _call_cached(self, *args: Any) -> Any:
        nd_args = [a if isinstance(a, NDArray) else NDArray(a) for a in args]
        self._ensure_shapes(*nd_args)
        from .parameter import dedupe_shared
        _, params = dedupe_shared(
            (k, p) for k, p in self.collect_params().items()
            if p.is_initialized)

        train = is_training()
        self._last_sig = [(tuple(a.shape), a.dtype) for a in nd_args]
        from ..ndarray.register import _amp_state
        amp_key = None
        if _amp_state["active"]:
            from ..amp import _STATE as _amp
            amp_key = str(_amp["target_dtype"])
        # a bumped epoch invalidates by CLEARING this block's cache (not
        # by keying on the epoch, which would strand the old compiled
        # executables in the dict for the block's lifetime) — and only
        # for blocks that CONTAIN an epoch-sensitive layer (BatchNorm):
        # other models' traced programs cannot have changed, so they
        # keep their executables
        if getattr(self, "_cache_epoch", None) != _GRAPH_EPOCH[0]:
            if self._epoch_sensitive():
                self._cached_graph.clear()
            self._cache_epoch = _GRAPH_EPOCH[0]
        # the remat flag joins the key: its value changes the traced
        # program for every remat-capable model, independent of the
        # BatchNorm-only epoch filter above
        key_sig = (tuple((tuple(a.shape), str(a.dtype)) for a in nd_args),
                   train, amp_key, _remat_enabled())
        entry = self._cached_graph.get(key_sig)
        if entry is None:
            cell: Dict[str, Any] = {}  # filled with treedef at trace time
            entry = (jax.jit(self._make_traced(params, train, cell)), cell)
            self._cached_graph[key_sig] = entry

        cached, cell = entry
        rng = _random.split_key()
        n_params = len(params)

        def impl(*arrays):
            return cached(rng, list(arrays[:n_params]), *arrays[n_params:])

        # launder eager-produced param AND input buffers: on the axon
        # remote backend they are lazy handles that re-pay their transfer
        # on every consuming jit call (engine.launder; no-op on CPU)
        from .. import engine as _engine
        clean = _engine.launder([p.data()._data for p in params] +
                                [a._data for a in nd_args])
        for p, a in zip(params, clean):
            p._data._data = a
        for nd, a in zip(nd_args, clean[len(params):]):
            nd._data = a
        inputs = [p.data() for p in params] + nd_args
        flat_out = invoke(f"cached_{type(self).__name__}", impl, inputs)
        leaves = list(flat_out) if isinstance(flat_out, tuple) else [flat_out]
        m_idx = cell.get("mutated_idx") or []
        if m_idx:
            n_out = cell["treedef"].num_leaves
            for i, a in zip(m_idx, leaves[n_out:]):
                raw = a._data if isinstance(a, NDArray) else a
                params[i]._data._data = raw
                _engine.mark_clean(raw)
            leaves = leaves[:n_out]
        return jax.tree_util.tree_unflatten(cell["treedef"], leaves)

    def __call__(self, *args: Any) -> Any:
        if self._active and not _tracing_now(args):
            if args and isinstance(args[0], PreActivation):
                # the hybrid cache boundary speaks NDArray: a deferred
                # epilogue materializes rather than crossing the jit
                args = (args[0].materialize(),) + args[1:]
            for hook in self._forward_pre_hooks:
                hook(self, args)
            out = self._call_cached(*args)
            for hook in self._forward_hooks:
                hook(self, args, out)
            return out
        return super().__call__(*args)

    # -- export/deploy -----------------------------------------------------
    def export(self, path: str, epoch: int = 0,
               input_signature: Optional[Sequence[tuple]] = None,
               dynamic_batch: bool = False) -> Tuple[str, str]:
        """Serialize a runnable program + params for deployment (reference:
        ``HybridBlock.export`` → ``prefix-symbol.json`` + ``.params``).

        The "symbol" payload is a jax.export StableHLO artifact traced in
        inference mode (the TPU-era graph format; the reference stored an
        NNVM json graph). ``input_signature`` is a list of (shape, dtype)
        per input; if omitted, the signature of the last hybridized call
        is used (so call the block once before exporting, as in the
        reference).

        ``dynamic_batch=True`` traces the leading dim of every input as a
        shape-polymorphic symbol: ONE serialized program answers every
        batch size — what the serving layer's batch buckets run against
        (a static artifact serves exactly its traced batch).  The batch
        entry of ``input_signature`` is then only a placeholder.
        """
        import base64
        import json

        if input_signature is None:
            input_signature = getattr(self, "_last_sig", None)
        if input_signature is None:
            raise MXNetError(
                "export() needs the input signature: run the block once "
                "(after hybridize()) or pass input_signature=[(shape, "
                "dtype), ...]")

        # tied/shared parameters (same object under several names) save
        # and trace ONCE, under their first name — a duplicate would
        # double-bind the buffer in the trace and read as a phantom
        # in-trace mutation
        from .parameter import dedupe_shared
        _pnames, _plist = dedupe_shared(
            (k, p) for k, p in self.collect_params().items()
            if p.is_initialized)
        params = dict(zip(_pnames, _plist))

        from jax import export as jax_export
        param_list = list(params.values())
        cell: Dict[str, Any] = {}
        key_spec = jax.ShapeDtypeStruct((2,), jnp.uint32)
        param_specs = [jax.ShapeDtypeStruct(p.shape, p.dtype)
                       for p in param_list]
        if dynamic_batch:
            # one polymorphic symbol shared by every input's leading dim
            # (inputs batch together); inner dims stay concrete
            (bdim,) = jax_export.symbolic_shape("_b")
            in_specs = [jax.ShapeDtypeStruct((bdim,) + tuple(s)[1:], d)
                        for s, d in input_signature]
        else:
            in_specs = [jax.ShapeDtypeStruct(tuple(s), d)
                        for s, d in input_signature]
        jitted = jax.jit(self._make_traced(param_list, False, cell))
        try:
            exp = jax_export.export(jitted, platforms=("cpu", "tpu"))(
                key_spec, param_specs, *in_specs)
        except Exception as e:
            # some backends (e.g. the axon tunnel) reject multi-platform
            # lowering; fall back to the current platform only. Anything
            # that is not a platform complaint is a real trace error.
            if "platform" not in str(e).lower():
                raise
            exp = jax_export.export(jitted)(key_spec, param_specs, *in_specs)
        if cell.get("mutated_idx"):
            raise MXNetError(
                "export traced a forward that mutates parameter state "
                "(training-mode BatchNorm?); export runs in inference "
                "mode — check autograd/use_global_stats configuration")
        program = bytes(exp.serialize())
        from .._durable import sha256_bytes, sha256_file
        meta = {
            "framework": "mxnet_tpu",
            "format_version": 1,
            "block": type(self).__name__,
            "dynamic_batch": bool(dynamic_batch),
            "inputs": [{"shape": list(s), "dtype": str(_np.dtype(d))}
                       for s, d in input_signature],
            "params": {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                       for k, v in params.items()},
            "param_order": list(params.keys()),
            "out_treedef": _treedef_to_obj(cell["treedef"]),
            "stablehlo": base64.b64encode(program).decode("ascii"),
            # the serving load path verifies these BEFORE deserializing:
            # a truncated/garbled artifact is named in a structured
            # error instead of an opaque deserializer crash
            "stablehlo_sha256": sha256_bytes(program),
        }
        # native-runtime deploy graph (c_predict_api analog): a layer-op
        # list MXPredCreate can execute with no Python, emitted whenever
        # the block maps onto the native op set
        from .deploy import deploy_graph
        meta["deploy_graph"] = deploy_graph(self)
        # write artifacts only after trace + serialization succeeded — a
        # failed export must not leave a stale .params behind. The FILE
        # carries EVERY name, aliases included (same array under each):
        # load_parameters and the native deploy_graph resolve parameters
        # by name and must find all of them; only the trace deduped.
        param_file = f"{path}-{epoch:04d}.params"
        from ..ndarray_io import save_params
        save_params(param_file,
                    {k: p.data() for k, p in self.collect_params().items()
                     if p.is_initialized})
        meta["params_sha256"] = sha256_file(param_file)
        sym_file = f"{path}-symbol.json"
        with open(sym_file, "w") as f:
            json.dump(meta, f, indent=2)
        return sym_file, param_file


def _treedef_to_obj(treedef: Any) -> Any:
    """Declarative (JSON-able) encoding of an output pytree structure.

    Supports the standard containers a forward may return (leaf, tuple,
    list, dict) — no pickle, so model files stay safe to load from
    untrusted sources.
    """
    n = treedef.num_leaves
    skeleton = jax.tree_util.tree_unflatten(treedef, list(range(n)))

    def enc(node: Any) -> Any:
        if isinstance(node, int):
            return {"t": "leaf"}
        if isinstance(node, tuple):
            return {"t": "tuple", "c": [enc(x) for x in node]}
        if isinstance(node, list):
            return {"t": "list", "c": [enc(x) for x in node]}
        if isinstance(node, dict):
            return {"t": "dict", "k": list(node.keys()),
                    "c": [enc(node[k]) for k in node.keys()]}
        if node is None:
            return {"t": "none"}
        raise MXNetError(
            f"export: forward returned a {type(node).__name__}; only "
            f"tuples/lists/dicts/arrays are exportable")

    return enc(skeleton)


def _obj_to_treedef(obj: Any) -> Any:
    def dec(node: Any) -> Any:
        t = node["t"]
        if t == "leaf":
            return 0  # placeholder leaf
        if t == "tuple":
            return tuple(dec(x) for x in node["c"])
        if t == "list":
            return [dec(x) for x in node["c"]]
        if t == "dict":
            return {k: dec(x) for k, x in zip(node["k"], node["c"])}
        if t == "none":
            return None
        raise MXNetError(f"bad treedef node type {t!r} in model file")

    return jax.tree_util.tree_structure(dec(obj))


class PreActivation:
    """A residual-block output BEFORE its epilogue ReLU, deferred so a
    consuming 1x1 conv can take the ReLU as a Pallas kernel prologue
    (ops/pallas/conv_fused.py) — the activated tensor then never
    round-trips HBM.  Blocks that understand the deferral set
    ``_consumes_preactivation = True``; every other ``Block.__call__``
    (and the hybrid cache boundary) materializes transparently, so the
    box can never leak into user code or a jit signature."""

    __slots__ = ("z",)

    def __init__(self, z) -> None:
        self.z = z

    def materialize(self):
        from .. import npx
        return npx.relu(self.z)


def _tracing_now(args) -> bool:
    for a in args:
        if isinstance(a, PreActivation):
            a = a.z
        data = a._data if isinstance(a, NDArray) else a
        if isinstance(data, jax.core.Tracer):
            return True
    return False


def _default_init_for(name: str):
    """Name-dispatched default initializer for symbol-created parameters
    (reference: the variable-name heuristics in ``initializer.py`` —
    gamma/moving_var -> ones, beta/bias/moving_mean -> zeros)."""
    from .. import initializer as _init_mod
    if name.endswith(("_gamma", "_moving_var", "_running_var")):
        return _init_mod.One()
    if name.endswith(("_beta", "_bias", "_moving_mean", "_running_mean")):
        return _init_mod.Zero()
    return None


class SymbolBlock(HybridBlock):
    """Run a symbolic graph as a gluon block (reference:
    ``gluon.SymbolBlock(outputs, inputs)`` and ``SymbolBlock.imports``
    over ``-symbol.json`` + ``.params``).

    Accepts either a ``mx.sym.Symbol`` with its input symbols (classic
    constructor), or a callable + params dict (used internally by
    ``imports`` for jax.export artifacts)."""

    def __init__(self, outputs: Any, inputs: Any = None,
                 params: Optional[Dict[str, Parameter]] = None) -> None:
        super().__init__()
        if hasattr(outputs, "_heads"):          # mx.sym.Symbol
            self._init_from_symbol(outputs, inputs, params)
            return
        self._fn = outputs
        self._symbol = None
        for k, v in (inputs if isinstance(inputs, dict)
                     else (params or {})).items():
            self._reg_params[k] = v

    def _init_from_symbol(self, outputs: Any, inputs: Any,
                          params: Optional[Dict[str, Parameter]]) -> None:
        from ..symbol.symbol import _eval_graph
        if inputs is None:
            raise MXNetError("SymbolBlock(symbol) requires the input "
                             "symbols, e.g. inputs=[mx.sym.var('data')]")
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        in_names = [i if isinstance(i, str) else i.name for i in inputs]
        self._symbol = outputs
        self._sym_input_names = in_names
        arg_names = [n for n in outputs.list_arguments()
                     if n not in in_names]
        aux_names = outputs.list_auxiliary_states()
        for n in arg_names:
            p = (params or {}).get(n) or Parameter(
                n, shape=None, allow_deferred_init=True,
                init=_default_init_for(n))
            self._reg_params[n] = p
        for n in aux_names:
            p = (params or {}).get(n) or Parameter(
                n, grad_req="null", shape=None, allow_deferred_init=True,
                init=_default_init_for(n))
            self._reg_params[n] = p

        def fn(*args: Any) -> Any:
            self._sym_finish_deferred(args)
            feed = {}
            for name, a in zip(in_names, args):
                feed[name] = a if isinstance(a, NDArray) else NDArray(a)
            for name, p in self._reg_params.items():
                feed[name] = p.data()

            def aux_hook(name: str, value: NDArray) -> None:
                self._reg_params[name].set_data(value.detach())

            from .._tape import is_training
            outs = _eval_graph(self._symbol, feed,
                               training=is_training(), aux_hook=aux_hook)
            return outs[0] if len(outs) == 1 else tuple(outs)

        self._fn = fn

    def _sym_finish_deferred(self, args: Any) -> None:
        pending = {n: p for n, p in self._reg_params.items()
                   if p._data is None and p._deferred_init is not None}
        if not pending:
            return
        from ..symbol.symbol import _infer_structs
        known = {n: tuple(a.shape)
                 for n, a in zip(self._sym_input_names, args)}
        var_structs, _ = _infer_structs(self._symbol, known, partial=True)
        for n, p in pending.items():
            st = var_structs.get(n)
            if st is None:
                raise MXNetError(
                    f"SymbolBlock: could not infer shape of parameter "
                    f"{n!r} from input shapes {known}")
            if p.dtype is None or _np.dtype(p.dtype) != _np.dtype(st.dtype):
                p.dtype = _np.dtype(st.dtype)
            p._finish_deferred_init(tuple(st.shape))

    @staticmethod
    def imports(symbol_file: str, input_names: Any = None,
                param_file: Optional[str] = None,
                ctx: Any = None) -> "SymbolBlock":
        """Load an exported model: deserializes the StableHLO artifact and
        rebinds the saved parameters (reference: ``SymbolBlock.imports``)."""
        import base64
        import json

        from jax import export as jax_export

        with open(symbol_file) as f:
            meta = json.load(f)
        if meta.get("framework") != "mxnet_tpu" or "stablehlo" not in meta:
            raise MXNetError(
                f"{symbol_file} is not an mxnet_tpu export (re-export with "
                "HybridBlock.export)")

        exp = jax_export.deserialize(
            bytearray(base64.b64decode(meta["stablehlo"])))
        treedef = _obj_to_treedef(meta["out_treedef"])
        order = meta["param_order"]

        params: Dict[str, Parameter] = {}
        if param_file is not None:
            from ..ndarray_io import load_params
            loaded = load_params(param_file, ctx=ctx)
            missing = [k for k in order if k not in loaded]
            if missing:
                raise MXNetError(
                    f"{param_file} is missing exported params: {missing}")
            for k in order:
                p = Parameter(k, shape=loaded[k].shape,
                              dtype=loaded[k].dtype, grad_req="null")
                p.set_data(loaded[k])
                params[k] = p
        elif order:
            # no params file: leave parameters uninitialized so first use
            # raises instead of silently running random weights
            raise MXNetError(
                "SymbolBlock.imports: this export has parameters — pass "
                "param_file=<prefix-NNNN.params> (loading without weights "
                "would silently return garbage)")

        def fn(*args: Any) -> Any:
            arrays = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
                      for a in args]
            rng = _random.split_key()
            if rng.shape != (2,):  # typed key -> raw uint32 pair
                rng = jax.random.key_data(rng)
            pa = [params[k].data()._data for k in order]
            leaves = exp.call(rng.astype(jnp.uint32), pa, *arrays)
            out = jax.tree_util.tree_unflatten(treedef, list(leaves))
            return jax.tree_util.tree_map(from_jax, out)

        return SymbolBlock(fn, params)

    def forward(self, *args: Any) -> Any:
        return self._fn(*args)


def nn_block_summary(block: Block, *inputs: Any) -> str:
    """Print a per-layer summary table (reference: ``Block.summary``)."""
    lines = [f"{'Layer':<40}{'Output Shape':<24}{'Param #':<12}"]
    total = 0
    for name, p in block.collect_params().items():
        n = 1
        for s in (p.shape or ()):
            n *= s
        total += n
        lines.append(f"{name:<40}{str(p.shape):<24}{n:<12}")
    lines.append(f"Total params: {total}")
    out = "\n".join(lines)
    print(out)
    return out
