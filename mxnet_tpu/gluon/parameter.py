"""Parameter — a block's learnable tensor with deferred initialization.

Reference parity (leezu/mxnet): ``python/mxnet/gluon/parameter.py``
(``Parameter``, ``DeferredInitializationError``, grad_req handling,
``_finish_deferred_init``) — SURVEY.md section 2.5.

Design (tpu-first): the reference keeps per-GPU copies of every parameter
(``_check_and_get`` per ctx); here a parameter owns ONE array which may be
*sharded* over a device mesh (jax.sharding) — replication/partition is a
sharding annotation, not a copy list. ``data(ctx)`` therefore returns the
single array (transferring if a different ctx is asked for).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as _np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray.ndarray import NDArray

__all__ = ["Parameter", "Constant", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Raised when a parameter with unknown shape is used before a forward
    pass has inferred it (reference: same name/purpose)."""


def _shape_is_known(shape: Optional[Tuple[int, ...]]) -> bool:
    if shape is None:
        return False
    return all(s > 0 for s in shape)


class Parameter:
    """A learnable parameter of a Block.

    Parameters
    ----------
    name : str
        Registration name (attribute path provides uniqueness at Block level).
    shape : tuple of int, optional
        Dims of value ``0``/``-1`` mean unknown — resolved at first forward
        (deferred initialization, the reference's signature feature).
    """

    def __init__(self, name: str = "weight", grad_req: str = "write",
                 shape: Optional[Union[int, Tuple[int, ...]]] = None,
                 dtype: Any = "float32", lr_mult: float = 1.0,
                 wd_mult: float = 1.0, init: Any = None,
                 allow_deferred_init: bool = True,
                 differentiable: bool = True, stype: str = "default",
                 grad_stype: str = "default",
                 persistent: bool = True) -> None:
        # persistent=False: runtime-only state excluded from .params
        # files (e.g. BatchNorm's stat-shift buffer) — torch's
        # register_buffer(persistent=False) notion; absent on load
        self.persistent = persistent
        self._name = name
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        if not differentiable:
            grad_req = "null"
        self._grad_req = grad_req
        # Storage types (reference: Parameter(..., stype, grad_stype)).
        # stype='row_sparse' weights (full sparse-weight training) are not
        # supported — fail loudly rather than silently training densely.
        # grad_stype is advisory: sparse gradients materialize when the
        # producing op emits them (npx.embedding sparse_grad), matching
        # how Embedding wires it; a dense-only graph yields dense grads.
        if stype != "default":
            raise ValueError(
                f"Parameter stype={stype!r} is not supported (only "
                "'default'; sparse *gradients* come via grad_stype)")
        if grad_stype not in ("default", "row_sparse"):
            raise ValueError(f"invalid grad_stype {grad_stype!r}")
        self.grad_stype = grad_stype
        self._data: Optional[NDArray] = None
        self._ctx: Optional[Context] = None
        self._deferred_init: Optional[tuple] = None  # (init, ctx, default_init)
        # attribute path set by Block registration, e.g. "dense0.weight"
        self._uuid = name
        self._grad_ready_cb: Optional[Callable] = None

    # ------------------------------------------------------------------
    @property
    def name(self) -> str:
        return self._name

    @property
    def shape(self) -> Optional[Tuple[int, ...]]:
        return self._shape

    @shape.setter
    def shape(self, new_shape) -> None:
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        # merge partially-known shapes
        if len(self._shape) != len(new_shape):
            raise MXNetError(
                f"{self.name}: cannot change parameter ndim "
                f"{self._shape} -> {tuple(new_shape)}")
        merged = []
        for old, new in zip(self._shape, new_shape):
            if old > 0 and new > 0 and old != new:
                raise MXNetError(
                    f"{self.name}: inferred shape {tuple(new_shape)} "
                    f"incompatible with declared {self._shape}")
            merged.append(old if old > 0 else new)
        self._shape = tuple(merged)

    @property
    def grad_req(self) -> str:
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req: str) -> None:
        if req not in ("write", "add", "null"):
            raise ValueError(f"invalid grad_req {req!r}")
        self._grad_req = req
        if self._data is not None:
            self._data.attach_grad(req)

    # ------------------------------------------------------------------
    def initialize(self, init: Any = None, ctx: Any = None,
                   default_init: Any = None, force_reinit: bool = False
                   ) -> None:
        """Materialize the parameter (or defer until shapes are known)."""
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0] if ctx else current_context()
        from .. import initializer as _init_mod
        default_init = default_init or _init_mod.Uniform()
        if not _shape_is_known(self._shape):
            if not self.allow_deferred_init:
                raise MXNetError(
                    f"Cannot initialize Parameter {self.name!r}: shape "
                    f"{self._shape} not fully known and deferred init "
                    f"disabled")
            self._deferred_init = (init, ctx, default_init)
            return
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init) -> None:
        from .. import initializer as _init_mod
        initializer = init or self.init or default_init
        if isinstance(initializer, str):
            initializer = _init_mod.get(initializer)
        data = initializer(self._shape, self.dtype, ctx)
        self._data = data if isinstance(data, NDArray) \
            else NDArray(data, ctx=ctx, dtype=self.dtype)
        self._ctx = ctx
        self._deferred_init = None
        if self._grad_req != "null":
            self._data.attach_grad(self._grad_req)

    def _finish_deferred_init(self, inferred_shape: Tuple[int, ...]) -> None:
        """Complete deferred init once a forward pass knows the shape."""
        self.shape = inferred_shape
        if self._deferred_init is None:
            if self._data is None:
                raise DeferredInitializationError(
                    f"Parameter {self.name!r} has not been initialized; "
                    f"call .initialize() first")
            return
        init, ctx, default_init = self._deferred_init
        self._finish_init(init, ctx, default_init)

    # ------------------------------------------------------------------
    def data(self, ctx: Optional[Context] = None) -> NDArray:
        """The parameter value (raises if deferred/uninitialized)."""
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"Parameter {self.name!r} awaits shape inference; run a "
                    f"forward pass before accessing .data()")
            raise MXNetError(
                f"Parameter {self.name!r} has not been initialized. Call "
                f".initialize() on the block or parameter first")
        if ctx is not None and ctx != self._data.context:
            return self._data.as_in_context(ctx)
        return self._data

    def list_data(self) -> List[NDArray]:
        return [self.data()]

    def grad(self, ctx: Optional[Context] = None) -> NDArray:
        d = self.data(ctx)
        if d.grad is None:
            raise MXNetError(
                f"Parameter {self.name!r} has grad_req='null'; no gradient "
                f"buffer exists")
        return d.grad

    def list_grad(self) -> List[NDArray]:
        return [self.grad()]

    def list_ctx(self) -> List[Context]:
        if self._data is None and self._deferred_init is not None:
            return [self._deferred_init[1]]
        return [self.data().context]

    def set_data(self, data: Any) -> None:
        """Replace the value, preserving the grad buffer/requirement."""
        nd = data if isinstance(data, NDArray) else NDArray(data, ctx=self._ctx)
        if self._shape is not None and _shape_is_known(self._shape) \
                and tuple(nd.shape) != self._shape:
            raise MXNetError(
                f"Parameter {self.name!r}: set_data shape {nd.shape} != "
                f"declared {self._shape}")
        self.shape = nd.shape
        if self._data is None:
            self._data = nd
            self._deferred_init = None
            if self._grad_req != "null":
                self._data.attach_grad(self._grad_req)
        else:
            self._data._data = nd._data  # keep NDArray identity (grad stays)

    def set_grad_ready_cb(self, cb: Optional[Callable]) -> None:
        """Install (or clear, with ``None``) this parameter's grad-ready
        hook: ``backward()`` calls ``cb(data_ndarray)`` the moment this
        parameter's gradient has received its final contribution —
        while later pullbacks of the same backward are still running.
        The gluon ``Trainer`` uses it to submit gradients to the
        overlapped kvstore scheduler DURING backward (per-layer
        streaming); re-installed every step, so parameter re-binds
        (``reset_ctx``/``cast``) self-heal at the next arm."""
        self._grad_ready_cb = cb
        if self._data is not None:
            self._data._grad_ready_cb = cb

    def zero_grad(self) -> None:
        if self._data is not None and self._data.grad is not None:
            import jax.numpy as jnp
            g = self._data.grad
            if getattr(g, "stype", "default") == "row_sparse":
                # drop the stored rows; a dense-cache write would leave
                # the sparse components alive for the next 'add' merge
                g._sp_values = g._sp_values[:0]
                g._sp_indices = g._sp_indices[:0]
                g._dense_cache = None
                return
            # zeros_like, not g*0: multiplying would keep NaN/Inf poison
            g._data = jnp.zeros_like(g._data)

    def reset_ctx(self, ctx: Context) -> None:
        if self._data is not None:
            self._data = self._data.as_in_context(ctx)
            self._ctx = ctx
            if self._grad_req != "null":
                self._data.attach_grad(self._grad_req)

    def cast(self, dtype: Any) -> None:
        self.dtype = dtype
        if self._data is not None:
            had_grad = self._data._grad_req != "null"
            self._data = self._data.astype(dtype)
            if had_grad:
                self._data.attach_grad(self._grad_req)

    @property
    def is_initialized(self) -> bool:
        return self._data is not None

    def __repr__(self) -> str:
        return (f"Parameter {self.name} (shape={self._shape}, "
                f"dtype={self.dtype})")


class Constant(Parameter):
    """A constant parameter excluded from gradients (reference: gluon
    ``Constant``)."""

    def __init__(self, value: Any, name: str = "const") -> None:
        if not isinstance(value, NDArray):
            value = NDArray(_np.asarray(value))
        super().__init__(name=name, grad_req="null",
                         shape=value.shape, dtype=value.dtype,
                         differentiable=False)
        self._value = value

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False) -> None:
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0] if ctx else None
        self._data = self._value.as_in_context(ctx) if ctx else self._value
        self._ctx = ctx


def dedupe_shared(named_params):
    """Keep each Parameter once, under its first name (tied/shared
    parameters register under several names; a trainer must optimize
    them exactly once or gradients double-count and the fused update
    donates one buffer twice). Returns (names, params) index-aligned."""
    names, params, seen = [], [], set()
    for name, p in named_params:
        if id(p) in seen:
            continue
        seen.add(id(p))
        names.append(name)
        params.append(p)
    return names, params
