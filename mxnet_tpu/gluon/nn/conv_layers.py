"""Convolution and pooling layers.

Reference parity (leezu/mxnet): ``python/mxnet/gluon/nn/conv_layers.py`` —
Conv1D/2D/3D (+Transpose), MaxPool/AvgPool 1-3D, GlobalPool, ReflectionPad.
Layout: the reference defaults to NCHW (cuDNN); we accept both and default
to NCHW for API parity — XLA's TPU layout assignment makes this near-free,
and models that want peak TPU throughput can pass layout='NHWC'.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple, Union

from ... import npx
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _tuplify(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


class _Conv(HybridBlock):
    def __init__(self, channels: int, kernel_size, strides, padding, dilation,
                 groups: int, layout: str, in_channels: int = 0,
                 activation: Optional[str] = None, use_bias: bool = True,
                 weight_initializer: Any = None,
                 bias_initializer: Any = "zeros", ndim: int = 2,
                 transpose: bool = False, output_padding=0,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = _tuplify(kernel_size, ndim)
        self._strides = _tuplify(strides, ndim)
        self._padding = _tuplify(padding, ndim)
        self._dilation = _tuplify(dilation, ndim)
        self._groups = groups
        self._layout = layout
        self._activation = activation
        self._ndim = ndim
        self._transpose = transpose
        self._output_padding = _tuplify(output_padding, ndim)
        # weight layout OIHW-style for NC* layouts (reference convention)
        wshape = self._weight_shape(in_channels)
        self.weight = Parameter("weight", shape=wshape,
                                init=weight_initializer)
        self.bias = Parameter("bias", shape=(channels,),
                              init=bias_initializer) if use_bias else None

    def _weight_shape(self, in_channels: int) -> tuple:
        if self._layout.startswith("NC"):
            if self._transpose:
                return (in_channels, self._channels // self._groups) + self._kernel
            return (self._channels, in_channels // self._groups
                    if in_channels else 0) + self._kernel
        # channels-last layouts: HWIO
        if self._transpose:
            return self._kernel + (self._channels // self._groups, in_channels)
        return self._kernel + (in_channels // self._groups
                               if in_channels else 0, self._channels)

    def _infer(self, x: NDArray) -> None:
        if self.weight.is_initialized:
            return
        c_axis = self._layout.index("C")
        in_c = x.shape[c_axis]
        self.weight._finish_deferred_init(self._weight_shape(in_c))
        if self.bias is not None:
            self.bias._finish_deferred_init((self._channels,))

    def forward(self, x: NDArray) -> NDArray:
        self._infer(x)
        if self._transpose:
            out = npx.deconvolution(
                x, self.weight.data(),
                None if self.bias is None else self.bias.data(),
                kernel=self._kernel, stride=self._strides,
                dilate=self._dilation, pad=self._padding,
                adj=self._output_padding,
                num_filter=self._channels, num_group=self._groups,
                no_bias=self.bias is None, layout=self._layout)
        else:
            out = npx.convolution(
                x, self.weight.data(),
                None if self.bias is None else self.bias.data(),
                kernel=self._kernel, stride=self._strides,
                dilate=self._dilation, pad=self._padding,
                num_filter=self._channels, num_group=self._groups,
                no_bias=self.bias is None, layout=self._layout)
        if self._activation:
            out = npx.activation(out, self._activation)
        return out

    def __repr__(self) -> str:
        return (f"{type(self).__name__}({self._channels}, "
                f"kernel_size={self._kernel}, stride={self._strides})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, ndim=1, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, ndim=2, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, ndim=3, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, ndim=1, transpose=True,
                         output_padding=output_padding, **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, ndim=2, transpose=True,
                         output_padding=output_padding, **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, ndim=3, transpose=True,
                         output_padding=output_padding, **kwargs)


class _Pool(HybridBlock):
    def __init__(self, pool_size, strides, padding, ndim: int,
                 pool_type: str, layout: str, global_pool: bool = False,
                 count_include_pad: bool = True, ceil_mode: bool = False,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._kernel = _tuplify(pool_size, ndim)
        self._strides = _tuplify(strides if strides is not None
                                 else pool_size, ndim)
        self._padding = _tuplify(padding, ndim)
        self._pool_type = pool_type
        self._layout = layout
        self._global = global_pool
        self._count_include_pad = count_include_pad

    def forward(self, x: NDArray) -> NDArray:
        return npx.pooling(x, kernel=self._kernel, pool_type=self._pool_type,
                           stride=self._strides, pad=self._padding,
                           global_pool=self._global,
                           count_include_pad=self._count_include_pad,
                           layout=self._layout)

    def __repr__(self) -> str:
        return (f"{type(self).__name__}(size={self._kernel}, "
                f"stride={self._strides}, padding={self._padding})")


class MaxPool1D(_Pool):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, 1, "max", layout,
                         ceil_mode=ceil_mode, **kwargs)


class MaxPool2D(_Pool):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, 2, "max", layout,
                         ceil_mode=ceil_mode, **kwargs)


class MaxPool3D(_Pool):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, 3, "max", layout,
                         ceil_mode=ceil_mode, **kwargs)


class AvgPool1D(_Pool):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(pool_size, strides, padding, 1, "avg", layout,
                         ceil_mode=ceil_mode,
                         count_include_pad=count_include_pad, **kwargs)


class AvgPool2D(_Pool):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(pool_size, strides, padding, 2, "avg", layout,
                         ceil_mode=ceil_mode,
                         count_include_pad=count_include_pad, **kwargs)


class AvgPool3D(_Pool):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(pool_size, strides, padding, 3, "avg", layout,
                         ceil_mode=ceil_mode,
                         count_include_pad=count_include_pad, **kwargs)


class GlobalMaxPool1D(_Pool):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__(1, 1, 0, 1, "max", layout, global_pool=True, **kwargs)


class GlobalMaxPool2D(_Pool):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__(1, 1, 0, 2, "max", layout, global_pool=True, **kwargs)


class GlobalMaxPool3D(_Pool):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__(1, 1, 0, 3, "max", layout, global_pool=True, **kwargs)


class GlobalAvgPool1D(_Pool):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__(1, 1, 0, 1, "avg", layout, global_pool=True, **kwargs)


class GlobalAvgPool2D(_Pool):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__(1, 1, 0, 2, "avg", layout, global_pool=True, **kwargs)


class GlobalAvgPool3D(_Pool):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__(1, 1, 0, 3, "avg", layout, global_pool=True, **kwargs)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding: int = 0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._padding = padding

    def forward(self, x: NDArray) -> NDArray:
        from ...ndarray import ops
        p = self._padding
        return ops.pad(x, ((0, 0), (0, 0), (p, p), (p, p)), mode="reflect")
