"""Activation blocks (reference: ``python/mxnet/gluon/nn/activations.py``)."""
from __future__ import annotations

from typing import Any

from ... import npx
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "GELU",
           "Swish", "SiLU", "Mish", "HardSigmoid", "HardSwish"]


class Activation(HybridBlock):
    """Named activation (``nn.Activation('relu'|'sigmoid'|'tanh'|...)``)."""

    def __init__(self, activation: str, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._act = activation

    def forward(self, x: NDArray) -> NDArray:
        return npx.activation(x, self._act)

    def __repr__(self) -> str:
        return f"Activation({self._act})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha: float = 0.01, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._alpha = alpha

    def forward(self, x: NDArray) -> NDArray:
        return npx.leaky_relu(x, slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer: Any = "constant",
                 in_channels: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        from ... import initializer
        init = initializer.Constant(0.25) \
            if alpha_initializer == "constant" else alpha_initializer
        self.alpha = Parameter("alpha", shape=(in_channels,), init=init)

    def forward(self, x: NDArray) -> NDArray:
        if not self.alpha.is_initialized:
            self.alpha._finish_deferred_init(self.alpha.shape)
        return npx.prelu(x, self.alpha.data())


class ELU(HybridBlock):
    def __init__(self, alpha: float = 1.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._alpha = alpha

    def forward(self, x: NDArray) -> NDArray:
        return npx.elu(x, self._alpha)


class SELU(HybridBlock):
    def forward(self, x: NDArray) -> NDArray:
        return npx.selu(x)


class GELU(HybridBlock):
    def __init__(self, approximation: str = "erf", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._approx = approximation != "erf"

    def forward(self, x: NDArray) -> NDArray:
        return npx.gelu(x, approximate=self._approx)


class Swish(HybridBlock):
    def __init__(self, beta: float = 1.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._beta = beta

    def forward(self, x: NDArray) -> NDArray:
        if self._beta == 1.0:
            return npx.silu(x)
        return x * npx.activation(x * self._beta, "sigmoid")


SiLU = Swish


class Mish(HybridBlock):
    def forward(self, x: NDArray) -> NDArray:
        return npx.mish(x)


class HardSigmoid(HybridBlock):
    def __init__(self, alpha: float = 0.2, beta: float = 0.5,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._alpha, self._beta = alpha, beta

    def forward(self, x: NDArray) -> NDArray:
        return npx.hard_sigmoid(x, self._alpha, self._beta)


class HardSwish(HybridBlock):
    def forward(self, x: NDArray) -> NDArray:
        return npx.hard_swish(x)
