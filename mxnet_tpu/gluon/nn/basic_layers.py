"""Basic neural-network layers.

Reference parity (leezu/mxnet): ``python/mxnet/gluon/nn/basic_layers.py`` —
Sequential/HybridSequential, Dense, Dropout, BatchNorm, LayerNorm,
GroupNorm, InstanceNorm, Embedding, Flatten, HybridLambda/Lambda,
Identity — SURVEY.md section 2.5.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple, Union

from ... import npx
from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm",
           "Embedding", "Flatten", "Lambda", "HybridLambda", "Identity",
           "HybridConcatenate", "Concatenate"]


class Sequential(Block):
    """Stack of blocks executed in order (``nn.Sequential``)."""

    def __init__(self, prefix: Optional[str] = None) -> None:
        super().__init__(prefix)

    def add(self, *blocks: Block) -> None:
        for b in blocks:
            self.register_child(b)

    def forward(self, x: Any, *args: Any) -> Any:
        for child in self._children.values():
            x = child(x, *args)
            args = ()
        return x

    def __len__(self) -> int:
        return len(self._children)

    def __getitem__(self, key: Union[int, slice]):
        items = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*items[key])
            return net
        return items[key]

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active: bool = True, **kwargs: Any) -> None:
        for child in self._children.values():
            if isinstance(child, HybridBlock):
                child.hybridize(active, **kwargs)


def _has_hooks(*blocks) -> bool:
    """Fused paths bypass the children's __call__, so any registered
    hook disqualifies fusion (hooks must keep firing identically on
    every backend)."""
    return any(b._forward_pre_hooks or b._forward_hooks for b in blocks)


def _conv1x1_fusable(conv) -> bool:
    """Can this conv be the GEMM of a Pallas prologue-fused junction?
    (1x1, stride 1, NCHW, no groups/dilation/activation, stock forward
    — the kernel contract of ops/pallas/conv_fused.py.)  Shared by the
    HybridSequential triple matcher and the resnet epilogue deferral."""
    from .conv_layers import Conv2D, _Conv
    return (isinstance(conv, Conv2D)
            and type(conv).forward is _Conv.forward
            and conv._kernel == (1, 1) and conv._strides == (1, 1)
            and conv._padding == (0, 0) and conv._dilation == (1, 1)
            and conv._groups == 1 and conv._layout == "NCHW"
            and not conv._activation and not _has_hooks(conv))


def _fusable_bn_relu_conv(children, i, x) -> bool:
    """Is children[i:i+3] a (BatchNorm, relu, 1x1-s1 Conv2D) junction the
    Pallas prologue-fused GEMM can take whole?  (NCHW, no groups/
    dilation, stock forwards — see ops/pallas/conv_fused.py.)"""
    if i + 3 > len(children):
        return False
    bn, act, conv = children[i], children[i + 1], children[i + 2]
    from .activations import Activation
    if not (isinstance(bn, BatchNorm) and type(bn).forward is BatchNorm.forward
            and isinstance(act, Activation)
            and type(act).forward is Activation.forward
            and _conv1x1_fusable(conv)):
        return False
    if bn._axis != 1 or act._act != "relu" or _has_hooks(bn, act):
        return False
    if not (isinstance(x, NDArray) and x.ndim == 4):
        return False
    from ...ops.pallas.conv_fused import fusion_profitable
    n, ci, h, w = x.shape
    return fusion_profitable(n, ci, conv._channels, h * w)


def _sequential_forward(children, x: Any, args: tuple = ()) -> Any:
    """The HybridSequential chain with junction fusion — shared with
    residual blocks that run a children suffix after a fused head
    (model_zoo resnet BottleneckV1)."""
    fuse = npx.conv_fusion_enabled() and not args
    i = 0
    while i < len(children):
        if fuse and _fusable_bn_relu_conv(children, i, x):
            x = children[i].fused_conv_forward(x, children[i + 2])
            i += 3
            continue
        x = children[i](x, *args)
        args = ()
        i += 1
    return x


class HybridSequential(HybridBlock):
    """Hybridizable Sequential — compiles to one XLA program.

    With MXNET_FUSE_BN_CONV enabled ('auto' = single-device TPU; default
    off), consecutive ``BatchNorm -> relu -> 1x1 Conv2D`` children
    execute as one Pallas prologue-fused GEMM: the normalized/activated
    tensor never round-trips HBM (the ResNet-50 bottleneck's hot
    junction — BASELINE.md bandwidth roofline)."""

    def __init__(self, prefix: Optional[str] = None) -> None:
        super().__init__(prefix)

    def add(self, *blocks: Block) -> None:
        for b in blocks:
            self.register_child(b)

    def forward(self, x: Any, *args: Any) -> Any:
        return _sequential_forward(list(self._children.values()), x, args)

    def __len__(self) -> int:
        return len(self._children)

    def __getitem__(self, key):
        items = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*items[key])
            return net
        return items[key]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer: out = act(x·Wᵀ + b).

    Weight layout (units, in_units) follows the reference
    (``FullyConnected``); ``in_units`` may be omitted for deferred init.
    """

    def __init__(self, units: int, activation: Optional[str] = None,
                 use_bias: bool = True, flatten: bool = True,
                 dtype: Any = "float32", weight_initializer: Any = None,
                 bias_initializer: Any = "zeros", in_units: int = 0,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._activation = activation
        self.weight = Parameter("weight", shape=(units, in_units),
                                dtype=dtype, init=weight_initializer)
        self.bias = Parameter("bias", shape=(units,), dtype=dtype,
                              init=bias_initializer) if use_bias else None

    def forward(self, x: NDArray) -> NDArray:
        if not self.weight.is_initialized:
            in_units = (x.size // x.shape[0]) if self._flatten \
                else x.shape[-1]
            self.weight._finish_deferred_init((self._units, in_units))
            if self.bias is not None:
                self.bias._finish_deferred_init((self._units,))
        out = npx.fully_connected(
            x, self.weight.data(), None if self.bias is None
            else self.bias.data(), num_hidden=self._units,
            no_bias=self.bias is None, flatten=self._flatten)
        if self._activation:
            out = npx.activation(out, self._activation)
        return out

    def __repr__(self) -> str:
        shape = self.weight.shape
        return (f"Dense({shape[1] if shape and len(shape) > 1 else None} "
                f"-> {self._units}, "
                f"{self._activation or 'linear'})")


class Dropout(HybridBlock):
    """Dropout with optional shared axes (``nn.Dropout``)."""

    def __init__(self, rate: float, axes: Tuple[int, ...] = (),
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def forward(self, x: NDArray) -> NDArray:
        return npx.dropout(x, self._rate, axes=self._axes)

    def __repr__(self) -> str:
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization with moving statistics (``nn.BatchNorm``).

    The moving-stat update happens outside the autograd tape (the
    reference mutates aux states inside the fused op; see ops/nn.py
    batch_norm docstring).
    """

    def __init__(self, axis: int = 1, momentum: float = 0.9,
                 epsilon: float = 1e-5, center: bool = True,
                 scale: bool = True, use_global_stats: bool = False,
                 beta_initializer: Any = "zeros",
                 gamma_initializer: Any = "ones",
                 running_mean_initializer: Any = "zeros",
                 running_variance_initializer: Any = "ones",
                 in_channels: int = 0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=gamma_initializer,
                               grad_req="write" if scale else "null")
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=beta_initializer,
                              grad_req="write" if center else "null")
        self.running_mean = Parameter("running_mean", shape=(in_channels,),
                                      init=running_mean_initializer,
                                      differentiable=False)
        self.running_var = Parameter("running_var", shape=(in_channels,),
                                     init=running_variance_initializer,
                                     differentiable=False)
        # variance-shift buffer for the fused one-pass training stats
        # (ops/nn.py _bn_train_math): holds the PREVIOUS batch's mean —
        # always ~E[x], so the shifted variance never catastrophically
        # cancels, independent of running-mean warm-up. Runtime-only
        # state: excluded from .params files (persistent=False) and
        # rebuilt from the first batch after any load. The very first
        # training forward (virgin shift) uses centered stats instead.
        self.stat_shift = Parameter("stat_shift", shape=(in_channels,),
                                    init="zeros", differentiable=False,
                                    persistent=False)
        self._stats_virgin: Optional[bool] = None
        self._virgin_for: Any = None  # weakref to the resolved buffer

    def _resolve_virgin_stats(self) -> bool:
        # the cached verdict is tied to the buffer OBJECT: initialize(
        # force_reinit=True) swaps in a fresh zero NDArray, which must
        # re-trigger the virgin (centered) step — a stale False here
        # would re-expose the cold-start cancellation
        arr = self.stat_shift.data()
        prev = self._virgin_for() if self._virgin_for is not None else None
        if prev is not arr:
            self._stats_virgin = None
        if self._stats_virgin is None:
            import jax
            import weakref
            self._virgin_for = weakref.ref(arr)
            sh = arr._data
            if isinstance(sh, jax.core.Tracer):
                # mid-trace: inspect the concrete buffer _bind_params
                # stashed (hybridize / SPMDTrainer both bind through it)
                sh = getattr(arr, "_concrete_shadow", None)
            if sh is None or isinstance(sh, jax.core.Tracer):
                return False  # no host value in reach: assume warm
            import numpy as onp
            try:
                self._stats_virgin = not onp.asarray(sh).any()
            except Exception:
                # e.g. non-addressable multi-process array: assume warm
                # (MXNET_BN_STATS=centered is the escape hatch)
                self._stats_virgin = False
        return self._stats_virgin

    def _pre(self, x: NDArray) -> Tuple[bool, bool]:
        """Deferred init + (training, virgin-shift) resolution — shared
        by forward() and the fused-conv path."""
        from ... import autograd
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean,
                  self.running_var, self.stat_shift):
            if not p.is_initialized:
                p._finish_deferred_init((c,))
        training = autograd.is_training() and not self._use_global_stats
        virgin = training and self._resolve_virgin_stats()
        return training, virgin

    def forward(self, x: NDArray) -> NDArray:
        training, virgin = self._pre(x)
        out, batch_mean, batch_var = npx.batch_norm(
            x, self.gamma.data(), self.beta.data(),
            self.running_mean.data(), self.running_var.data(),
            eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale, axis=self._axis,
            use_global_stats=self._use_global_stats,
            stats="centered" if virgin else None,
            shift=self.stat_shift.data())
        self._post(training, virgin, batch_mean, batch_var)
        return out

    def fused_conv_forward(self, x: NDArray, conv) -> NDArray:
        """``conv(relu(bn(x)))`` through the Pallas prologue-fused GEMM
        (ops/pallas/conv_fused.py) — the BN statistics contract (shifted
        one-pass, virgin step, moving-average update) is identical to
        forward(); only the apply+ReLU+conv execute as one kernel."""
        training, virgin = self._pre(x)
        conv._infer(x)
        out, batch_mean, batch_var = npx.batch_norm_relu_conv1x1(
            x, self.gamma.data(), self.beta.data(),
            self.running_mean.data(), self.running_var.data(),
            conv.weight.data(),
            conv_bias=None if conv.bias is None else conv.bias.data(),
            eps=self._epsilon, fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats,
            stats="centered" if virgin else None,
            shift=self.stat_shift.data())
        self._post(training, virgin, batch_mean, batch_var)
        return out

    def _post(self, training: bool, virgin: bool, batch_mean: NDArray,
              batch_var: NDArray) -> None:
        if training:
            # side-effecting moving-average update, off the tape
            # (reference momentum recursion, preserved exactly)
            m = self._momentum
            rm, rv = self.running_mean.data(), self.running_var.data()
            rm._data = m * rm._data + (1 - m) * batch_mean.detach()._data
            rv._data = m * rv._data + (1 - m) * batch_var.detach()._data
            # shift buffer tracks the last batch mean (no blending: it
            # only needs to be NEAR E[x] for numerical stability)
            sh = self.stat_shift.data()
            sh._data = batch_mean.detach()._data.astype(sh._data.dtype)
            if virgin:
                self._stats_virgin = False
                # the centered first-step graph runs exactly once:
                # cached executables must re-trace onto the shifted path
                from ..block import invalidate_cached_graphs
                invalidate_cached_graphs()

    def __repr__(self) -> str:
        return f"BatchNorm(axis={self._axis}, momentum={self._momentum})"


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference: contrib.SyncBatchNorm over
    NCCL). Under SPMD the stats reduction happens automatically when the
    batch axis is sharded over the mesh — XLA inserts the collective — so
    this is BatchNorm with a documented mesh contract."""

    def __init__(self, in_channels: int = 0, num_devices: Optional[int] = None,
                 **kwargs: Any) -> None:
        kwargs.setdefault("in_channels", in_channels)
        super().__init__(**kwargs)


class LayerNorm(HybridBlock):
    """Layer normalization (``nn.LayerNorm``; fast path = XLA fusion)."""

    def __init__(self, axis: int = -1, epsilon: float = 1e-5,
                 center: bool = True, scale: bool = True,
                 beta_initializer: Any = "zeros",
                 gamma_initializer: Any = "ones", in_channels: int = 0,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=gamma_initializer)
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=beta_initializer)

    def forward(self, x: NDArray) -> NDArray:
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            if not p.is_initialized:
                p._finish_deferred_init((c,))
        return npx.layer_norm(x, self.gamma.data(), self.beta.data(),
                              axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups: int = 1, epsilon: float = 1e-5,
                 center: bool = True, scale: bool = True,
                 beta_initializer: Any = "zeros",
                 gamma_initializer: Any = "ones", in_channels: int = 0,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=gamma_initializer)
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=beta_initializer)

    def forward(self, x: NDArray) -> NDArray:
        c = x.shape[1]
        for p in (self.gamma, self.beta):
            if not p.is_initialized:
                p._finish_deferred_init((c,))
        return npx.group_norm(x, self.gamma.data(), self.beta.data(),
                              num_groups=self._num_groups, eps=self._epsilon)


class InstanceNorm(HybridBlock):
    def __init__(self, axis: int = 1, epsilon: float = 1e-5,
                 center: bool = True, scale: bool = True,
                 beta_initializer: Any = "zeros",
                 gamma_initializer: Any = "ones", in_channels: int = 0,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._epsilon = epsilon
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=gamma_initializer)
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=beta_initializer)

    def forward(self, x: NDArray) -> NDArray:
        c = x.shape[1]
        for p in (self.gamma, self.beta):
            if not p.is_initialized:
                p._finish_deferred_init((c,))
        return npx.instance_norm(x, self.gamma.data(), self.beta.data(),
                                 eps=self._epsilon)


class Embedding(HybridBlock):
    """Index → vector lookup table (``nn.Embedding``)."""

    def __init__(self, input_dim: int, output_dim: int,
                 dtype: Any = "float32", weight_initializer: Any = None,
                 sparse_grad: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        self.weight = Parameter("weight", shape=(input_dim, output_dim),
                                dtype=dtype, init=weight_initializer,
                                grad_stype="row_sparse" if sparse_grad
                                else "default")

    def forward(self, x: NDArray) -> NDArray:
        return npx.embedding(x, self.weight.data(),
                             input_dim=self._input_dim,
                             output_dim=self._output_dim,
                             sparse_grad=self._sparse_grad)

    def __repr__(self) -> str:
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    """Collapse all but the batch axis (``nn.Flatten``)."""

    def forward(self, x: NDArray) -> NDArray:
        return x.reshape(x.shape[0], -1)

    def __repr__(self) -> str:
        return "Flatten"


class Identity(HybridBlock):
    def forward(self, x: NDArray) -> NDArray:
        return x


class Lambda(Block):
    """Wrap an arbitrary function as a Block (``nn.Lambda``)."""

    def __init__(self, function: Union[str, Callable], **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if isinstance(function, str):
            from ... import numpy as mxnp
            function = getattr(mxnp, function)
        self._func = function

    def forward(self, *args: Any) -> Any:
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function: Union[str, Callable], **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if isinstance(function, str):
            from ... import numpy as mxnp
            function = getattr(mxnp, function)
        self._func = function

    def forward(self, *args: Any) -> Any:
        return self._func(*args)


class HybridConcatenate(HybridBlock):
    """Run children on the same input and concat outputs (contrib)."""

    def __init__(self, axis: int = -1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.axis = axis

    def add(self, *blocks: Block) -> None:
        for b in blocks:
            self.register_child(b)

    def forward(self, x: Any) -> Any:
        from ... import numpy as mxnp
        outs = [child(x) for child in self._children.values()]
        return mxnp.concatenate(outs, axis=self.axis)


Concatenate = HybridConcatenate
