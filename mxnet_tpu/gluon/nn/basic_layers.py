"""Basic neural-network layers.

Reference parity (leezu/mxnet): ``python/mxnet/gluon/nn/basic_layers.py`` —
Sequential/HybridSequential, Dense, Dropout, BatchNorm, LayerNorm,
GroupNorm, InstanceNorm, Embedding, Flatten, HybridLambda/Lambda,
Identity — SURVEY.md section 2.5.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple, Union

from ... import npx
from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm",
           "Embedding", "Flatten", "Lambda", "HybridLambda", "Identity",
           "HybridConcatenate", "Concatenate"]


class Sequential(Block):
    """Stack of blocks executed in order (``nn.Sequential``)."""

    def __init__(self, prefix: Optional[str] = None) -> None:
        super().__init__(prefix)

    def add(self, *blocks: Block) -> None:
        for b in blocks:
            self.register_child(b)

    def forward(self, x: Any, *args: Any) -> Any:
        for child in self._children.values():
            x = child(x, *args)
            args = ()
        return x

    def __len__(self) -> int:
        return len(self._children)

    def __getitem__(self, key: Union[int, slice]):
        items = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*items[key])
            return net
        return items[key]

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active: bool = True, **kwargs: Any) -> None:
        for child in self._children.values():
            if isinstance(child, HybridBlock):
                child.hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Hybridizable Sequential — compiles to one XLA program."""

    def __init__(self, prefix: Optional[str] = None) -> None:
        super().__init__(prefix)

    def add(self, *blocks: Block) -> None:
        for b in blocks:
            self.register_child(b)

    def forward(self, x: Any, *args: Any) -> Any:
        for child in self._children.values():
            x = child(x, *args)
            args = ()
        return x

    def __len__(self) -> int:
        return len(self._children)

    def __getitem__(self, key):
        items = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*items[key])
            return net
        return items[key]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer: out = act(x·Wᵀ + b).

    Weight layout (units, in_units) follows the reference
    (``FullyConnected``); ``in_units`` may be omitted for deferred init.
    """

    def __init__(self, units: int, activation: Optional[str] = None,
                 use_bias: bool = True, flatten: bool = True,
                 dtype: Any = "float32", weight_initializer: Any = None,
                 bias_initializer: Any = "zeros", in_units: int = 0,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._activation = activation
        self.weight = Parameter("weight", shape=(units, in_units),
                                dtype=dtype, init=weight_initializer)
        self.bias = Parameter("bias", shape=(units,), dtype=dtype,
                              init=bias_initializer) if use_bias else None

    def forward(self, x: NDArray) -> NDArray:
        if not self.weight.is_initialized:
            in_units = (x.size // x.shape[0]) if self._flatten \
                else x.shape[-1]
            self.weight._finish_deferred_init((self._units, in_units))
            if self.bias is not None:
                self.bias._finish_deferred_init((self._units,))
        out = npx.fully_connected(
            x, self.weight.data(), None if self.bias is None
            else self.bias.data(), num_hidden=self._units,
            no_bias=self.bias is None, flatten=self._flatten)
        if self._activation:
            out = npx.activation(out, self._activation)
        return out

    def __repr__(self) -> str:
        shape = self.weight.shape
        return (f"Dense({shape[1] if shape and len(shape) > 1 else None} "
                f"-> {self._units}, "
                f"{self._activation or 'linear'})")


class Dropout(HybridBlock):
    """Dropout with optional shared axes (``nn.Dropout``)."""

    def __init__(self, rate: float, axes: Tuple[int, ...] = (),
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def forward(self, x: NDArray) -> NDArray:
        return npx.dropout(x, self._rate, axes=self._axes)

    def __repr__(self) -> str:
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization with moving statistics (``nn.BatchNorm``).

    The moving-stat update happens outside the autograd tape (the
    reference mutates aux states inside the fused op; see ops/nn.py
    batch_norm docstring).
    """

    def __init__(self, axis: int = 1, momentum: float = 0.9,
                 epsilon: float = 1e-5, center: bool = True,
                 scale: bool = True, use_global_stats: bool = False,
                 beta_initializer: Any = "zeros",
                 gamma_initializer: Any = "ones",
                 running_mean_initializer: Any = "zeros",
                 running_variance_initializer: Any = "ones",
                 in_channels: int = 0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=gamma_initializer,
                               grad_req="write" if scale else "null")
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=beta_initializer,
                              grad_req="write" if center else "null")
        self.running_mean = Parameter("running_mean", shape=(in_channels,),
                                      init=running_mean_initializer,
                                      differentiable=False)
        self.running_var = Parameter("running_var", shape=(in_channels,),
                                     init=running_variance_initializer,
                                     differentiable=False)
        # variance-shift buffer for the fused one-pass training stats
        # (ops/nn.py _bn_train_math): holds the PREVIOUS batch's mean —
        # always ~E[x], so the shifted variance never catastrophically
        # cancels, independent of running-mean warm-up. Runtime-only
        # state: excluded from .params files (persistent=False) and
        # rebuilt from the first batch after any load. The very first
        # training forward (virgin shift) uses centered stats instead.
        self.stat_shift = Parameter("stat_shift", shape=(in_channels,),
                                    init="zeros", differentiable=False,
                                    persistent=False)
        self._stats_virgin: Optional[bool] = None
        self._virgin_for: Any = None  # weakref to the resolved buffer

    def _resolve_virgin_stats(self) -> bool:
        # the cached verdict is tied to the buffer OBJECT: initialize(
        # force_reinit=True) swaps in a fresh zero NDArray, which must
        # re-trigger the virgin (centered) step — a stale False here
        # would re-expose the cold-start cancellation
        arr = self.stat_shift.data()
        prev = self._virgin_for() if self._virgin_for is not None else None
        if prev is not arr:
            self._stats_virgin = None
        if self._stats_virgin is None:
            import jax
            import weakref
            self._virgin_for = weakref.ref(arr)
            sh = arr._data
            if isinstance(sh, jax.core.Tracer):
                # mid-trace: inspect the concrete buffer _bind_params
                # stashed (hybridize / SPMDTrainer both bind through it)
                sh = getattr(arr, "_concrete_shadow", None)
            if sh is None or isinstance(sh, jax.core.Tracer):
                return False  # no host value in reach: assume warm
            import numpy as onp
            try:
                self._stats_virgin = not onp.asarray(sh).any()
            except Exception:
                # e.g. non-addressable multi-process array: assume warm
                # (MXNET_BN_STATS=centered is the escape hatch)
                self._stats_virgin = False
        return self._stats_virgin

    def forward(self, x: NDArray) -> NDArray:
        from ... import autograd
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean,
                  self.running_var, self.stat_shift):
            if not p.is_initialized:
                p._finish_deferred_init((c,))
        training = autograd.is_training() and not self._use_global_stats
        virgin = training and self._resolve_virgin_stats()
        out, batch_mean, batch_var = npx.batch_norm(
            x, self.gamma.data(), self.beta.data(),
            self.running_mean.data(), self.running_var.data(),
            eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale, axis=self._axis,
            use_global_stats=self._use_global_stats,
            stats="centered" if virgin else None,
            shift=self.stat_shift.data())
        if training:
            # side-effecting moving-average update, off the tape
            # (reference momentum recursion, preserved exactly)
            m = self._momentum
            rm, rv = self.running_mean.data(), self.running_var.data()
            rm._data = m * rm._data + (1 - m) * batch_mean.detach()._data
            rv._data = m * rv._data + (1 - m) * batch_var.detach()._data
            # shift buffer tracks the last batch mean (no blending: it
            # only needs to be NEAR E[x] for numerical stability)
            sh = self.stat_shift.data()
            sh._data = batch_mean.detach()._data.astype(sh._data.dtype)
            if virgin:
                self._stats_virgin = False
                # the centered first-step graph runs exactly once:
                # cached executables must re-trace onto the shifted path
                from ..block import invalidate_cached_graphs
                invalidate_cached_graphs()
        return out

    def __repr__(self) -> str:
        return f"BatchNorm(axis={self._axis}, momentum={self._momentum})"


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference: contrib.SyncBatchNorm over
    NCCL). Under SPMD the stats reduction happens automatically when the
    batch axis is sharded over the mesh — XLA inserts the collective — so
    this is BatchNorm with a documented mesh contract."""

    def __init__(self, in_channels: int = 0, num_devices: Optional[int] = None,
                 **kwargs: Any) -> None:
        kwargs.setdefault("in_channels", in_channels)
        super().__init__(**kwargs)


class LayerNorm(HybridBlock):
    """Layer normalization (``nn.LayerNorm``; fast path = XLA fusion)."""

    def __init__(self, axis: int = -1, epsilon: float = 1e-5,
                 center: bool = True, scale: bool = True,
                 beta_initializer: Any = "zeros",
                 gamma_initializer: Any = "ones", in_channels: int = 0,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=gamma_initializer)
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=beta_initializer)

    def forward(self, x: NDArray) -> NDArray:
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            if not p.is_initialized:
                p._finish_deferred_init((c,))
        return npx.layer_norm(x, self.gamma.data(), self.beta.data(),
                              axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups: int = 1, epsilon: float = 1e-5,
                 center: bool = True, scale: bool = True,
                 beta_initializer: Any = "zeros",
                 gamma_initializer: Any = "ones", in_channels: int = 0,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=gamma_initializer)
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=beta_initializer)

    def forward(self, x: NDArray) -> NDArray:
        c = x.shape[1]
        for p in (self.gamma, self.beta):
            if not p.is_initialized:
                p._finish_deferred_init((c,))
        return npx.group_norm(x, self.gamma.data(), self.beta.data(),
                              num_groups=self._num_groups, eps=self._epsilon)


class InstanceNorm(HybridBlock):
    def __init__(self, axis: int = 1, epsilon: float = 1e-5,
                 center: bool = True, scale: bool = True,
                 beta_initializer: Any = "zeros",
                 gamma_initializer: Any = "ones", in_channels: int = 0,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._epsilon = epsilon
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=gamma_initializer)
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=beta_initializer)

    def forward(self, x: NDArray) -> NDArray:
        c = x.shape[1]
        for p in (self.gamma, self.beta):
            if not p.is_initialized:
                p._finish_deferred_init((c,))
        return npx.instance_norm(x, self.gamma.data(), self.beta.data(),
                                 eps=self._epsilon)


class Embedding(HybridBlock):
    """Index → vector lookup table (``nn.Embedding``)."""

    def __init__(self, input_dim: int, output_dim: int,
                 dtype: Any = "float32", weight_initializer: Any = None,
                 sparse_grad: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        self.weight = Parameter("weight", shape=(input_dim, output_dim),
                                dtype=dtype, init=weight_initializer,
                                grad_stype="row_sparse" if sparse_grad
                                else "default")

    def forward(self, x: NDArray) -> NDArray:
        return npx.embedding(x, self.weight.data(),
                             input_dim=self._input_dim,
                             output_dim=self._output_dim,
                             sparse_grad=self._sparse_grad)

    def __repr__(self) -> str:
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    """Collapse all but the batch axis (``nn.Flatten``)."""

    def forward(self, x: NDArray) -> NDArray:
        return x.reshape(x.shape[0], -1)

    def __repr__(self) -> str:
        return "Flatten"


class Identity(HybridBlock):
    def forward(self, x: NDArray) -> NDArray:
        return x


class Lambda(Block):
    """Wrap an arbitrary function as a Block (``nn.Lambda``)."""

    def __init__(self, function: Union[str, Callable], **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if isinstance(function, str):
            from ... import numpy as mxnp
            function = getattr(mxnp, function)
        self._func = function

    def forward(self, *args: Any) -> Any:
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function: Union[str, Callable], **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if isinstance(function, str):
            from ... import numpy as mxnp
            function = getattr(mxnp, function)
        self._func = function

    def forward(self, *args: Any) -> Any:
        return self._func(*args)


class HybridConcatenate(HybridBlock):
    """Run children on the same input and concat outputs (contrib)."""

    def __init__(self, axis: int = -1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.axis = axis

    def add(self, *blocks: Block) -> None:
        for b in blocks:
            self.register_child(b)

    def forward(self, x: Any) -> Any:
        from ... import numpy as mxnp
        outs = [child(x) for child in self._children.values()]
        return mxnp.concatenate(outs, axis=self.axis)


Concatenate = HybridConcatenate
