"""``gluon.data`` — datasets, samplers, DataLoader (reference:
``python/mxnet/gluon/data/``)."""
from .dataset import ArrayDataset, Dataset, SimpleDataset
from .sampler import (BatchSampler, FilterSampler, IntervalSampler,
                      RandomSampler, Sampler, SequentialSampler)
from .dataloader import DataLoader, default_batchify_fn
from . import vision

__all__ = ["ArrayDataset", "Dataset", "SimpleDataset", "BatchSampler",
           "FilterSampler", "IntervalSampler", "RandomSampler", "Sampler",
           "SequentialSampler", "DataLoader", "default_batchify_fn",
           "vision"]
