"""Vision transforms (reference:
``python/mxnet/gluon/data/vision/transforms.py`` — ToTensor, Normalize,
Resize, CenterCrop, RandomResizedCrop, RandomFlip*, Cast, Compose; backed
by C++ image ops in the reference, by numpy/PIL + XLA ops here)."""
from __future__ import annotations

import random as _pyrandom
from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as _np

from ....ndarray.ndarray import NDArray
from ...block import Block, HybridBlock

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomCrop",
           "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation", "RandomHue",
           "RandomLighting", "RandomColorJitter"]


def _to_np(x: Any) -> _np.ndarray:
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


class Compose(Block):
    """Sequentially apply transforms (``transforms.Compose``)."""

    def __init__(self, transforms: Sequence[Any]) -> None:
        super().__init__()
        self._transforms = list(transforms)

    def forward(self, x: Any) -> Any:
        for t in self._transforms:
            x = t(x)
        return x


class Cast(Block):
    def __init__(self, dtype: str = "float32") -> None:
        super().__init__()
        self._dtype = dtype

    def forward(self, x: NDArray) -> NDArray:
        return x.astype(self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference semantics)."""

    def forward(self, x: NDArray) -> NDArray:
        arr = _to_np(x).astype(_np.float32) / 255.0
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)
        elif arr.ndim == 4:
            arr = arr.transpose(0, 3, 1, 2)
        return NDArray(arr)


class Normalize(Block):
    """(x - mean) / std per channel, CHW input (after ToTensor)."""

    def __init__(self, mean: Union[float, Sequence[float]] = 0.0,
                 std: Union[float, Sequence[float]] = 1.0) -> None:
        super().__init__()
        self._mean = _np.asarray(mean, dtype=_np.float32)
        self._std = _np.asarray(std, dtype=_np.float32)

    def forward(self, x: NDArray) -> NDArray:
        arr = _to_np(x)
        c = arr.shape[0] if arr.ndim == 3 else arr.shape[1]
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return NDArray((arr - mean) / std)


def _pil_resize(arr: _np.ndarray, size: Tuple[int, int],
                interpolation: int = 1) -> _np.ndarray:
    from PIL import Image
    modes = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC,
             3: Image.LANCZOS}
    squeeze = arr.shape[-1] == 1
    img = Image.fromarray(arr.squeeze(-1) if squeeze else arr)
    img = img.resize(size, modes.get(interpolation, Image.BILINEAR))
    out = _np.asarray(img)
    if squeeze:
        out = out[:, :, None]
    return out


class Resize(Block):
    """Resize HWC image; ``size`` int (short edge if keep_ratio) or (w,h)."""

    def __init__(self, size: Union[int, Tuple[int, int]],
                 keep_ratio: bool = False, interpolation: int = 1) -> None:
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interp = interpolation

    def forward(self, x: NDArray) -> NDArray:
        arr = _to_np(x)
        h, w = arr.shape[:2]
        if isinstance(self._size, int):
            if self._keep:
                if h < w:
                    size = (int(w * self._size / h), self._size)
                else:
                    size = (self._size, int(h * self._size / w))
            else:
                size = (self._size, self._size)
        else:
            size = tuple(self._size)
        return NDArray(_pil_resize(arr, size, self._interp))


class CenterCrop(Block):
    def __init__(self, size: Union[int, Tuple[int, int]],
                 interpolation: int = 1) -> None:
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._interp = interpolation

    def forward(self, x: NDArray) -> NDArray:
        arr = _to_np(x)
        h, w = arr.shape[:2]
        cw, ch = self._size
        if h < ch or w < cw:
            arr = _pil_resize(arr, (max(w, cw), max(h, ch)), self._interp)
            h, w = arr.shape[:2]
        y0 = (h - ch) // 2
        x0 = (w - cw) // 2
        return NDArray(arr[y0:y0 + ch, x0:x0 + cw])


class RandomCrop(Block):
    def __init__(self, size: Union[int, Tuple[int, int]], pad: int = 0,
                 interpolation: int = 1) -> None:
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._pad = pad
        self._interp = interpolation

    def forward(self, x: NDArray) -> NDArray:
        arr = _to_np(x)
        if self._pad:
            arr = _np.pad(arr, ((self._pad,) * 2, (self._pad,) * 2, (0, 0)),
                          mode="constant")
        h, w = arr.shape[:2]
        cw, ch = self._size
        if h < ch or w < cw:  # upscale like CenterCrop before cropping
            arr = _pil_resize(arr, (max(w, cw), max(h, ch)), self._interp)
            h, w = arr.shape[:2]
        y0 = _pyrandom.randint(0, h - ch)
        x0 = _pyrandom.randint(0, w - cw)
        return NDArray(arr[y0:y0 + ch, x0:x0 + cw])


class RandomResizedCrop(Block):
    """Random area/aspect crop then resize (the ImageNet train transform)."""

    def __init__(self, size: Union[int, Tuple[int, int]],
                 scale: Tuple[float, float] = (0.08, 1.0),
                 ratio: Tuple[float, float] = (3 / 4, 4 / 3),
                 interpolation: int = 1) -> None:
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio
        self._interp = interpolation

    def forward(self, x: NDArray) -> NDArray:
        import math
        arr = _to_np(x)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = _pyrandom.uniform(*self._scale) * area
            log_r = (math.log(self._ratio[0]), math.log(self._ratio[1]))
            aspect = math.exp(_pyrandom.uniform(*log_r))
            cw = int(round(math.sqrt(target * aspect)))
            ch = int(round(math.sqrt(target / aspect)))
            if cw <= w and ch <= h:
                x0 = _pyrandom.randint(0, w - cw)
                y0 = _pyrandom.randint(0, h - ch)
                crop = arr[y0:y0 + ch, x0:x0 + cw]
                return NDArray(_pil_resize(crop, self._size, self._interp))
        # fallback: center crop
        return CenterCrop(self._size, self._interp)(NDArray(arr))


class RandomFlipLeftRight(Block):
    def __init__(self, p: float = 0.5) -> None:
        super().__init__()
        self._p = p

    def forward(self, x: NDArray) -> NDArray:
        if _pyrandom.random() < self._p:
            return NDArray(_to_np(x)[:, ::-1].copy())
        return x


class RandomFlipTopBottom(Block):
    def __init__(self, p: float = 0.5) -> None:
        super().__init__()
        self._p = p

    def forward(self, x: NDArray) -> NDArray:
        if _pyrandom.random() < self._p:
            return NDArray(_to_np(x)[::-1].copy())
        return x


class _RandomJitterBase(Block):
    def __init__(self, value: float) -> None:
        super().__init__()
        self._value = max(0.0, value)

    def _factor(self) -> float:
        return 1.0 + _pyrandom.uniform(-self._value, self._value)


class RandomBrightness(_RandomJitterBase):
    def forward(self, x: NDArray) -> NDArray:
        arr = _to_np(x).astype(_np.float32) * self._factor()
        return NDArray(_np.clip(arr, 0, 255).astype(_to_np(x).dtype))


class RandomContrast(_RandomJitterBase):
    def forward(self, x: NDArray) -> NDArray:
        arr = _to_np(x).astype(_np.float32)
        mean = arr.mean()
        out = (arr - mean) * self._factor() + mean
        return NDArray(_np.clip(out, 0, 255).astype(_to_np(x).dtype))


class RandomSaturation(_RandomJitterBase):
    def forward(self, x: NDArray) -> NDArray:
        arr = _to_np(x).astype(_np.float32)
        gray = arr.mean(axis=-1, keepdims=True)
        out = (arr - gray) * self._factor() + gray
        return NDArray(_np.clip(out, 0, 255).astype(_to_np(x).dtype))


class RandomLighting(_RandomJitterBase):
    """AlexNet-style PCA noise."""

    _eigval = _np.array([55.46, 4.794, 1.148], dtype=_np.float32)
    _eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.814],
                         [-0.5836, -0.6948, 0.4203]], dtype=_np.float32)

    def forward(self, x: NDArray) -> NDArray:
        alpha = _np.random.normal(0, self._value, size=(3,)).astype(_np.float32)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        arr = _to_np(x).astype(_np.float32) + rgb
        return NDArray(_np.clip(arr, 0, 255).astype(_to_np(x).dtype))


class RandomHue(_RandomJitterBase):
    """Rotate hue by up to ±value (in [0,0.5] half-turns of the hue wheel),
    via the YIQ rotation the reference's C++ hue op uses."""

    def forward(self, x: NDArray) -> NDArray:
        import math
        alpha = _pyrandom.uniform(-self._value, self._value)
        theta = alpha * math.pi
        u, w = math.cos(theta), math.sin(theta)
        t_yiq = _np.array([[0.299, 0.587, 0.114],
                           [0.596, -0.274, -0.321],
                           [0.211, -0.523, 0.311]], dtype=_np.float32)
        t_rgb = _np.array([[1.0, 0.956, 0.621],
                           [1.0, -0.272, -0.647],
                           [1.0, -1.107, 1.705]], dtype=_np.float32)
        rot = _np.array([[1, 0, 0], [0, u, -w], [0, w, u]], dtype=_np.float32)
        m = t_rgb @ rot @ t_yiq
        arr = _to_np(x).astype(_np.float32)
        out = arr @ m.T
        return NDArray(_np.clip(out, 0, 255).astype(_to_np(x).dtype))


class RandomColorJitter(Block):
    def __init__(self, brightness: float = 0, contrast: float = 0,
                 saturation: float = 0, hue: float = 0) -> None:
        super().__init__()
        self._ts: List[Block] = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x: NDArray) -> NDArray:
        order = list(self._ts)
        _pyrandom.shuffle(order)
        for t in order:
            x = t(x)
        return x
