"""``gluon.data.vision`` — datasets and transforms."""
from . import transforms
from .datasets import (MNIST, CIFAR10, CIFAR100, FashionMNIST,
                       ImageFolderDataset, ImageRecordDataset,
                       SyntheticImageDataset)

__all__ = ["transforms", "MNIST", "CIFAR10", "CIFAR100", "FashionMNIST",
           "ImageFolderDataset", "ImageRecordDataset",
           "SyntheticImageDataset"]
