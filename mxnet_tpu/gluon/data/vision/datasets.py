"""Vision datasets (reference: ``python/mxnet/gluon/data/vision/datasets.py``).

MNIST/FashionMNIST (idx files), CIFAR10/100 (binary batches),
ImageFolderDataset (PIL decode), ImageRecordDataset (recordio), and a
SyntheticImageDataset for benchmarking without data on disk. Downloads are
not possible in this environment (zero egress): datasets read from a local
``root`` and raise a clear error naming the expected files when absent.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Any, Callable, List, Optional, Tuple

import numpy as _np

from ....base import MXNetError
from ..dataset import ArrayDataset, Dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageFolderDataset", "ImageRecordDataset",
           "SyntheticImageDataset"]


def _read_idx_images(path: str) -> _np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise MXNetError(f"{path}: bad idx image magic {magic}")
        data = _np.frombuffer(f.read(), dtype=_np.uint8)
        return data.reshape(n, rows, cols, 1)


def _read_idx_labels(path: str) -> _np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise MXNetError(f"{path}: bad idx label magic {magic}")
        return _np.frombuffer(f.read(), dtype=_np.uint8).astype(_np.int32)


class _DownloadedDataset(Dataset):
    def __init__(self, root: str, train: bool,
                 transform: Optional[Callable]) -> None:
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data: Optional[_np.ndarray] = None
        self._label: Optional[_np.ndarray] = None
        self._get_data()

    def __getitem__(self, idx: int):
        from ....ndarray.ndarray import NDArray
        data = NDArray(self._data[idx])
        label = int(self._label[idx])
        if self._transform is not None:
            return self._transform(data, label)
        return data, label

    def __len__(self) -> int:
        return len(self._data)

    def _get_data(self) -> None:
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from idx files in ``root`` (reference: gluon.data.vision.MNIST;
    files as distributed: train-images-idx3-ubyte[.gz] etc.)."""

    _files = {
        True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }

    def __init__(self, root: str = "~/.mxnet/datasets/mnist",
                 train: bool = True,
                 transform: Optional[Callable] = None) -> None:
        super().__init__(root, train, transform)

    def _find(self, stem: str) -> str:
        for cand in (stem, stem + ".gz"):
            p = os.path.join(self._root, cand)
            if os.path.exists(p):
                return p
        raise MXNetError(
            f"MNIST file {stem}[.gz] not found under {self._root}; this "
            f"environment has no network egress — place the idx files "
            f"there manually, or use SyntheticImageDataset for smoke runs")

    def _get_data(self) -> None:
        img, lbl = self._files[self._train]
        self._data = _read_idx_images(self._find(img))
        self._label = _read_idx_labels(self._find(lbl))


class FashionMNIST(MNIST):
    def __init__(self, root: str = "~/.mxnet/datasets/fashion-mnist",
                 train: bool = True,
                 transform: Optional[Callable] = None) -> None:
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from the python-version pickled batches in ``root``."""

    def __init__(self, root: str = "~/.mxnet/datasets/cifar10",
                 train: bool = True,
                 transform: Optional[Callable] = None) -> None:
        super().__init__(root, train, transform)

    def _batches(self) -> List[str]:
        if self._train:
            return [f"data_batch_{i}" for i in range(1, 6)]
        return ["test_batch"]

    def _get_data(self) -> None:
        base = self._root
        sub = os.path.join(base, "cifar-10-batches-py")
        if os.path.isdir(sub):
            base = sub
        datas, labels = [], []
        for name in self._batches():
            p = os.path.join(base, name)
            if not os.path.exists(p):
                raise MXNetError(
                    f"CIFAR10 batch {name} not found under {base}; place "
                    f"the python-version batches there (no network egress)")
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            datas.append(d[b"data"].reshape(-1, 3, 32, 32)
                         .transpose(0, 2, 3, 1))
            labels.extend(d[b"labels"])
        self._data = _np.concatenate(datas).astype(_np.uint8)
        self._label = _np.asarray(labels, dtype=_np.int32)


class CIFAR100(CIFAR10):
    def __init__(self, root: str = "~/.mxnet/datasets/cifar100",
                 fine_label: bool = True, train: bool = True,
                 transform: Optional[Callable] = None) -> None:
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _get_data(self) -> None:
        base = self._root
        sub = os.path.join(base, "cifar-100-python")
        if os.path.isdir(sub):
            base = sub
        name = "train" if self._train else "test"
        p = os.path.join(base, name)
        if not os.path.exists(p):
            raise MXNetError(f"CIFAR100 file {name} not found under {base}")
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        self._data = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1) \
            .astype(_np.uint8)
        key = b"fine_labels" if self._fine else b"coarse_labels"
        self._label = _np.asarray(d[key], dtype=_np.int32)


class ImageFolderDataset(Dataset):
    """root/class_x/img.jpg layout, PIL-decoded (reference:
    ImageFolderDataset; decode was OpenCV in the reference)."""

    def __init__(self, root: str, flag: int = 1,
                 transform: Optional[Callable] = None) -> None:
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = {".jpg", ".jpeg", ".png", ".bmp"}
        self.synsets: List[str] = []
        self.items: List[Tuple[str, int]] = []
        if not os.path.isdir(self._root):
            raise MXNetError(f"ImageFolderDataset root {self._root} missing")
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if os.path.splitext(fname)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, fname), label))

    def __getitem__(self, idx: int):
        from PIL import Image
        from ....ndarray.ndarray import NDArray
        path, label = self.items[idx]
        img = Image.open(path)
        img = img.convert("RGB" if self._flag else "L")
        arr = _np.asarray(img, dtype=_np.uint8)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        data = NDArray(arr)
        if self._transform is not None:
            return self._transform(data, label)
        return data, label

    def __len__(self) -> int:
        return len(self.items)


class ImageRecordDataset(Dataset):
    """RecordIO-packed images (reference: ImageRecordDataset over
    ``tools/im2rec.py`` output)."""

    def __init__(self, filename: str, flag: int = 1,
                 transform: Optional[Callable] = None) -> None:
        from ....recordio import MXIndexedRecordIO, MXRecordIO, unpack_img
        self._flag = flag
        self._transform = transform
        self._unpack_img = unpack_img
        idx_file = os.path.splitext(filename)[0] + ".idx"
        if os.path.exists(idx_file):
            self._record = MXIndexedRecordIO(idx_file, filename, "r")
            self._keys = self._record.keys
        else:
            # fall back: scan sequentially once to index in memory
            rec = MXRecordIO(filename, "r")
            self._items = []
            while True:
                item = rec.read()
                if item is None:
                    break
                self._items.append(item)
            rec.close()
            self._record = None
            self._keys = list(range(len(self._items)))

    def __getitem__(self, idx: int):
        from ....ndarray.ndarray import NDArray
        if self._record is not None:
            raw = self._record.read_idx(self._keys[idx])
        else:
            raw = self._items[idx]
        header, img = self._unpack_img(raw, flag=self._flag)
        label = header.label
        if hasattr(label, "__len__") and len(label) == 1:
            label = float(label[0])
        data = NDArray(img)
        if self._transform is not None:
            return self._transform(data, label)
        return data, label

    def __len__(self) -> int:
        return len(self._keys)


class SyntheticImageDataset(Dataset):
    """Deterministic random images+labels for benchmarks — stands in for
    ImageNet when no data is mounted (benchmark-only; not in reference)."""

    def __init__(self, length: int = 1024,
                 shape: Tuple[int, ...] = (224, 224, 3),
                 num_classes: int = 1000, seed: int = 0,
                 transform: Optional[Callable] = None) -> None:
        self._length = length
        self._shape = shape
        self._num_classes = num_classes
        self._seed = seed
        self._transform = transform

    def __getitem__(self, idx: int):
        from ....ndarray.ndarray import NDArray
        rng = _np.random.RandomState((self._seed * 1000003 + idx) % (2**31))
        img = rng.randint(0, 256, size=self._shape, dtype=_np.uint8)
        label = int(rng.randint(0, self._num_classes))
        data = NDArray(img)
        if self._transform is not None:
            return self._transform(data, label)
        return data, label

    def __len__(self) -> int:
        return self._length
