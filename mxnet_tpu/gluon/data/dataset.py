"""Datasets (reference: ``python/mxnet/gluon/data/dataset.py``)."""
from __future__ import annotations

from typing import Any, Callable, List, Sequence

from ...ndarray.ndarray import NDArray

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset"]


class Dataset:
    """Abstract dataset: ``__getitem__`` + ``__len__``."""

    def __getitem__(self, idx: int) -> Any:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    def filter(self, fn: Callable[[Any], bool]) -> "SimpleDataset":
        return SimpleDataset([s for s in self if fn(s)])

    def shard(self, num_shards: int, index: int) -> "_ShardedDataset":
        return _ShardedDataset(self, num_shards, index)

    def take(self, count: int) -> "_TakenDataset":
        return _TakenDataset(self, count)

    def sample(self, sampler) -> "_SampledDataset":
        return _SampledDataset(self, sampler)

    def transform(self, fn: Callable, lazy: bool = True) -> "Dataset":
        """Return a dataset with ``fn`` applied to each sample."""
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn: Callable, lazy: bool = True) -> "Dataset":
        """Apply ``fn`` to the first element of each (data, label) sample;
        bare (non-tuple) samples pass through fn directly."""
        def first(*sample):
            if len(sample) == 1:
                return fn(sample[0])
            return (fn(sample[0]), *sample[1:])
        return self.transform(first, lazy)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]


class SimpleDataset(Dataset):
    def __init__(self, data: Sequence[Any]) -> None:
        self._data = data

    def __getitem__(self, idx: int) -> Any:
        return self._data[idx]

    def __len__(self) -> int:
        return len(self._data)


class ArrayDataset(Dataset):
    """Zip of equal-length arrays: sample i = (a[i], b[i], ...)."""

    def __init__(self, *args: Any) -> None:
        assert args, "needs at least one array"
        self._length = len(args[0])
        for a in args:
            assert len(a) == self._length, "all arrays must share length"
        self._data = list(args)

    def __getitem__(self, idx: int) -> Any:
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)

    def __len__(self) -> int:
        return self._length


class _LazyTransformDataset(Dataset):
    def __init__(self, dataset: Dataset, fn: Callable) -> None:
        self._dataset = dataset
        self._fn = fn

    def __getitem__(self, idx: int) -> Any:
        sample = self._dataset[idx]
        if isinstance(sample, tuple):
            return self._fn(*sample)
        return self._fn(sample)

    def __len__(self) -> int:
        return len(self._dataset)


class _ShardedDataset(Dataset):
    def __init__(self, dataset: Dataset, num_shards: int, index: int) -> None:
        self._dataset = dataset
        self._num = num_shards
        self._index = index

    def __getitem__(self, idx: int) -> Any:
        return self._dataset[idx * self._num + self._index]

    def __len__(self) -> int:
        n = len(self._dataset)
        return (n - self._index + self._num - 1) // self._num


class _TakenDataset(Dataset):
    def __init__(self, dataset: Dataset, count: int) -> None:
        self._dataset = dataset
        self._count = min(count, len(dataset))

    def __getitem__(self, idx: int) -> Any:
        if idx >= self._count:
            raise IndexError(idx)
        return self._dataset[idx]

    def __len__(self) -> int:
        return self._count


class _SampledDataset(Dataset):
    def __init__(self, dataset: Dataset, sampler) -> None:
        self._dataset = dataset
        self._indices = list(sampler)

    def __getitem__(self, idx: int) -> Any:
        return self._dataset[self._indices[idx]]

    def __len__(self) -> int:
        return len(self._indices)
