"""DataLoader — batched, shuffled, multi-worker data loading.

Reference parity (leezu/mxnet): ``python/mxnet/gluon/data/dataloader.py`` —
``DataLoader(dataset, batch_size, shuffle, sampler, last_batch,
batch_sampler, batchify_fn, num_workers, pin_memory, thread_pool,
prefetch)``.

Design (tpu-first): the reference forks worker processes and ships
NDArrays back through POSIX shared memory (``cpu_shared_storage_manager``).
Here workers produce **numpy** batches (host memory) in a persistent
``multiprocessing`` pool with index-order prefetch, and the main process
uploads to device — matching jax's host-to-device model where the transfer
wants one contiguous pinned buffer per batch. ``thread_pool=True`` uses
threads (for datasets that are not fork-safe). The engine's atfork concern
(reference ``src/initialize.cc ForkHandler``) does not apply: workers never
touch the device.
"""
from __future__ import annotations

import multiprocessing
import multiprocessing.pool
import threading
from collections import deque
from typing import Any, Callable, List, Optional, Sequence

import numpy as _np

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, Sampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def _as_numpy(sample: Any) -> Any:
    if isinstance(sample, NDArray):
        return sample.asnumpy()
    return sample


def default_batchify_fn(data: Sequence[Any]) -> Any:
    """Stack samples into a batch (reference: default_batchify_fn)."""
    first = data[0]
    if isinstance(first, tuple):
        return tuple(default_batchify_fn([d[i] for d in data])
                     for i in range(len(first)))
    if isinstance(first, NDArray):
        from ...ndarray import ops
        return ops.stack(list(data), axis=0)
    arrs = [_np.asarray(_as_numpy(d)) for d in data]
    return NDArray(_np.stack(arrs, axis=0))


default_mp_batchify_fn = default_batchify_fn


# worker globals installed by the pool initializer (fork start method)
_WORKER_DATASET: Optional[Dataset] = None
_WORKER_BATCHIFY: Optional[Callable] = None


def _worker_init(dataset: Dataset, batchify_fn: Callable) -> None:
    global _WORKER_DATASET, _WORKER_BATCHIFY
    _WORKER_DATASET = dataset
    _WORKER_BATCHIFY = batchify_fn


def _np_batchify(samples: List[Any]) -> Any:
    """Batchify to plain numpy inside workers (NDArrays don't cross the
    process boundary; numpy pickles via shared pages on fork+POSIX)."""
    first = samples[0]
    if isinstance(first, tuple):
        return tuple(_np_batchify([s[i] for s in samples])
                     for i in range(len(first)))
    return _np.stack([_np.asarray(_as_numpy(s)) for s in samples], axis=0)


def _batch_to_np(batch: Any) -> Any:
    """Convert a batch (possibly NDArrays from a custom batchify_fn) to
    numpy so it crosses the process boundary."""
    if isinstance(batch, (tuple, list)):
        return type(batch)(_batch_to_np(b) for b in batch)
    if isinstance(batch, dict):
        return {k: _batch_to_np(v) for k, v in batch.items()}
    if isinstance(batch, NDArray):
        return batch.asnumpy()
    return batch


def _worker_fn(indices: List[int]) -> Any:
    samples = [_WORKER_DATASET[i] for i in indices]
    if _WORKER_BATCHIFY is not None:
        return _batch_to_np(_WORKER_BATCHIFY(samples))
    return _np_batchify(samples)


def _to_ndarray(batch: Any) -> Any:
    if isinstance(batch, tuple):
        return tuple(_to_ndarray(b) for b in batch)
    if isinstance(batch, NDArray):
        return batch
    return NDArray(batch)


class DataLoader:
    def __init__(self, dataset: Dataset, batch_size: Optional[int] = None,
                 shuffle: bool = False, sampler: Optional[Sampler] = None,
                 last_batch: Optional[str] = None,
                 batch_sampler: Optional[BatchSampler] = None,
                 batchify_fn: Optional[Callable] = None,
                 num_workers: int = 0, pin_memory: bool = False,
                 prefetch: Optional[int] = None,
                 thread_pool: bool = False, timeout: int = 120) -> None:
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool
        self._timeout = timeout

        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size is required when batch_sampler "
                                 "is not given")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError("batch_size/shuffle/sampler/last_batch must not "
                             "be set when batch_sampler is given")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._custom_batchify = batchify_fn  # None => fast numpy default
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._pool = None
        if self._num_workers > 0:
            if thread_pool:
                self._pool = multiprocessing.pool.ThreadPool(
                    self._num_workers)
            else:
                # fork (reference behavior): zero-copy dataset inheritance.
                # CAVEAT: forking a process whose JAX runtime already spun
                # up threads can in principle deadlock a child mid-malloc;
                # workers here never call into jax, which makes this rare
                # in practice, but pass thread_pool=True for a fork-free
                # loader if your dataset is GIL-friendly (pure numpy/PIL).
                ctx = multiprocessing.get_context("fork")
                self._pool = ctx.Pool(
                    self._num_workers,
                    initializer=_worker_init,
                    initargs=(self._dataset, self._custom_batchify))

    def __iter__(self):
        if self._pool is None:
            for indices in self._batch_sampler:
                yield self._batchify_fn([self._dataset[i] for i in indices])
            return

        # async prefetch: keep up to `prefetch` outstanding batch jobs
        pending: deque = deque()
        batches = iter(self._batch_sampler)

        def submit():
            try:
                indices = next(batches)
            except StopIteration:
                return False
            if self._thread_pool:
                def thread_job(idx):
                    samples = [self._dataset[i] for i in idx]
                    if self._custom_batchify is not None:
                        return self._custom_batchify(samples)
                    return _np_batchify(samples)
                job = self._pool.apply_async(thread_job, (indices,))
            else:
                job = self._pool.apply_async(_worker_fn, (indices,))
            pending.append(job)
            return True

        for _ in range(self._prefetch or 1):
            if not submit():
                break
        while pending:
            job = pending.popleft()
            batch = job.get(self._timeout)
            submit()
            yield _to_ndarray(batch)

    def __len__(self) -> int:
        return len(self._batch_sampler)

    def __del__(self) -> None:
        pool = getattr(self, "_pool", None)  # __init__ may have raised early
        if pool is not None:
            try:
                pool.terminate()
            except Exception:
                pass  # interpreter shutdown: modules already torn down
