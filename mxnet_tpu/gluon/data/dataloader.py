"""DataLoader — batched, shuffled, multi-worker data loading.

Reference parity (leezu/mxnet): ``python/mxnet/gluon/data/dataloader.py`` —
``DataLoader(dataset, batch_size, shuffle, sampler, last_batch,
batch_sampler, batchify_fn, num_workers, pin_memory, thread_pool,
prefetch)``.

Design (tpu-first): the reference forks worker processes and ships
NDArrays back through POSIX shared memory (``cpu_shared_storage_manager``).
Here workers produce **numpy** batches (host memory) in a persistent
``multiprocessing`` pool with index-order prefetch, and the main process
uploads to device — matching jax's host-to-device model where the transfer
wants one contiguous pinned buffer per batch. ``thread_pool=True`` uses
threads (for datasets that are not fork-safe).

Worker start method (VERDICT r5 weak 1): workers **spawn** by default.
The reference could fork because its engine installs atfork handlers
(``src/initialize.cc ForkHandler``: quiesce the ThreadedEngine around the
fork); the XLA runtime has no such hook, so forking after jax has spun up
its dispatch threads deadlocks the child the moment the dataset touches a
jax-backed NDArray — exactly what any real image dataset does
(``ImageRecordDataset.__getitem__``).  Spawned workers start from a clean
interpreter (dataset + batchify ship by pickle; ``JAX_PLATFORMS=cpu`` and
``MXNET_NO_AUTO_DISTRIBUTED=1`` are pinned in the child env so a worker
can never grab the accelerator or join the job's rendezvous).  Spawn
costs one interpreter+import per worker at pool creation — amortized by
the persistent pool.  ``MXNET_DATALOADER_START_METHOD=fork`` restores
the old behavior for numpy-only datasets that want free pool startup.
"""
from __future__ import annotations

import multiprocessing
import multiprocessing.pool
import os
import threading
from collections import deque
from typing import Any, Callable, List, Optional, Sequence

import numpy as _np

from ...base import MXNetError, getenv, register_env
from ...ndarray.ndarray import NDArray
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, Sampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]

register_env("MXNET_DATALOADER_START_METHOD", "spawn",
             "Start method for DataLoader worker processes: 'spawn' "
             "(default — fork-after-jax deadlocks; spawned workers run a "
             "clean interpreter with JAX_PLATFORMS=cpu) or 'fork' "
             "(zero-cost pool startup, safe ONLY for datasets/transforms "
             "that never touch jax, e.g. pure numpy/PIL). "
             "'forkserver' is also accepted.")

register_env("MXNET_DATALOADER_IN_WORKER", 0,
             "Internal guard, set to 1 by the DataLoader in the "
             "environment of its spawned worker processes: a "
             "DataLoader constructed inside a worker (a guard-less "
             "script re-executing under spawn) degrades to in-process "
             "loading instead of recursively spawning nested pools. "
             "Not meant to be set by hand.")


def _as_numpy(sample: Any) -> Any:
    if isinstance(sample, NDArray):
        return sample.asnumpy()
    return sample


def default_batchify_fn(data: Sequence[Any]) -> Any:
    """Stack samples into a batch (reference: default_batchify_fn)."""
    first = data[0]
    if isinstance(first, tuple):
        return tuple(default_batchify_fn([d[i] for d in data])
                     for i in range(len(first)))
    if isinstance(first, NDArray):
        from ...ndarray import ops
        return ops.stack(list(data), axis=0)
    arrs = [_np.asarray(_as_numpy(d)) for d in data]
    return NDArray(_np.stack(arrs, axis=0))


default_mp_batchify_fn = default_batchify_fn


# worker globals installed by the pool initializer (dataset + batchify
# arrive by inheritance under fork, by pickle under spawn)
_WORKER_DATASET: Optional[Dataset] = None
_WORKER_BATCHIFY: Optional[Callable] = None


def _worker_init(dataset: Dataset, batchify_fn: Callable) -> None:
    global _WORKER_DATASET, _WORKER_BATCHIFY
    # re-assert the worker pins IN the worker: the parent scopes them to
    # pool construction (_WorkerEnv), but the pool's maintenance thread
    # respawns crashed workers later with the parent's unpinned env.
    # jax may already be imported (initargs unpickling) — its backend
    # resolves lazily, so forcing the config here still lands first.
    import os as _os
    _os.environ.update(_WorkerEnv._PINS)
    import sys as _sys
    _jax = _sys.modules.get("jax")
    if _jax is not None:
        try:
            _jax.config.update("jax_platforms", "cpu")
        except Exception:   # noqa: BLE001 - backend already initialized
            pass
    _WORKER_DATASET = dataset
    _WORKER_BATCHIFY = batchify_fn


class _WorkerEnv:
    """Pin the worker-safe env around child creation: spawned children
    snapshot ``os.environ`` at ``Process.start()``, so scoping the pins
    to pool construction gives every worker a CPU-only, rendezvous-free
    jax without disturbing the parent.

    Also hides ``__main__.__file__`` when it names no real file (stdin
    scripts report ``<stdin>``): spawn's preparation data would tell
    every child to re-run that path, each would crash on the missing
    file, and the pool would respawn crashing workers forever.  With it
    hidden, spawn skips main-module re-import — library-defined
    datasets still unpickle fine; objects defined in a stdin __main__
    fail with a clear pickle error instead of a hang."""

    _PINS = {"JAX_PLATFORMS": "cpu", "MXNET_NO_AUTO_DISTRIBUTED": "1",
             "MXNET_DATALOADER_IN_WORKER": "1"}

    def __enter__(self) -> None:
        import sys
        self._saved = {k: os.environ.get(k) for k in self._PINS}
        os.environ.update(self._PINS)
        self._main_file = None
        main = sys.modules.get("__main__")
        mf = getattr(main, "__file__", None)
        if mf is not None and getattr(main, "__spec__", None) is None \
                and not os.path.exists(mf):
            self._main_file = mf
            del main.__file__

    def __exit__(self, *exc: Any) -> None:
        import sys
        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if self._main_file is not None:
            sys.modules["__main__"].__file__ = self._main_file


def _np_batchify(samples: List[Any]) -> Any:
    """Batchify to plain numpy inside workers (NDArrays don't cross the
    process boundary; numpy pickles via shared pages on fork+POSIX)."""
    first = samples[0]
    if isinstance(first, tuple):
        return tuple(_np_batchify([s[i] for s in samples])
                     for i in range(len(first)))
    return _np.stack([_np.asarray(_as_numpy(s)) for s in samples], axis=0)


def _batch_to_np(batch: Any) -> Any:
    """Convert a batch (possibly NDArrays from a custom batchify_fn) to
    numpy so it crosses the process boundary."""
    if isinstance(batch, (tuple, list)):
        return type(batch)(_batch_to_np(b) for b in batch)
    if isinstance(batch, dict):
        return {k: _batch_to_np(v) for k, v in batch.items()}
    if isinstance(batch, NDArray):
        return batch.asnumpy()
    return batch


def _worker_fn(indices: List[int]) -> Any:
    # chaos site: MXNET_FAULT_PLAN rides into spawned workers via the
    # env snapshot, so 'dataloader.worker:kind=crash' kills a real
    # worker process mid-job (kind=error propagates through the pool)
    from ...faults import maybe_fault
    maybe_fault("dataloader.worker", batch_size=len(indices))
    samples = [_WORKER_DATASET[i] for i in indices]
    if _WORKER_BATCHIFY is not None:
        return _batch_to_np(_WORKER_BATCHIFY(samples))
    return _np_batchify(samples)


def _to_ndarray(batch: Any) -> Any:
    if isinstance(batch, tuple):
        return tuple(_to_ndarray(b) for b in batch)
    if isinstance(batch, NDArray):
        return batch
    return NDArray(batch)


class DataLoader:
    def __init__(self, dataset: Dataset, batch_size: Optional[int] = None,
                 shuffle: bool = False, sampler: Optional[Sampler] = None,
                 last_batch: Optional[str] = None,
                 batch_sampler: Optional[BatchSampler] = None,
                 batchify_fn: Optional[Callable] = None,
                 num_workers: int = 0, pin_memory: bool = False,
                 prefetch: Optional[int] = None,
                 thread_pool: bool = False, timeout: int = 120) -> None:
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool
        self._timeout = timeout

        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size is required when batch_sampler "
                                 "is not given")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError("batch_size/shuffle/sampler/last_batch must not "
                             "be set when batch_sampler is given")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._custom_batchify = batchify_fn  # None => fast numpy default
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._pool = None
        if self._num_workers > 0 and not thread_pool \
                and os.environ.get("MXNET_DATALOADER_IN_WORKER") == "1":
            # this process IS a spawned loader worker re-executing a
            # guard-less script (no `if __name__ == "__main__":`): a
            # nested pool here would recurse and the parent pool would
            # respawn crashing workers forever.  Degrade to in-process
            # loading — slow but terminating; real scripts should guard
            # their entry point (standard multiprocessing requirement).
            self._num_workers = 0
        if self._num_workers > 0:
            if thread_pool:
                self._pool = multiprocessing.pool.ThreadPool(
                    self._num_workers)
            else:
                # spawn (default): fork-after-jax deadlocks the child as
                # soon as the dataset touches a jax-backed NDArray (see
                # module docstring); spawned workers start clean.  The
                # dataset and a custom batchify_fn must pickle — define
                # them at module level (closures/lambdas only survive
                # the opt-in fork mode).
                method = str(getenv("MXNET_DATALOADER_START_METHOD",
                                    "spawn"))
                try:
                    ctx = multiprocessing.get_context(method)
                except ValueError:
                    raise MXNetError(
                        f"unknown MXNET_DATALOADER_START_METHOD "
                        f"{method!r} (use spawn, forkserver, or fork)")
                with _WorkerEnv():
                    self._pool = ctx.Pool(
                        self._num_workers,
                        initializer=_worker_init,
                        initargs=(self._dataset, self._custom_batchify))

    def __iter__(self):
        if self._pool is None:
            for indices in self._batch_sampler:
                yield self._batchify_fn([self._dataset[i] for i in indices])
            return

        # async prefetch: keep up to `prefetch` outstanding batch jobs
        pending: deque = deque()
        batches = iter(self._batch_sampler)

        def submit():
            try:
                indices = next(batches)
            except StopIteration:
                return False
            if self._thread_pool:
                def thread_job(idx):
                    from ...faults import maybe_fault
                    maybe_fault("dataloader.worker", batch_size=len(idx))
                    samples = [self._dataset[i] for i in idx]
                    if self._custom_batchify is not None:
                        return self._custom_batchify(samples)
                    return _np_batchify(samples)
                job = self._pool.apply_async(thread_job, (indices,))
            else:
                job = self._pool.apply_async(_worker_fn, (indices,))
            pending.append(job)
            return True

        for _ in range(self._prefetch or 1):
            if not submit():
                break
        while pending:
            job = pending.popleft()
            try:
                batch = job.get(self._timeout)
            except multiprocessing.TimeoutError:
                # a worker killed (OOM, SIGKILL) while holding this job
                # loses it forever — the pool respawns the worker but
                # never re-runs in-flight work.  Translate the bare
                # TimeoutError into a structured, actionable error
                # instead of letting the caller guess.
                raise MXNetError(
                    f"DataLoader batch not ready after {self._timeout}s "
                    "(DataLoader(timeout=...)): a worker process likely "
                    "died mid-job (killed/OOM) and its batch is lost; "
                    "the pool respawned the worker but in-flight jobs "
                    "do not recover — re-create the DataLoader iterator "
                    "to retry this epoch")
            submit()
            yield _to_ndarray(batch)

    def __len__(self) -> int:
        return len(self._batch_sampler)

    def __del__(self) -> None:
        pool = getattr(self, "_pool", None)  # __init__ may have raised early
        if pool is not None:
            try:
                pool.terminate()
            except Exception:
                pass  # interpreter shutdown: modules already torn down
