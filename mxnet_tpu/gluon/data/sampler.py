"""Samplers (reference: ``python/mxnet/gluon/data/sampler.py``)."""
from __future__ import annotations

import math
import random as _pyrandom
from typing import Iterator, List

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler",
           "IntervalSampler", "FilterSampler"]


class Sampler:
    def __iter__(self) -> Iterator:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length: int, start: int = 0) -> None:
        self._length = length
        self._start = start

    def __iter__(self):
        return iter(range(self._start, self._start + self._length))

    def __len__(self) -> int:
        return self._length


class RandomSampler(Sampler):
    def __init__(self, length: int) -> None:
        self._length = length

    def __iter__(self):
        indices = list(range(self._length))
        _pyrandom.shuffle(indices)
        return iter(indices)

    def __len__(self) -> int:
        return self._length


class IntervalSampler(Sampler):
    def __init__(self, length: int, interval: int, rollover: bool = True) -> None:
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        starts = range(self._interval) if self._rollover else [0]
        for start in starts:
            for i in range(start, self._length, self._interval):
                yield i

    def __len__(self) -> int:
        return self._length


class FilterSampler(Sampler):
    def __init__(self, fn, dataset) -> None:
        self._indices = [i for i in range(len(dataset)) if fn(dataset[i])]

    def __iter__(self):
        return iter(self._indices)

    def __len__(self) -> int:
        return len(self._indices)


class BatchSampler(Sampler):
    """Wrap a sampler into batches; last_batch in {'keep','discard',
    'rollover'} (reference semantics)."""

    def __init__(self, sampler: Sampler, batch_size: int,
                 last_batch: str = "keep") -> None:
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev: List[int] = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "discard":
                return
            elif self._last_batch == "rollover":
                self._prev = batch
            else:
                raise ValueError(
                    f"last_batch must be keep/discard/rollover, "
                    f"got {self._last_batch}")

    def __len__(self) -> int:
        if self._last_batch == "keep":
            return math.ceil(len(self._sampler) / self._batch_size)
        if self._last_batch == "discard":
            return len(self._sampler) // self._batch_size
        return (len(self._sampler) + len(self._prev)) // self._batch_size
