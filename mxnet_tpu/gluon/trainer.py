"""Trainer — applies an optimizer over a block's parameters.

Reference parity (leezu/mxnet): ``python/mxnet/gluon/trainer.py`` — kvstore
wiring (``update_on_kvstore`` decision, ``allreduce_grads``), per-param
fused optimizer updates, ``save_states/load_states`` exact-resume.

Design (tpu-first): data-parallel gradient reduction happens either through
a KVStore ('device'/'ici' → psum over the mesh, see ``kvstore.py``) or is a
no-op on one chip. Parameters keep a single (possibly sharded) buffer, so
there is no per-device copy fan-out to manage.
"""
from __future__ import annotations

import io
import pickle
import weakref
from typing import Any, Dict, List, Optional, Sequence, Union

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import optimizer as opt
from .. import tracing as _tracing
from .parameter import Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params: Any, optimizer: Union[str, "opt.Optimizer"],
                 optimizer_params: Optional[Dict[str, Any]] = None,
                 kvstore: Union[str, Any, None] = "device",
                 compression_params: Optional[Dict[str, Any]] = None,
                 update_on_kvstore: Optional[bool] = None) -> None:
        if isinstance(params, dict):
            named = list(params.items())
        elif isinstance(params, (list, tuple)):
            named = [(getattr(p, "name", str(i)), p)
                     for i, p in enumerate(params)]
        else:
            raise MXNetError(
                "Trainer expects a ParameterDict (from collect_params()) or "
                f"a list of Parameters, got {type(params)}")
        for _, p in named:
            if not isinstance(p, Parameter):
                raise MXNetError(f"non-Parameter {p!r} passed to Trainer")
        from .parameter import dedupe_shared
        self._param_names, self._params = dedupe_shared(named)
        self._params_to_init: List[Parameter] = []

        optimizer_params = optimizer_params or {}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params:
                raise MXNetError("optimizer_params must be None when "
                                 "optimizer is an Optimizer instance")
            self._optimizer = optimizer
        else:
            self._optimizer = opt.create(optimizer, **optimizer_params)
        # param_dict drives lr_mult/wd_mult lookups by index
        self._optimizer.param_dict = dict(enumerate(self._params))

        self._states: Dict[int, Any] = {}
        self._kvstore_arg = kvstore
        self._compression_params = compression_params
        self._update_on_kvstore_arg = update_on_kvstore
        self._update_on_kvstore = False
        self._kvstore = None
        self._kv_initialized = False
        self._scale = 1.0
        # event-driven gradient streaming (per-layer backward overlap):
        # the round armed for the NEXT step, its planned grad wrappers,
        # the per-key staging buffers reduced values land in, and the
        # dirty latch a second backward-before-step trips
        self._stream_round = None
        self._stream_vals: Dict[int, Any] = {}
        self._stream_bufs: Dict[int, Any] = {}
        self._stream_staging: Dict[int, NDArray] = {}
        self._stream_dirty = False
        self._stream_cbs_installed = False

    # -- kvstore ------------------------------------------------------------
    def _init_kvstore(self) -> None:
        from .. import kvstore as kvs
        if self._kvstore_arg is None:
            self._kvstore = None
        elif isinstance(self._kvstore_arg, str):
            self._kvstore = kvs.create(self._kvstore_arg)
        else:
            self._kvstore = self._kvstore_arg
        if self._kvstore is not None and self._compression_params:
            self._kvstore.set_gradient_compression(self._compression_params)
        # update_on_kvstore (reference trainer.py decision): explicit
        # argument wins; default True only for the async parameter
        # service, whose whole point is server-side updates. The store
        # then owns weights AND optimizer — ship both.
        if self._kvstore is not None:
            auto = getattr(self._kvstore, "type", "") == "dist_async"
            if auto and self._update_on_kvstore_arg is not None \
                    and not self._update_on_kvstore_arg:
                # the async service has no worker-count-aware per-round
                # aggregation: without a server-side optimizer, pulls
                # return running gradient SUMS since init, not per-step
                # reductions — reject rather than silently mistrain
                raise MXNetError(
                    "kvstore='dist_async' requires updates on the "
                    "kvstore (the server applies the optimizer per "
                    "push); update_on_kvstore=False is not supported — "
                    "use kvstore='ici' for worker-side updates")
            self._update_on_kvstore = (auto
                                       if self._update_on_kvstore_arg is None
                                       else bool(self._update_on_kvstore_arg))
        if self._update_on_kvstore:
            # For a SHARED remote store (dist_async: one server-side copy)
            # rank 0 alone seeds weights and ships the optimizer, THEN
            # everyone crosses the barrier — a later init would race and a
            # later set_optimizer would reset server momentum. Per-process
            # stores (local/device/ici) hold per-rank state: every rank
            # must seed its own copy and updater.
            shared = getattr(self._kvstore, "type", "") == "dist_async"
            if not shared or getattr(self._kvstore, "rank", 0) == 0:
                for i, p in enumerate(self._params):
                    if p.grad_req != "null" and p.is_initialized:
                        self._kvstore.init(i, p.data())
                self._kvstore.set_optimizer(self._optimizer)
            if shared and hasattr(self._kvstore, "barrier"):
                self._kvstore.barrier()
                # EVERY rank starts from the server's seeded weights
                # (the reference broadcasts initial params via kvstore
                # init + pull) — without this, ranks > 0 would compute
                # their first gradient at their own local random init,
                # pushing updates unrelated to the served model
                keys = [i for i, p in enumerate(self._params)
                        if p.grad_req != "null" and p.is_initialized]
                if keys:
                    self._kvstore.pull(
                        keys, out=[self._params[i].data() for i in keys])
        self._kv_initialized = True

    @property
    def learning_rate(self) -> float:
        return self._optimizer.learning_rate

    @property
    def optimizer(self) -> "opt.Optimizer":
        return self._optimizer

    def set_learning_rate(self, lr: float) -> None:
        self._optimizer.set_learning_rate(lr)

    # -- core step ----------------------------------------------------------
    def _overlap_enabled(self) -> bool:
        """Overlapped (bucketed, priority-scheduled, comm-thread)
        gradient reduction — MXNET_KV_OVERLAP (default on), engaged
        only when the store has an actual wire to hide: a
        multi-process collective store, the dist_async parameter
        service, or the synthetic-slow-wire knob.  A single-process
        'local'/'device' store's reduction is a pure no-op — routing
        it through the comm thread would add cross-thread handshakes
        per step for nothing.  See kvstore_sched.py and
        docs/performance.md 'Overlapped collectives'."""
        from ..base import getenv
        kv = self._kvstore
        if kv is None or int(getenv("MXNET_KV_OVERLAP", 1)) == 0:
            return False
        if float(getenv("MXNET_KV_SYNTH_WIRE_GBPS", 0.0)) > 0:
            return True
        ktype = getattr(kv, "type", "")
        if ktype == "dist_async":
            return True
        if ktype in ("ici", "dist", "dist_sync", "dist_device_sync",
                     "dist_sync_device", "horovod"):
            try:
                import jax
                return jax.process_count() > 1
            except Exception:   # noqa: BLE001 - no backend yet
                return False
        return False

    def _push_with_recovery(self, keys, grads, priority=0,
                            reserved_seqs=None) -> None:
        """One kvstore push with the restarted-empty-server recovery
        (shared by the serialized path and the scheduler's per-bucket
        comm-thread dispatch)."""
        kw = {}
        if reserved_seqs is not None:
            kw["_reserved_seqs"] = reserved_seqs
        try:
            self._kvstore.push(keys, grads, priority, **kw)
        except MXNetError as e:
            if not (getattr(self._kvstore, "type", "") == "dist_async"
                    and "uninitialized" in str(e)):
                raise
            # a parameter server restarted with empty state: resume
            # from this worker's current weights (pulled from the
            # server at most one step ago) and re-ship the optimizer.
            # Server-side momentum resets — announce it.
            import warnings
            warnings.warn(
                "parameter server lost its state (restart?) — "
                "re-seeding from this worker's current weights; "
                "server-side optimizer state resets")
            # re-seed the FULL key set _init_kvstore seeds, not just
            # the keys in this push: with ignore_stale_grad, params
            # whose grads are stale right now would otherwise stay
            # uninitialized on the restarted server and re-trigger
            # this recovery (resetting momentum) on every later push
            for i, p in enumerate(self._params):
                if p.grad_req != "null" and p.is_initialized:
                    self._kvstore.init(i, p.data())
            self._kvstore.set_optimizer(self._optimizer)
            self._kvstore.push(keys, grads, priority)

    def allreduce_grads(self, ignore_stale_grad: bool = False) -> None:
        """Sum gradients across data-parallel workers (kvstore push+pull).

        Gradients are fully reduced when this returns — the documented
        allreduce_grads -> inspect/modify grads -> update() pattern
        stays valid under the overlapped scheduler (``step()`` uses
        the internal async variant, where the per-parameter waits move
        into the optimizer update so wire time hides under compute).

        With a sharded SPMD train step this is a no-op: the psum is inside
        the compiled program (kvstore='ici' path, SURVEY.md section 3.5 TPU
        MAPPING)."""
        self._allreduce_grads_async(ignore_stale_grad)
        rnd = getattr(self, "_sched_round", None)
        if rnd is not None:
            # called directly (not via step): honor the public
            # contract — drain the round before handing grads back
            self._sched_round = None
            try:
                streamed = getattr(rnd, "_streaming", False)
                for b in rnd.buckets:
                    rnd.wait(b)
                    if streamed:
                        self._absorb_streamed(b)
            except BaseException:
                rnd.abort()
                raise
            rnd.finish()

    def _allreduce_grads_async(self, ignore_stale_grad: bool = False) \
            -> None:
        """The scheduler-aware reduction ``step()`` drives.

        With MXNET_KV_OVERLAP=1 (default) and a real wire, the
        reduction is bucketed (MXNET_KV_BUCKET_BYTES, composition
        fixed by parameter registration order) and dispatched on the
        scheduler's comm thread in priority order
        (priority=-param_index: the params the next forward needs
        first reduce first); ``self._sched_round`` is left pending and
        the optimizer update for a parameter blocks only on ITS
        bucket, so wire time hides under the remaining
        backward/update compute.  Grads are NOT yet reduced when this
        returns — ``_update`` (or the public wrapper above) consumes
        the round."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is None:
            return
        keys, grads = [], []
        for i, p in enumerate(self._params):
            if p.grad_req != "null" and p.is_initialized:
                g = p.data().grad
                if self._update_on_kvstore and \
                        not p.data()._fresh_grad:
                    # same stale-grad contract as the local _update path:
                    # never push (and server-apply) a gradient backward
                    # did not refresh this step
                    if ignore_stale_grad:
                        continue
                    raise MXNetError(
                        f"Gradient of Parameter '{p.name}' has not been "
                        "updated by backward since the last step — wrap "
                        "the forward in autograd.record() or pass "
                        "ignore_stale_grad=True")
                if getattr(g, "stype", "default") == "row_sparse":
                    if self._update_on_kvstore:
                        raise MXNetError(
                            f"Parameter '{p.name}' has a row_sparse "
                            "gradient, which the server-side update "
                            "path does not support — use a dense "
                            "gradient or update_on_kvstore=False")
                    # row-sparse grads skip the dense allreduce round-trip;
                    # multi-worker aggregation uses row_sparse_pull
                    # semantics (reference: Trainer._row_sparse_pull)
                    continue
                keys.append(i)
                grads.append(g)
        if not keys:
            return
        # reference trainer.py semantics: priority = -param_index, so
        # the parameters the next forward consumes first reduce first
        prios = [-i for i in keys]
        if self._overlap_enabled():
            # an armed streaming round (grad-ready hooks fed it during
            # backward) becomes this step's scheduled round; a dirty or
            # mismatched one is discarded and re-reduced fresh
            if not self._update_on_kvstore and \
                    self._consume_stream(keys, grads):
                return
            self._allreduce_scheduled(keys, grads, prios)
            return
        # overlap got disabled between arming and this step: any armed
        # round only ever touched staging — drop it before serializing
        self._discard_stream()
        # serialized path: one batched push (KVStoreICI fuses the small
        # gradients into bucket collectives instead of one per param),
        # then one batched pull — wire time adds to step time
        self._push_with_recovery(keys, grads, prios)
        if self._update_on_kvstore:
            # the store applied the optimizer — pull WEIGHTS back and
            # mark grads consumed; _update is skipped
            ws = [self._params[i].data() for i in keys]
            self._kvstore.pull(keys, out=ws)
            for i in keys:
                self._params[i].data()._fresh_grad = False
        else:
            self._kvstore.pull(keys, out=grads)

    def _allreduce_scheduled(self, keys, grads, prios) -> None:
        """Submit the gradient set to the bucketed comm-thread
        scheduler.  Worker-side-update stores leave the round pending
        for ``_update`` to consume bucket by bucket (the overlap);
        server-side-update stores (dist_async) pull each bucket's
        WEIGHTS back on the comm thread and drain here — bucketed,
        priority-ordered, replay-safe sends, with the per-bucket seqs
        reserved at enqueue."""
        from .. import kvstore_sched as _ks
        kv = self._kvstore
        # a round left over from an aborted step (exception between
        # allreduce and update) must drain before its grad arrays are
        # re-submitted — finish() cancels queued buckets and re-raises
        # any reduce error the aborted step never consumed
        stale = getattr(self, "_sched_round", None)
        if stale is not None:
            self._sched_round = None
            stale.finish()
        if self._update_on_kvstore:
            prepare = None
            if hasattr(kv, "reserve_push_seqs"):
                def prepare(bucket):
                    bucket.ctx["seqs"] = kv.reserve_push_seqs(
                        bucket.keys,
                        [int(v.size) for v in bucket.vals])

            def reduce_fn(bucket):
                self._push_with_recovery(
                    bucket.keys, bucket.vals, bucket.priority,
                    reserved_seqs=bucket.ctx.get("seqs"))
                ws = [self._params[i].data() for i in bucket.keys]
                kv.pull(bucket.keys, out=ws)

            rnd = _ks.submit(keys, grads, prios, reduce_fn,
                             prepare_fn=prepare)
            try:
                for b in rnd.buckets:
                    rnd.wait(b)
                for i in keys:
                    self._params[i].data()._fresh_grad = False
            except BaseException:
                # drain without raising: a secondary bucket error must
                # not mask the one already propagating
                rnd.abort()
                raise
            rnd.finish()
            return

        def reduce_fn(bucket):
            self._push_with_recovery(bucket.keys, bucket.vals,
                                     bucket.priority)
            kv.pull(bucket.keys, out=bucket.vals)

        self._sched_round = _ks.submit(
            keys, grads, prios, reduce_fn,
            strict_order=self._strict_collective_order())

    # -- event-driven streaming (per-layer backward overlap) ----------------
    def _stream_enabled(self) -> bool:
        """The grad-ready streaming path (ISSUE 15): engages exactly
        where the scheduled worker-side path would, minus the cases
        whose contracts it cannot keep — server-side updates apply the
        optimizer AT push (a streamed push is an uncancellable training
        update, so a second backward before step would corrupt it),
        strict-order collective stores need rank-identical dispatch
        sequences (seal order is readiness timing), gradient
        compression mutates per-key error-feedback residuals AT push
        (a dirty round's discarded pushes would leave the residuals
        advanced, and the fallback re-reduction would compress the
        same keys twice in one step — compressed trainers keep the
        step-time submission, where every key compresses exactly
        once), and armed fault plans corrupt gradients at the
        trainer.step site, which must happen BEFORE anything reaches
        the wire."""
        from ..base import getenv
        from .. import faults as _faults
        return (self._overlap_enabled()
                and not self._update_on_kvstore
                and not self._strict_collective_order()
                and not self._compression_params
                and not getattr(self._kvstore, "_compression", None)
                and int(getenv("MXNET_KV_BACKWARD_STREAM", 1)) != 0
                and not _faults._ARMED)

    def _arm_stream(self) -> None:
        """Open next step's streaming round and install the grad-ready
        hooks: backward will ``Round.offer`` each parameter as its
        gradient finalizes, sealing and dispatching reduction buckets
        while the rest of backward still runs.  Re-armed every step —
        cheap (one pass over the params), and it self-heals across
        parameter re-binds, env flips, and fault-plan arming."""
        stale, self._stream_round = self._stream_round, None
        if stale is not None:
            # a skipped/aborted step never consumed its round; sealed
            # buckets only ever reduced into staging, so discarding is
            # free of user-visible effects
            stale.abort()
        self._stream_dirty = False
        if not self._stream_enabled():
            if self._stream_cbs_installed:
                for p in self._params:
                    p.set_grad_ready_cb(None)
                self._stream_cbs_installed = False
            return
        keys, vals, prios = [], [], []
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or not p.is_initialized:
                continue
            if getattr(p, "grad_stype", "default") != "default":
                continue   # row-sparse grads never join dense rounds
            g = p.data().grad
            if g is None or getattr(g, "stype", "default") != "default":
                continue
            keys.append(i)
            vals.append(g)
            prios.append(-i)
        if not keys:
            return
        import jax.numpy as jnp
        staging = self._stream_staging
        for i in keys:
            if i not in staging:
                # a shell for kvstore.pull to rebind — never read until
                # the pull of its bucket landed
                staging[i] = NDArray(jnp.zeros((1,), "float32"),
                                     _wrap=True)
        wself = weakref.ref(self)

        def reduce_fn(bucket):
            tr = wself()
            if tr is None:
                raise MXNetError(
                    "trainer was garbage-collected with a streaming "
                    "gradient-reduction round in flight")
            tr._push_with_recovery(bucket.keys, bucket.vals,
                                   bucket.priority)
            tr._kvstore.pull(
                bucket.keys,
                out=[tr._stream_staging[k] for k in bucket.keys])

        from .. import kvstore_sched as _ks
        self._stream_round = _ks.open_round(keys, vals, prios, reduce_fn)
        self._stream_vals = dict(zip(keys, vals))
        self._stream_bufs = {}

        def make_cb(k):
            def _cb(_arr):
                tr = wself()
                if tr is not None:
                    tr._stream_offer(k)
            return _cb

        keyset = set(keys)
        for i, p in enumerate(self._params):
            p.set_grad_ready_cb(make_cb(i) if i in keyset else None)
        self._stream_cbs_installed = True

    def _stream_offer(self, key: int) -> None:
        """The grad-ready hook body (fires inside backward)."""
        rnd = self._stream_round
        if rnd is None:
            return
        p = self._params[key]
        cur = p._data._grad if p._data is not None else None
        if cur is not self._stream_vals.get(key):
            # the grad wrapper was rebound since arming (a row_sparse
            # cotangent materialized, attach_grad re-ran): the planned
            # value is stale — poison the round, step re-reduces fresh
            self._stream_dirty = True
            return
        if not rnd.offer(key):
            self._stream_dirty = True
            return
        # snapshot the grad's raw buffer: the value that streams is the
        # one backward wrote, and any later rebind (user clipping/
        # scaling between backward and step, zero_grad) must invalidate
        # the round or the modification would be silently discarded
        self._stream_bufs[key] = cur._buf

    def _discard_stream(self) -> None:
        """Drop an armed streaming round (never raising): sealed
        buckets only ever reduced into staging, so there is nothing to
        undo."""
        rnd, self._stream_round = self._stream_round, None
        if rnd is not None:
            rnd.abort()
        self._stream_dirty = False

    def _consume_stream(self, keys, grads) -> bool:
        """At step time: adopt the armed streaming round as this step's
        ``_sched_round`` when it is still sound — otherwise discard it
        (sealed buckets only touched staging) and let the caller run a
        fresh post-backward reduction of the accumulated gradients."""
        rnd, self._stream_round = self._stream_round, None
        if rnd is None:
            return False
        dirty, self._stream_dirty = self._stream_dirty, False
        if dirty or self._update_on_kvstore:
            rnd.abort()
            return False
        actual = set(keys)
        if not actual <= set(rnd.planned_keys):
            rnd.abort()   # a parameter initialized after arming
            return False
        for k, g in zip(keys, grads):
            if self._stream_vals.get(k) is not g:
                rnd.abort()
                return False
            buf = self._stream_bufs.get(k)
            if buf is not None and g._buf is not buf:
                # the grad VALUE was rebound after it streamed (user
                # clipped/scaled it between backward and step): the
                # wire carries the pre-modification value — discard
                # and re-reduce the current gradients
                rnd.abort()
                return False
        rnd.seal_remaining(actual)
        self._sched_round = rnd
        return True

    def _absorb_streamed(self, bucket) -> None:
        """Move one reduced bucket from staging into the user-visible
        grad buffers (called after waiting the bucket): after step, a
        parameter's ``.grad`` holds the reduced gradient exactly as the
        non-streaming paths leave it."""
        with _tracing.child_span("bucket.absorb",
                                 keys=len(bucket.keys)):
            for k, v in zip(bucket.keys, bucket.vals):
                p = self._params[k]
                g = p._data._grad if p._data is not None else None
                s = self._stream_staging.get(k)
                if g is v and s is not None:
                    g._data = s._data

    def _strict_collective_order(self) -> bool:
        """Multi-process collective stores need every rank to issue the
        identical reduction sequence — the scheduler must dispatch in
        pure priority order, never readiness order (readiness timing
        differs per rank and a mismatched collective sequence deadlocks
        the job)."""
        if getattr(self._kvstore, "type", "") not in (
                "ici", "dist", "dist_sync", "dist_device_sync",
                "dist_sync_device", "horovod"):
            return False
        try:
            import jax
            return jax.process_count() > 1
        except Exception:   # noqa: BLE001 - no backend: stay safe
            return True

    def step(self, batch_size: int, ignore_stale_grad: bool = False) -> None:
        """Rescale grads by 1/batch_size and apply one optimizer update."""
        import time
        from .. import faults as _faults
        from .. import metrics as _metrics
        if _faults._ARMED:
            self._fault_site()
        t0 = time.perf_counter()
        try:
            # per-step root span: reduction buckets (seal/dispatch/
            # wire/absorb), PS-side handling, and optimizer updates
            # all land as children in this trace
            with _tracing.span("trainer.step", batch_size=batch_size):
                self._step_impl(batch_size, ignore_stale_grad)
        finally:
            _metrics.TRAINER_STEP_SECONDS.observe(time.perf_counter() - t0)

    def _fault_site(self) -> None:
        """The ``trainer.step`` chaos site: ``kind=nan`` corrupts the
        first fresh gradient BEFORE the reduction/update (and before
        any health-guard check), so the sentry's recovery schedule
        replays deterministically from ``MXNET_FAULT_PLAN``."""
        from .. import faults as _faults
        target = None
        for p in self._params:
            if p.grad_req != "null" and p.is_initialized:
                w = p.data()
                if w.grad is not None and w._fresh_grad:
                    target = w
                    break
        if target is None:
            _faults.maybe_fault("trainer.step")
            return
        out = _faults.maybe_corrupt("trainer.step", [target.grad._data])
        if out[0] is not target.grad._data:
            from ..ndarray.ndarray import from_jax
            target._grad = from_jax(out[0])

    def _step_impl(self, batch_size: int, ignore_stale_grad: bool) -> None:
        self._optimizer.rescale_grad = self._scale / batch_size
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore and \
                hasattr(self._kvstore, "update_optimizer_params"):
            # the worker-side optimizer never runs _update, so advance
            # its schedule clock here or lr_scheduler(num_update) would
            # stay frozen at step 0 forever
            self._optimizer.num_update += 1
            # live hyperparams (lr schedule, loss-scale rescale, wd) must
            # reach the server-side optimizer without resetting its state
            self._kvstore.update_optimizer_params({
                "learning_rate": float(self._optimizer.learning_rate),
                "rescale_grad": float(self._optimizer.rescale_grad),
                "wd": float(self._optimizer.wd)})
        # the async variant: a scheduled round stays pending so
        # _update's per-bucket waits overlap wire with update compute
        self._allreduce_grads_async(ignore_stale_grad)
        if not self._update_on_kvstore:
            self._update(ignore_stale_grad)
        # arm the NEXT step's streaming round: its grad-ready hooks
        # will stream buckets onto the wire during the next backward
        self._arm_stream()

    def update(self, batch_size: int, ignore_stale_grad: bool = False) -> None:
        """Apply the optimizer without gradient reduction (caller already
        reduced, e.g. Horovod-style)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "update() cannot be used when updates run on the kvstore "
                "(update_on_kvstore=True) — use step()")
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad: bool = False) -> None:
        updatable = []
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or not p.is_initialized:
                continue
            w = p.data()
            g = w.grad
            if g is None or not w._fresh_grad:
                if ignore_stale_grad:
                    continue
                raise MXNetError(
                    f"Gradient of Parameter `{p.name}` has not been updated "
                    f"by backward since the last step — run backward() "
                    f"inside autograd.record() first, or pass "
                    f"ignore_stale_grad=True")
            if i not in self._states:
                self._states[i] = \
                    self._optimizer.create_state_multi_precision(i, w)
            updatable.append((i, w, g))
        # Both update paths (per-param Optimizer.update and the fused
        # group below) donate weight/state buffers into jitted programs:
        # any pending bulked segment still holding one of those buffers
        # BY VALUE must materialize before the donation deletes it.
        # Targeted (flush_holding, not flush_all): a segment that never
        # captured a donated buffer — the prefetch thread's in-build
        # preprocessing — keeps building.
        import jax as _jax
        from .. import bulk as _bulk
        donated = [w._data for _, w, _ in updatable]
        for i, _, _ in updatable:
            donated.extend(_jax.tree_util.tree_leaves(self._states[i]))
        _bulk.flush_holding(donated, "mutation")
        rnd = getattr(self, "_sched_round", None)
        if rnd is not None:
            # overlapped reduction: walk buckets in registration order
            # (composition IS registration-contiguous), waiting only on
            # the bucket whose parameters update next — the wire for
            # later buckets keeps running under this compute.  Params
            # outside the round (row_sparse grads reduced elsewhere)
            # update in a final chunk.
            self._sched_round = None
            try:
                done = set()
                streamed = getattr(rnd, "_streaming", False)

                def chunk(b):
                    if streamed:
                        # a streamed bucket reduced into staging —
                        # land it in the user-visible grad buffers
                        # before the optimizer reads them
                        self._absorb_streamed(b)
                    members = set(b.keys)
                    done.update(members)
                    self._update_entries(
                        [t for t in updatable if t[0] in members])

                if self._fused_optimizer_ok():
                    # per-param updates are order-independent for
                    # functional optimizers: consume buckets as they
                    # ARRIVE, updating early winners while later
                    # buckets are still on the wire
                    for b in rnd.as_completed():
                        chunk(b)
                else:
                    # order-sensitive optimizers (eager RNG noise in
                    # update, e.g. SGLD) keep registration order so
                    # replays stay deterministic
                    for b in rnd.buckets:
                        rnd.wait(b)
                        chunk(b)
                self._update_entries(
                    [t for t in updatable if t[0] not in done])
            except BaseException:
                # drain without raising: a secondary bucket error must
                # not mask the one already propagating
                rnd.abort()
                raise
            rnd.finish()
        else:
            self._update_entries(updatable)
        for _, w, _ in updatable:
            w._fresh_grad = False

    def _update_entries(self, updatable) -> None:
        """Apply the optimizer to one list of (idx, weight, grad)
        entries — the fused-group batching below is unchanged from the
        pre-scheduler path, it just runs per bucket now."""
        if not updatable:
            return
        with _tracing.child_span("optimizer.update",
                                 params=len(updatable)):
            self._update_entries_impl(updatable)

    def _update_entries_impl(self, updatable) -> None:
        agg = self._optimizer.aggregate_num
        if len(updatable) > 1 and agg > 1 and self._fused_optimizer_ok():
            # reference semantics: MXNET_OPTIMIZER_AGGREGATION_SIZE bounds
            # the number of parameters per fused update batch. Params that
            # can't fuse (row_sparse grads, fp32 master weights) take the
            # per-param path WITHOUT disabling fusion for the dense
            # majority in mixed models.
            fusible, rest = [], []
            for t in updatable:
                (fusible if self._param_fusible(t) else rest).append(t)
            if len(fusible) < 2:
                fusible, rest = [], updatable
            for k in range(0, len(fusible), agg):
                group = fusible[k:k + agg]
                if len(group) > 1:
                    self._fused_update(group)
                else:
                    i, w, g = group[0]
                    self._states[i] = \
                        self._optimizer.update_multi_precision(
                            i, w, g, self._states[i])
        else:
            rest = updatable
        for i, w, g in rest:
            self._states[i] = self._optimizer.update_multi_precision(
                i, w, g, self._states[i])

    def _fused_optimizer_ok(self) -> bool:
        """Optimizers fully described by the functional ``_step`` core can
        fuse; ones that override ``update``/``update_multi_precision``
        (e.g. SGLD's eager Langevin noise) must take the per-param path."""
        cls = type(self._optimizer)
        return not (cls._step is opt.Optimizer._step or
                    cls.update is not opt.Optimizer.update or
                    cls.update_multi_precision is not
                    opt.Optimizer.update_multi_precision)

    def _param_fusible(self, t) -> bool:
        """Dense params without fp32-master-weight state can join a fused
        update group."""
        i, w, g = t
        return (getattr(g, "stype", "default") != "row_sparse" and
                not isinstance(self._states[i], opt.MasterWeightState))

    _HYPER_CACHE_CAP = 512

    def _committed_hypers(self, lrs, wds, rescale, clip):
        """Value-keyed LRU of committed device hyperparameter arrays.

        The fused update used to build fresh ``jnp.asarray`` host arrays
        for lr/wd/rescale/clip EVERY step — on a remote accelerator
        backend each varying-value host argument pays the slow
        uncommitted-argument dispatch path per call (the same plateau
        ``SPMDTrainer._committed_scalar`` exists for).  Hyperparameters
        revisit a small value set (constant, or a cyclic schedule), so
        an LRU by value makes the steady state zero-transfer."""
        import jax.numpy as jnp
        from .. import engine
        key = (tuple(lrs), tuple(wds), float(rescale), float(clip))
        cache = getattr(self, "_hyper_cache", None)
        if cache is None:
            from collections import OrderedDict
            cache = self._hyper_cache = OrderedDict()
        hit = cache.get(key)
        if hit is None:
            hit = tuple(engine.launder(
                [jnp.asarray(lrs, jnp.float32),
                 jnp.asarray(wds, jnp.float32),
                 jnp.float32(rescale), jnp.float32(clip)]))
            cache[key] = hit
            if len(cache) > self._HYPER_CACHE_CAP:
                cache.popitem(last=False)
        else:
            cache.move_to_end(key)
        return hit

    def _fused_ts(self, key, ts):
        """Device-resident per-group schedule clock.  The counts
        increment every step, so a host-built array would never cache —
        instead the fused program returns ``ts + 1`` and the device copy
        advances in-program; the host-side expected-value check resyncs
        after ``load_states``/rewind (and a skipped update, which never
        calls this, leaves both sides untouched)."""
        import jax.numpy as jnp
        from .. import engine
        expected = tuple(float(t) for t in ts)
        clock = getattr(self, "_fused_clock", None)
        if clock is None:
            clock = self._fused_clock = {}
        hit = clock.get(key)
        if hit is not None and hit[1] == expected:
            return hit[0]
        return engine.launder([jnp.asarray(ts, jnp.float32)])[0]

    def _fused_update(self, group) -> None:
        """One compiled program applying a group of parameter updates —
        the TPU-native form of the reference's multi-tensor ops
        (``multi_sgd_mom_update`` etc.): XLA fuses the group's update
        sweep into one dispatch."""
        import jax
        import jax.numpy as jnp
        o = self._optimizer
        cls = type(o)
        lrs, wds, ts = [], [], []
        for i, w, g in group:
            o._update_count(i)
            lrs.append(o._get_lr(i))
            wds.append(o._get_wd(i))
            ts.append(o._index_update_count[i])
        key = (cls, o.clip_gradient is not None,
               tuple((i, tuple(w.shape), str(w.dtype), o._hyper(i))
                     for i, w, _ in group))
        cache = getattr(self, "_fused_cache", None)
        if cache is None:
            cache = self._fused_cache = {}
        fn = cache.get(key)
        if fn is None:
            has_clip = o.clip_gradient is not None
            hps = [o._hyper(i) for i, _, _ in group]

            def raw(ws, gs, sts, lrs_, wds_, ts_, rescale_, clip_):
                new_ws, new_sts = [], []
                for k, (w, g, st) in enumerate(zip(ws, gs, sts)):
                    g = g.astype(jnp.float32) if w.dtype != g.dtype else g
                    g = g * rescale_
                    if has_clip:
                        g = jnp.clip(g, -clip_, clip_)
                    nw, ns = cls._step(w, g, st, lrs_[k], wds_[k], ts_[k],
                                       hps[k])
                    new_ws.append(nw)
                    new_sts.append(ns)
                # the schedule clock advances IN-PROGRAM (fed back as
                # the next step's ts_): the loop never ships a fresh
                # varying-value host array per step
                return new_ws, new_sts, ts_ + 1.0

            fn = cache[key] = jax.jit(raw, donate_argnums=(0, 2, 5))
        clip = o.clip_gradient if o.clip_gradient is not None else 0.0
        lrs_a, wds_a, rescale_a, clip_a = self._committed_hypers(
            lrs, wds, o.rescale_grad, clip)
        new_ws, new_sts, ts_next = fn(
            [w._data for _, w, _ in group],
            [g._data for _, _, g in group],
            [self._states[i] for i, _, _ in group],
            lrs_a, wds_a, self._fused_ts(key, ts), rescale_a, clip_a)
        self._fused_clock[key] = (
            ts_next, tuple(float(t) + 1.0 for t in ts))
        from .. import engine
        for (i, w, _), nw, ns in zip(group, new_ws, new_sts):
            w._data = nw
            engine.track(nw)
            self._states[i] = ns

    def zero_grad(self) -> None:
        for p in self._params:
            p.zero_grad()

    # -- exact resume (reference: Trainer.save_states/load_states) ----------
    def save_states(self, fname: str) -> None:
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            if hasattr(self._kvstore, "save_optimizer_states"):
                # states live in the store (server-side for dist_async) —
                # the reference delegates in exactly this mode
                self._kvstore.save_optimizer_states(fname)
                return
        import numpy as _np
        import jax
        payload = {
            "format": 2,  # >=2: MasterWeightState pickles as its type
            "num_update": self._optimizer.num_update,
            "index_update_count": self._optimizer._index_update_count,
            "states": {
                i: jax.tree_util.tree_map(lambda a: _np.asarray(a), s)
                for i, s in self._states.items()},
        }
        with open(fname, "wb") as f:
            pickle.dump(payload, f)

    def load_states(self, fname: str) -> None:
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            if hasattr(self._kvstore, "load_optimizer_states"):
                self._kvstore.load_optimizer_states(fname)
                return
        import jax.numpy as jnp
        import jax
        import numpy as _np
        with open(fname, "rb") as f:
            payload = pickle.load(f)
        self._optimizer.num_update = payload["num_update"]
        self._optimizer._index_update_count = payload["index_update_count"]

        legacy = payload.get("format", 1) < 2

        def restore(i, s):
            # format<2 states stored the master-weight layout as a plain
            # (master, inner_state_tuple) tuple; rewrap so the typed
            # dispatch still routes them. The inner-is-a-tuple condition
            # distinguishes it from Adam-style (m, v) plain state (whose
            # second element is an array), and masters only ever exist
            # for non-fp32 weights.
            if legacy and self._optimizer.multi_precision and \
                    type(s) is tuple and len(s) == 2 and \
                    isinstance(s[0], _np.ndarray) and \
                    s[0].dtype == _np.float32 and \
                    isinstance(s[1], tuple) and \
                    i < len(self._params) and \
                    self._params[i].dtype != _np.float32 and \
                    tuple(s[0].shape) == tuple(self._params[i].shape):
                s = opt.MasterWeightState(s[0], s[1])
            return jax.tree_util.tree_map(jnp.asarray, s)

        self._states = {i: restore(i, s)
                        for i, s in payload["states"].items()}
