"""``mx.gluon`` — the imperative/hybrid neural network API.

Reference parity: ``python/mxnet/gluon/`` — Block/HybridBlock, Parameter,
Trainer, nn layers, losses, data, model_zoo, rnn, contrib.
"""
from .block import Block, HybridBlock, SymbolBlock
from .parameter import Parameter, Constant, DeferredInitializationError
from .trainer import Trainer
from . import nn
from . import loss
from .loss import Loss

_LAZY = {
    "data": ".data",
    "model_zoo": ".model_zoo",
    "rnn": ".rnn",
    "contrib": ".contrib",
    "utils": ".utils",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(_LAZY[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu.gluon' has no attribute {name!r}")


__all__ = ["Block", "HybridBlock", "SymbolBlock", "Parameter", "Constant",
           "DeferredInitializationError", "Trainer", "nn", "loss", "Loss",
           "data", "model_zoo", "rnn", "contrib", "utils"]
