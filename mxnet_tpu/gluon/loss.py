"""Loss blocks (reference: ``python/mxnet/gluon/loss.py``).

The full zoo: L1/L2, SoftmaxCrossEntropy, SigmoidBinaryCrossEntropy,
KLDiv, Huber, Hinge, SquaredHinge, Logistic, Triplet, Cosine, PoissonNLL,
CTC. Same weighting conventions: ``sample_weight`` broadcasting via
``_apply_weighting``, per-sample mean over non-batch axes.
"""
from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from .. import npx
from ..ndarray.ndarray import NDArray
from ..ndarray import ops
from ..ndarray.register import invoke
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SoftmaxCrossEntropyLoss",
           "SoftmaxCELoss", "SigmoidBinaryCrossEntropyLoss", "SigmoidBCELoss",
           "KLDivLoss", "HuberLoss", "HingeLoss", "SquaredHingeLoss",
           "LogisticLoss", "TripletLoss", "CosineEmbeddingLoss",
           "PoissonNLLLoss", "CTCLoss", "SDMLLoss"]


def _apply_weighting(loss: NDArray, weight: Optional[float],
                     sample_weight: Optional[NDArray]) -> NDArray:
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None and weight != 1.0:
        loss = loss * weight
    return loss


def _batch_mean(loss: NDArray, batch_axis: int) -> NDArray:
    axes = tuple(i for i in range(loss.ndim) if i != batch_axis)
    return loss.mean(axis=axes) if axes else loss


class Loss(HybridBlock):
    """Base loss block."""

    def __init__(self, weight: Optional[float] = 1.0, batch_axis: int = 0,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self) -> str:
        return (f"{type(self).__name__}(batch_axis={self._batch_axis}, "
                f"w={self._weight})")


class L2Loss(Loss):
    """0.5 * (pred - label)^2, mean over non-batch axes."""

    def forward(self, pred: NDArray, label: NDArray,
                sample_weight: Optional[NDArray] = None) -> NDArray:
        label = label.reshape(pred.shape)
        loss = ops.square(label - pred) * 0.5
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class L1Loss(Loss):
    def forward(self, pred: NDArray, label: NDArray,
                sample_weight: Optional[NDArray] = None) -> NDArray:
        label = label.reshape(pred.shape)
        loss = ops.abs(label - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class SoftmaxCrossEntropyLoss(Loss):
    """Softmax + CE fused for numerical stability (reference:
    SoftmaxCrossEntropyLoss; the fusion mirrors ``softmax_cross_entropy``)."""

    def __init__(self, axis: int = -1, sparse_label: bool = True,
                 from_logits: bool = False, weight: Optional[float] = 1.0,
                 batch_axis: int = 0, **kwargs: Any) -> None:
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def forward(self, pred: NDArray, label: NDArray,
                sample_weight: Optional[NDArray] = None) -> NDArray:
        if not self._from_logits:
            pred = npx.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -npx.pick(pred, label, axis=self._axis, keepdims=False)
        else:
            label = label.reshape(pred.shape)
            loss = -(pred * label).sum(axis=self._axis, keepdims=False)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid: bool = False,
                 weight: Optional[float] = 1.0, batch_axis: int = 0,
                 **kwargs: Any) -> None:
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred: NDArray, label: NDArray,
                sample_weight: Optional[NDArray] = None,
                pos_weight: Optional[NDArray] = None) -> NDArray:
        label = label.reshape(pred.shape)
        if not self._from_sigmoid:
            # max(x,0) - x*z + log(1+exp(-|x|)), the stable form
            def impl(x, z):
                base = jnp.maximum(x, 0) - x * z + \
                    jnp.log1p(jnp.exp(-jnp.abs(x)))
                return base
            loss = invoke("sigmoid_bce", impl, (pred, label))
            if pos_weight is not None:
                # rescale positive-term contribution
                lsig = npx.log_sigmoid(pred)
                extra = (pos_weight - 1) * label * (-lsig)
                loss = loss + extra
        else:
            eps = 1e-12
            one_m = (1.0 - pred + eps).log()
            if pos_weight is None:
                loss = -((pred + eps).log() * label + one_m * (1 - label))
            else:
                loss = -((pred + eps).log() * label * pos_weight
                         + one_m * (1 - label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits: bool = True, axis: int = -1,
                 weight: Optional[float] = 1.0, batch_axis: int = 0,
                 **kwargs: Any) -> None:
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred: NDArray, label: NDArray,
                sample_weight: Optional[NDArray] = None) -> NDArray:
        if not self._from_logits:
            pred = npx.log_softmax(pred, axis=self._axis)
        loss = label * ((label + 1e-12).log() - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class HuberLoss(Loss):
    def __init__(self, rho: float = 1.0, weight: Optional[float] = 1.0,
                 batch_axis: int = 0, **kwargs: Any) -> None:
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def forward(self, pred: NDArray, label: NDArray,
                sample_weight: Optional[NDArray] = None) -> NDArray:
        label = label.reshape(pred.shape)
        err = ops.abs(label - pred)
        rho = self._rho
        loss = ops.where(err > rho, err - 0.5 * rho,
                         (0.5 / rho) * ops.square(err))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class HingeLoss(Loss):
    def __init__(self, margin: float = 1.0, weight: Optional[float] = 1.0,
                 batch_axis: int = 0, **kwargs: Any) -> None:
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred: NDArray, label: NDArray,
                sample_weight: Optional[NDArray] = None) -> NDArray:
        label = label.reshape(pred.shape)
        loss = (self._margin - pred * label).clip(0.0, None)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class SquaredHingeLoss(HingeLoss):
    def forward(self, pred: NDArray, label: NDArray,
                sample_weight: Optional[NDArray] = None) -> NDArray:
        label = label.reshape(pred.shape)
        loss = ops.square((self._margin - pred * label).clip(0.0, None))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class LogisticLoss(Loss):
    def __init__(self, label_format: str = "signed",
                 weight: Optional[float] = 1.0, batch_axis: int = 0,
                 **kwargs: Any) -> None:
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def forward(self, pred: NDArray, label: NDArray,
                sample_weight: Optional[NDArray] = None) -> NDArray:
        label = label.reshape(pred.shape)
        if self._label_format == "binary":
            label = 2 * label - 1
        def impl(x):
            return jnp.log1p(jnp.exp(-jnp.abs(x))) + jnp.maximum(-x, 0)
        loss = invoke("logistic", impl, (pred * label,))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class TripletLoss(Loss):
    def __init__(self, margin: float = 1.0, weight: Optional[float] = 1.0,
                 batch_axis: int = 0, **kwargs: Any) -> None:
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred: NDArray, positive: NDArray,
                negative: NDArray,
                sample_weight: Optional[NDArray] = None) -> NDArray:
        axes = tuple(range(1, pred.ndim))
        d_pos = ops.square(pred - positive).sum(axis=axes)
        d_neg = ops.square(pred - negative).sum(axis=axes)
        loss = (d_pos - d_neg + self._margin).clip(0.0, None)
        return _apply_weighting(loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    def __init__(self, margin: float = 0.0, weight: Optional[float] = 1.0,
                 batch_axis: int = 0, **kwargs: Any) -> None:
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, input1: NDArray, input2: NDArray, label: NDArray,
                sample_weight: Optional[NDArray] = None) -> NDArray:
        margin = self._margin
        def impl(a, b, lab):
            a2 = a.reshape(a.shape[0], -1)
            b2 = b.reshape(b.shape[0], -1)
            cos = (a2 * b2).sum(-1) / (
                jnp.linalg.norm(a2, axis=-1) *
                jnp.linalg.norm(b2, axis=-1) + 1e-12)
            lab = lab.reshape(-1)
            return jnp.where(lab > 0, 1 - cos,
                             jnp.maximum(cos - margin, 0.0))
        loss = invoke("cosine_embedding", impl, (input1, input2, label))
        return _apply_weighting(loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, from_logits: bool = True,
                 compute_full: bool = False, weight: Optional[float] = 1.0,
                 batch_axis: int = 0, **kwargs: Any) -> None:
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def forward(self, pred: NDArray, target: NDArray,
                sample_weight: Optional[NDArray] = None,
                epsilon: float = 1e-8) -> NDArray:
        target = target.reshape(pred.shape)
        if self._from_logits:
            loss = pred.exp() - target * pred
        else:
            loss = pred - target * (pred + epsilon).log()
        if self._compute_full:
            import math
            # Stirling approximation of log(target!)
            stirling = (target * target.log() - target
                        + 0.5 * (2 * math.pi * target).log())
            loss = loss + ops.where(target > 1, stirling,
                                    ops.zeros_like(target))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean()


class CTCLoss(Loss):
    """Connectionist temporal classification (reference: warp-ctc-backed
    ``CTCLoss``). Implemented as a log-domain dynamic program over
    ``lax.scan`` — compiler-friendly, fully on device."""

    def __init__(self, layout: str = "NTC", label_layout: str = "NT",
                 weight: Optional[float] = 1.0, **kwargs: Any) -> None:
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def forward(self, pred: NDArray, label: NDArray,
                pred_lengths: Optional[NDArray] = None,
                label_lengths: Optional[NDArray] = None,
                sample_weight: Optional[NDArray] = None) -> NDArray:
        import jax
        from jax import lax
        layout = self._layout

        def impl(logits, labels, *lens):
            if layout == "TNC":
                logits = jnp.swapaxes(logits, 0, 1)  # -> NTC
            N, T, C = logits.shape
            L = labels.shape[1]
            logp = jax.nn.log_softmax(logits, axis=-1)
            blank = 0
            labels = labels.astype(jnp.int32)
            if lens:
                plen = lens[0].astype(jnp.int32)
                llen = lens[1].astype(jnp.int32) if len(lens) > 1 else \
                    jnp.full((N,), L, jnp.int32)
            else:
                plen = jnp.full((N,), T, jnp.int32)
                llen = (labels != blank).sum(axis=1).astype(jnp.int32) \
                    if True else jnp.full((N,), L, jnp.int32)
            # extended label seq: blank, l1, blank, l2, ... blank (2L+1)
            S = 2 * L + 1
            ext = jnp.full((N, S), blank, jnp.int32)
            ext = ext.at[:, 1::2].set(labels)
            neg_inf = -1e30
            alpha0 = jnp.full((N, S), neg_inf)
            alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
            alpha0 = alpha0.at[:, 1].set(
                jnp.take_along_axis(logp[:, 0, :], ext[:, 1:2], axis=1)[:, 0])
            same_as_prev2 = jnp.concatenate(
                [jnp.ones((N, 2), bool),
                 ext[:, 2:] == ext[:, :-2]], axis=1)

            def step(alpha, t):
                a_prev = alpha
                a1 = jnp.concatenate(
                    [jnp.full((N, 1), neg_inf), a_prev[:, :-1]], axis=1)
                a2 = jnp.concatenate(
                    [jnp.full((N, 2), neg_inf), a_prev[:, :-2]], axis=1)
                a2 = jnp.where(same_as_prev2, neg_inf, a2)
                merged = jnp.logaddexp(jnp.logaddexp(a_prev, a1), a2)
                emit = jnp.take_along_axis(logp[:, t, :], ext, axis=1)
                new_alpha = merged + emit
                # freeze past end-of-sequence
                new_alpha = jnp.where((t < plen)[:, None], new_alpha, a_prev)
                return new_alpha, None

            alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
            end = 2 * llen  # index of final blank
            last = jnp.take_along_axis(alpha, end[:, None], axis=1)[:, 0]
            last2 = jnp.take_along_axis(
                alpha, jnp.maximum(end - 1, 0)[:, None], axis=1)[:, 0]
            return -jnp.logaddexp(last, last2)

        inputs = [pred, label]
        if pred_lengths is not None:
            inputs.append(pred_lengths)
            if label_lengths is not None:
                inputs.append(label_lengths)
        loss = invoke("ctc_loss", impl, tuple(inputs))
        return _apply_weighting(loss, self._weight, sample_weight)


class SDMLLoss(Loss):
    """Smoothed deep metric learning loss (reference: gluon.loss.SDMLLoss,
    gluon-nlp era): two aligned embedding batches x1/x2 (N, d) where row
    i of each is a positive pair and every other row is an in-batch
    negative. Minimizes the KL divergence between smoothed identity
    labels and the softmax over negative pairwise L2 distances."""

    def __init__(self, smoothing_parameter: float = 0.3,
                 weight: float = 1.0, batch_axis: int = 0,
                 **kwargs: Any) -> None:
        super().__init__(weight, batch_axis, **kwargs)
        self._smoothing = float(smoothing_parameter)

    def forward(self, x1: NDArray, x2: NDArray) -> NDArray:
        from .. import numpy as mxnp
        N = x1.shape[0]
        # pairwise squared-L2 distance matrix (N, N)
        sq1 = ops.square(x1).sum(axis=1).reshape((N, 1))
        sq2 = ops.square(x2).sum(axis=1).reshape((1, N))
        dist = sq1 + sq2 - 2.0 * mxnp.matmul(x1, x2.T)
        # smoothed identity labels: diagonal mass 1-s, off-diag s/(N-1)
        s = self._smoothing
        eye = ops.eye(N, dtype=x1.dtype)
        labels = eye * (1.0 - s) + (1.0 - eye) * (s / max(N - 1, 1))
        log_prob = npx.log_softmax(-dist, axis=-1)
        # KL(labels || softmax(-dist)) including the constant label-
        # entropy term: gradients match cross-entropy, but the VALUES
        # match the reference's KLDivLoss-based implementation
        import math as _math
        if N > 1 and 0.0 < s < 1.0:
            label_entropy = ((1.0 - s) * _math.log(1.0 - s)
                             + s * _math.log(s / (N - 1)))
        else:
            label_entropy = 0.0
        loss = label_entropy - (labels * log_prob).sum(axis=1)
        loss = _apply_weighting(loss, self._weight, None)
        return _batch_mean(loss, self._batch_axis)
