"""Gluon utilities (reference: ``python/mxnet/gluon/utils.py``).

``split_and_load`` / ``split_data`` — the reference's manual multi-GPU
batch fan-out. Kept for API parity; on TPU the preferred path is a single
mesh-sharded array (``parallel.shard_batch``) so XLA manages placement.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence

from ..base import MXNetError
from ..context import Context
from ..ndarray.ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm",
           "check_sha1", "download"]


def split_data(data: NDArray, num_slice: int, batch_axis: int = 0,
               even_split: bool = True) -> List[NDArray]:
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"batch size {size} not divisible by num_slice {num_slice}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        from ..ndarray import ops
        slices.append(ops.slice_axis(data, axis=batch_axis,
                                     begin=begin, end=end))
    return slices


def split_and_load(data: Any, ctx_list: Sequence[Context],
                   batch_axis: int = 0, even_split: bool = True
                   ) -> List[NDArray]:
    """Slice a batch across contexts (reference DP idiom)."""
    if not isinstance(data, NDArray):
        data = NDArray(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays: Sequence[NDArray], max_norm: float,
                     check_isfinite: bool = True) -> float:
    """Rescale arrays in place so the joint L2 norm <= max_norm."""
    import math
    total = 0.0
    for a in arrays:
        n = a.norm().item()
        total += n * n
    total = math.sqrt(total)
    if check_isfinite and not math.isfinite(total):
        import warnings
        warnings.warn("nan or inf found in clip_global_norm")
    if total > max_norm:
        scale = max_norm / (total + 1e-8)
        for a in arrays:
            a._data = (a * scale)._data
    return total


def check_sha1(filename: str, sha1_hash: str) -> bool:
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1 << 20)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url: str, path: Optional[str] = None, overwrite: bool = False,
             sha1_hash: Optional[str] = None, retries: int = 5,
             verify_ssl: bool = True) -> str:
    raise MXNetError(
        "download() requires network egress, which this environment does "
        "not provide; place files locally and pass paths directly")
