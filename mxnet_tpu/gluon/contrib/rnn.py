"""Experimental recurrent cells.

Reference parity (leezu/mxnet): ``python/mxnet/gluon/contrib/rnn/
conv_rnn_cell.py`` (``Conv2DLSTMCell`` family) and ``rnn_cell.py``
(``VariationalDropoutCell`` — per-sequence dropout masks shared across
time steps, Gal & Ghahramani).
"""
from __future__ import annotations

from typing import Any, List, Optional, Tuple

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ...ndarray import ops as ndops
from ... import npx
from ..parameter import Parameter
from ..rnn.rnn_cell import (ModifierCell, RecurrentCell,
                            _BaseGatedCell)

__all__ = ["VariationalDropoutCell", "Conv2DLSTMCell", "LSTMPCell"]


class VariationalDropoutCell(ModifierCell):
    """Applies the SAME dropout mask at every time step (variational RNN
    dropout) to inputs, states, and/or outputs."""

    def __init__(self, base_cell: RecurrentCell,
                 drop_inputs: float = 0.0, drop_states: float = 0.0,
                 drop_outputs: float = 0.0, **kwargs: Any) -> None:
        super().__init__(base_cell, **kwargs)
        self._di, self._ds, self._do = drop_inputs, drop_states, \
            drop_outputs
        self._mask_in: Optional[NDArray] = None
        self._mask_st: Optional[NDArray] = None
        self._mask_out: Optional[NDArray] = None

    def reset(self) -> None:
        self._mask_in = self._mask_st = self._mask_out = None
        if hasattr(self.base_cell, "reset"):
            self.base_cell.reset()

    def _mask(self, cached: Optional[NDArray], p: float,
              like: NDArray) -> Tuple[Optional[NDArray], NDArray]:
        from ..._tape import is_training
        if not p or not is_training():
            return cached, like
        if cached is None:
            from ...ndarray import random as rnd
            cached = rnd.bernoulli(1 - p, shape=like.shape) / (1 - p)
        return cached, like * cached

    def forward(self, inputs: NDArray, states: List[NDArray]):
        self._mask_in, inputs = self._mask(self._mask_in, self._di,
                                           inputs)
        if self._ds:
            self._mask_st, h = self._mask(self._mask_st, self._ds,
                                          states[0])
            states = [h] + list(states[1:])
        out, new_states = self.base_cell(inputs, states)
        self._mask_out, out = self._mask(self._mask_out, self._do, out)
        return out, new_states

    def __repr__(self) -> str:
        return (f"VariationalDropoutCell(in={self._di}, state={self._ds},"
                f" out={self._do}, base={self.base_cell!r})")


class Conv2DLSTMCell(RecurrentCell):
    """Convolutional LSTM (Shi et al. 2015): gates computed by conv over
    (C, H, W) states instead of dense projections
    (reference ``gluon.contrib.rnn.Conv2DLSTMCell``, NCHW layout)."""

    def __init__(self, input_shape: Tuple[int, int, int],
                 hidden_channels: int,
                 i2h_kernel=(3, 3), h2h_kernel=(3, 3),
                 i2h_pad=(1, 1), **kwargs: Any) -> None:
        super().__init__(**kwargs)
        in_c, in_h, in_w = input_shape
        self._shape = (in_h, in_w)
        self._hc = hidden_channels
        kh, kw = h2h_kernel
        if kh % 2 == 0 or kw % 2 == 0:
            raise MXNetError("h2h_kernel must be odd (state-preserving)")
        self._i2h_kernel = tuple(i2h_kernel)
        self._h2h_kernel = tuple(h2h_kernel)
        self._i2h_pad = tuple(i2h_pad)
        self._h2h_pad = (kh // 2, kw // 2)
        self.i2h_weight = Parameter(
            "i2h_weight", shape=(4 * hidden_channels, in_c)
            + self._i2h_kernel)
        self.h2h_weight = Parameter(
            "h2h_weight", shape=(4 * hidden_channels, hidden_channels)
            + self._h2h_kernel)
        self.i2h_bias = Parameter("i2h_bias",
                                  shape=(4 * hidden_channels,),
                                  init="zeros")
        self.h2h_bias = Parameter("h2h_bias",
                                  shape=(4 * hidden_channels,),
                                  init="zeros")

    def state_info(self, batch_size: int = 0):
        shape = (batch_size, self._hc) + self._shape
        return [{"shape": shape, "__layout__": "NCHW"},
                {"shape": shape, "__layout__": "NCHW"}]

    def forward(self, inputs: NDArray, states: List[NDArray]):
        for p in (self.i2h_weight, self.h2h_weight, self.i2h_bias,
                  self.h2h_bias):
            if not p.is_initialized:
                p._finish_deferred_init(p.shape)
        h, c = states
        gi = npx.convolution(inputs, self.i2h_weight.data(),
                             self.i2h_bias.data(),
                             kernel=self._i2h_kernel, pad=self._i2h_pad,
                             num_filter=4 * self._hc)
        gh = npx.convolution(h, self.h2h_weight.data(),
                             self.h2h_bias.data(),
                             kernel=self._h2h_kernel, pad=self._h2h_pad,
                             num_filter=4 * self._hc)
        g = gi + gh
        i_g, f_g, c_g, o_g = [
            ndops.slice_axis(g, axis=1, begin=k * self._hc,
                             end=(k + 1) * self._hc) for k in range(4)]
        i_g = ndops.sigmoid(i_g)
        f_g = ndops.sigmoid(f_g)
        o_g = ndops.sigmoid(o_g)
        c_next = f_g * c + i_g * ndops.tanh(c_g)
        h_next = o_g * ndops.tanh(c_next)
        return h_next, [h_next, c_next]


class LSTMPCell(_BaseGatedCell):
    """LSTM cell with a hidden-state projection (reference:
    gluon.contrib.rnn.LSTMPCell, the LSTMP architecture of Sak et al.
    2014): the recurrent state is the PROJECTED hidden ``r`` of size
    ``projection_size``; the cell state keeps ``hidden_size``. Gate
    order i, f, g, o, matching :class:`LSTMCell`; parameter plumbing
    (deferred init, fused gate projections) comes from the shared
    gated-cell base with ``recurrent_size=projection_size``."""

    def __init__(self, hidden_size: int, projection_size: int,
                 input_size: int = 0,
                 h2r_weight_initializer: Any = None,
                 **kwargs: Any) -> None:
        super().__init__(hidden_size, 4, input_size=input_size,
                         recurrent_size=projection_size, **kwargs)
        self._projection_size = projection_size
        self.h2r_weight = Parameter(
            "h2r_weight", shape=(projection_size, hidden_size),
            init=h2r_weight_initializer)

    def state_info(self, batch_size: int = 0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def forward(self, inputs: NDArray, states: List[NDArray]):
        from ... import numpy as mxnp
        r_prev, c_prev = states
        gi, gh = self._proj(inputs, r_prev)
        parts = mxnp.split(gi + gh, 4, axis=-1)
        i = parts[0].sigmoid()
        f = parts[1].sigmoid()
        g = parts[2].tanh()
        o = parts[3].sigmoid()
        c = f * c_prev + i * g
        hidden = o * c.tanh()
        if not self.h2r_weight.is_initialized:
            self.h2r_weight._finish_deferred_init(self.h2r_weight.shape)
        r = npx.fully_connected(hidden, self.h2r_weight.data(), None,
                                num_hidden=self._projection_size,
                                flatten=False)
        return r, [r, c]
