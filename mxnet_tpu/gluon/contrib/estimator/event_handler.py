"""Estimator event handlers.

Reference parity (leezu/mxnet): ``python/mxnet/gluon/contrib/estimator/
event_handler.py`` — mixin interfaces (TrainBegin/TrainEnd/EpochBegin/
EpochEnd/BatchBegin/BatchEnd) and the stock handlers (stopping, metric,
validation, logging, checkpoint, early stopping).
"""
from __future__ import annotations

import logging
import os
import time
import warnings
from typing import Any, List, Optional

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd", "BatchBegin",
           "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]


class TrainBegin:
    def train_begin(self, estimator: Any, *args: Any, **kwargs: Any) -> None:
        pass


class TrainEnd:
    def train_end(self, estimator: Any, *args: Any, **kwargs: Any) -> None:
        pass


class EpochBegin:
    def epoch_begin(self, estimator: Any, *args: Any, **kwargs: Any) -> None:
        pass


class EpochEnd:
    def epoch_end(self, estimator: Any, *args: Any, **kwargs: Any) -> bool:
        return False


class BatchBegin:
    def batch_begin(self, estimator: Any, *args: Any, **kwargs: Any) -> None:
        pass


class BatchEnd:
    def batch_end(self, estimator: Any, *args: Any, **kwargs: Any) -> bool:
        return False


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop on max_epoch / max_batch."""

    def __init__(self, max_epoch: Optional[int] = None,
                 max_batch: Optional[int] = None) -> None:
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator: Any, *args: Any, **kwargs: Any) -> None:
        self.max_epoch = estimator.max_epoch
        self.max_batch = estimator.max_batch
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator: Any, *args: Any, **kwargs: Any) -> bool:
        self.current_batch += 1
        if self.max_batch and self.current_batch == self.max_batch:
            self.stop_training = True
        return self.stop_training

    def epoch_end(self, estimator: Any, *args: Any, **kwargs: Any) -> bool:
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch == self.max_epoch:
            self.stop_training = True
        return self.stop_training


class MetricHandler(EpochBegin, BatchEnd):
    """Reset metrics at epoch start; update after each batch."""

    def __init__(self, metrics: List[Any], priority: int = -1000) -> None:
        self.metrics = metrics
        self.priority = priority

    def epoch_begin(self, estimator: Any, *args: Any, **kwargs: Any) -> None:
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator: Any, *args: Any, **kwargs: Any) -> bool:
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for m in self.metrics:
            name = m.get()[0] if not isinstance(m.get()[0], list) else ""
            if "loss" in str(name):
                m.update(0, loss)
            else:
                m.update([label], [pred])
        return False


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run validation every ``epoch_period`` epochs (or batch_period)."""

    def __init__(self, val_data: Any, eval_fn: Any,
                 epoch_period: int = 1,
                 batch_period: Optional[int] = None,
                 priority: int = -1000) -> None:
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.priority = priority
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator: Any, *args: Any, **kwargs: Any) -> None:
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator: Any, *args: Any, **kwargs: Any) -> bool:
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data)
        return False

    def epoch_end(self, estimator: Any, *args: Any, **kwargs: Any) -> bool:
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data)
        return False


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchEnd):
    """Log metrics per epoch (and optionally every N batches)."""

    def __init__(self, log_interval: Any = "epoch",
                 metrics: Optional[List[Any]] = None,
                 priority: int = float("inf")) -> None:
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.priority = priority
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0

    def train_begin(self, estimator: Any, *args: Any, **kwargs: Any) -> None:
        self.train_start = time.time()
        estimator.logger.info("Training begin: using optimizer %s with lr %s",
                              type(estimator.trainer.optimizer).__name__,
                              estimator.trainer.learning_rate)

    def train_end(self, estimator: Any, *args: Any, **kwargs: Any) -> None:
        estimator.logger.info("Train finished in %.3fs",
                              time.time() - self.train_start)

    def epoch_begin(self, estimator: Any, *args: Any, **kwargs: Any) -> None:
        self.epoch_start = time.time()
        self.batch_index = 0

    def epoch_end(self, estimator: Any, *args: Any, **kwargs: Any) -> bool:
        msg = f"[Epoch {self.current_epoch}] finished in " \
              f"{time.time() - self.epoch_start:.3f}s: "
        for m in self.metrics:
            name, value = m.get()
            msg += f"{name}: {value:.4f} "
        estimator.logger.info(msg)
        self.current_epoch += 1
        return False

    def batch_end(self, estimator: Any, *args: Any, **kwargs: Any) -> bool:
        if isinstance(self.log_interval, int):
            self.batch_index += 1
            if self.batch_index % self.log_interval == 0:
                msg = f"[Epoch {self.current_epoch}][Batch " \
                      f"{self.batch_index}] "
                for m in self.metrics:
                    name, value = m.get()
                    msg += f"{name}: {value:.4f} "
                estimator.logger.info(msg)
        return False


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Save model (and trainer) per epoch; keeps best by monitored metric."""

    def __init__(self, model_dir: str, model_prefix: str = "model",
                 monitor: Any = None, verbose: int = 0,
                 save_best: bool = False, mode: str = "auto",
                 epoch_period: int = 1,
                 max_checkpoints: int = 5) -> None:
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.max_checkpoints = max_checkpoints
        self.current_epoch = 0
        self.best = None
        self.mode = mode
        os.makedirs(model_dir, exist_ok=True)

    def _is_better(self, value: float) -> bool:
        if self.best is None:
            return True
        if self.mode == "min":
            return value < self.best
        if self.mode == "max":
            return value > self.best
        # auto: loss/error -> min else max
        name = self.monitor.get()[0] if self.monitor else ""
        minimize = any(t in str(name) for t in ("loss", "error"))
        return value < self.best if minimize else value > self.best

    def epoch_end(self, estimator: Any, *args: Any, **kwargs: Any) -> bool:
        self.current_epoch += 1
        if self.current_epoch % self.epoch_period != 0:
            return False
        prefix = os.path.join(self.model_dir, self.model_prefix)
        estimator.net.save_parameters(
            f"{prefix}-epoch{self.current_epoch}.params")
        if estimator.trainer is not None:
            estimator.trainer.save_states(
                f"{prefix}-epoch{self.current_epoch}.states")
        if self.save_best and self.monitor is not None:
            _, value = self.monitor.get()
            if self._is_better(value):
                self.best = value
                estimator.net.save_parameters(f"{prefix}-best.params")
        return False


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop when the monitored metric stops improving."""

    def __init__(self, monitor: Any, min_delta: float = 0.0,
                 patience: int = 0, mode: str = "auto",
                 baseline: Optional[float] = None) -> None:
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.mode = mode
        self.baseline = baseline
        self.wait = 0
        self.best: Optional[float] = None
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False

    def _improved(self, value: float) -> bool:
        if self.best is None:
            return True
        name = str(self.monitor.get()[0])
        if self.mode == "min" or (self.mode == "auto" and
                                  any(t in name for t in ("loss", "error"))):
            return value < self.best - self.min_delta
        return value > self.best + self.min_delta

    def epoch_end(self, estimator: Any, *args: Any, **kwargs: Any) -> bool:
        _, value = self.monitor.get()
        self.current_epoch += 1
        if self.baseline is not None and self.best is None:
            self.best = self.baseline
        if self._improved(value):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self.current_epoch
                self.stop_training = True
        return self.stop_training

    def train_end(self, estimator: Any, *args: Any, **kwargs: Any) -> None:
        if self.stopped_epoch > 0:
            estimator.logger.info("Early stopping at epoch %d",
                                  self.stopped_epoch)
