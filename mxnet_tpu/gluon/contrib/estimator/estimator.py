"""Estimator fit loop.

Reference parity (leezu/mxnet): ``python/mxnet/gluon/contrib/estimator/
estimator.py`` — ``Estimator(net, loss, train_metrics, trainer).fit(
train_data, val_data, epochs, event_handlers)``.
"""
from __future__ import annotations

import logging
from typing import Any, List, Optional, Sequence, Union

from .... import autograd
from ....base import MXNetError
from ....context import current_context
from ....metric import EvalMetric, Loss as LossMetric, create as metric_create
from ....ndarray.ndarray import NDArray
from ...trainer import Trainer
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            LoggingHandler, MetricHandler, StoppingHandler,
                            TrainBegin, TrainEnd, ValidationHandler)

__all__ = ["Estimator"]


def _as_nd(x: Any) -> NDArray:
    return x if isinstance(x, NDArray) else NDArray(x)


class Estimator:
    """High-level train facility over a gluon block."""

    def __init__(self, net: Any, loss: Any,
                 train_metrics: Any = None,
                 trainer: Optional[Trainer] = None,
                 context: Any = None,
                 val_metrics: Any = None) -> None:
        self.net = net
        self.loss = loss
        self.context = context or current_context()
        self.logger = logging.getLogger("mxnet_tpu.estimator")
        self.logger.setLevel(logging.INFO)

        def _norm_metrics(m: Any) -> List[EvalMetric]:
            if m is None:
                return []
            if isinstance(m, (list, tuple)):
                return [mm if isinstance(mm, EvalMetric)
                        else metric_create(mm) for mm in m]
            return [m if isinstance(m, EvalMetric) else metric_create(m)]

        self.train_metrics = _norm_metrics(train_metrics)
        self.val_metrics = _norm_metrics(val_metrics) or \
            [type(m)() for m in self.train_metrics]
        self.train_loss_metric = LossMetric(name="train_loss")
        self.val_loss_metric = LossMetric(name="val_loss")

        if trainer is None:
            params = net.collect_params()
            trainer = Trainer(params, "adam", {"learning_rate": 1e-3})
        self.trainer = trainer
        self.max_epoch: Optional[int] = None
        self.max_batch: Optional[int] = None

    # ------------------------------------------------------------------
    def evaluate(self, val_data: Any = None) -> None:
        if val_data is None:
            return
        for m in self.val_metrics:
            m.reset()
        self.val_loss_metric.reset()
        for batch in val_data:
            data, label = _as_nd(batch[0]), _as_nd(batch[1])
            pred = self.net(data)
            loss = self.loss(pred, label)
            self.val_loss_metric.update(0, loss)
            for m in self.val_metrics:
                m.update([label], [pred])

    def _default_handlers(self, val_data: Any) -> list:
        handlers: list = [StoppingHandler(self.max_epoch, self.max_batch),
                          MetricHandler([self.train_loss_metric]
                                        + self.train_metrics)]
        if val_data is not None:
            handlers.append(ValidationHandler(val_data, self.evaluate))
        handlers.append(LoggingHandler(
            metrics=[self.train_loss_metric] + self.train_metrics))
        return handlers

    def fit(self, train_data: Any, val_data: Any = None,
            epochs: Optional[int] = None,
            event_handlers: Optional[Sequence[Any]] = None,
            batches: Optional[int] = None,
            checkpoint_manager: Any = None,
            checkpoint_every: int = 0,
            health_guard: Any = None) -> None:
        """Train; with ``checkpoint_manager`` the call is preemption-
        safe: the newest verified checkpoint is restored before the
        first batch, a checkpoint is written every ``checkpoint_every``
        steps (0: only at the end / on preemption), and a
        SIGTERM/SIGINT finishes the in-flight batch, checkpoints, and
        returns cleanly.  A
        :class:`~mxnet_tpu.checkpoint.CoordinatedCheckpointManager`
        (over a ``dist_async`` kvstore) slots in unchanged: the
        cluster then agrees on one checkpoint step via the two-phase
        mark/commit rendezvous before any rank commits it — for
        Hogwild ranks running at different paces the agreed label is
        the min proposed step (the cluster-consistent floor) — and a
        restarted cluster resumes every rank from the same committed
        step.  Idempotence under kill-and-restart holds for
        ``batches``-mode, where ``batches`` counts TOTAL optimizer
        steps across restarts; ``epochs``-mode resumes the weights and
        optimizer state but restarts its epoch count (epoch progress is
        not recorded in the checkpoint) — prefer ``batches`` for
        preemptible jobs.

        With ``health_guard`` (:class:`mxnet_tpu.health.HealthGuard`):
        the trainer's step gains the fused numerics sentry
        (``guard.install``) covering the loss (finiteness + EMA
        divergence) and every gradient in ONE reduction before the
        update, a bad batch is dropped or rewound per policy (rewind
        needs ``checkpoint_manager``; the loop then continues with
        subsequent batches), and the hang watchdog arms around every
        batch."""
        if epochs is None and batches is None:
            raise MXNetError("fit: specify epochs or batches")
        resumed = 0
        if checkpoint_manager is not None:
            if checkpoint_manager.restore(self.trainer,
                                          block=self.net) is not None:
                # Trainer.load_states restored the optimizer's schedule
                # clock — the global step across restarts
                resumed = int(self.trainer._optimizer.num_update)
            if batches is not None:
                batches = batches - resumed
                if batches <= 0:
                    return      # a completed run's rerun is a no-op
        self.max_epoch = epochs
        self.max_batch = batches

        handlers = list(event_handlers or [])
        existing = {type(h) for h in handlers}
        for h in self._default_handlers(val_data):
            if type(h) not in existing:
                handlers.append(h)
        handlers.sort(key=lambda h: getattr(h, "priority", 0))

        train_begin = [h for h in handlers if isinstance(h, TrainBegin)]
        epoch_begin = [h for h in handlers if isinstance(h, EpochBegin)]
        batch_begin = [h for h in handlers if isinstance(h, BatchBegin)]
        batch_end = [h for h in handlers if isinstance(h, BatchEnd)]
        epoch_end = [h for h in handlers if isinstance(h, EpochEnd)]
        train_end = [h for h in handlers if isinstance(h, TrainEnd)]

        for h in train_begin:
            h.train_begin(self)

        import contextlib
        import time
        from .... import metrics as _metrics
        from ....preemption import PreemptionGuard

        if health_guard is not None:
            health_guard.install(self.trainer)
            if checkpoint_manager is not None:
                health_guard.set_rewind(
                    lambda: checkpoint_manager.restore(self.trainer,
                                                       block=self.net))

        last_saved = [-1]

        def _save_checkpoint() -> None:
            step = int(self.trainer._optimizer.num_update)
            if step == last_saved[0]:
                return                  # already checkpointed this step
            # watchdog-armed: a coordinated save blocks in the cluster
            # rendezvous until every rank arrives — a wedged peer dumps
            # stacks (and a DEAD one is named) instead of hanging here
            from ....health import watch_section
            with watch_section("checkpoint.save", step=step):
                checkpoint_manager.save(self.trainer, step=step,
                                        block=self.net)
            last_saved[0] = step

        def _start_async_read(*arrays) -> None:
            # begin the device->host transfers WITHOUT blocking: by the
            # time the one-step-late handlers read these values, the
            # copy has ridden under the next step's device execution
            for a in arrays:
                try:
                    a._data.copy_to_host_async()
                except Exception:   # noqa: BLE001 - backend-dependent
                    pass            # surface (and non-NDArray labels)

        stop = False
        dispatched = 0      # optimizer steps dispatched by THIS call
        # one-step-late READS: only the handlers whose batch_end is a
        # pure device->host read (metric update, logging) defer a step —
        # their asnumpy() then lands on an already-transferred value
        # while the NEXT step executes, instead of serializing the
        # device every batch.  Control handlers (stopping, validation,
        # checkpoints, user hooks — including SUBCLASSES of the metric/
        # logging handlers, which may stop or mutate) keep their exact
        # pre-deferral timing: they observe each optimizer state once,
        # at the original point.
        deferred_ends = [h for h in batch_end
                         if type(h) in (MetricHandler, LoggingHandler)]
        immediate_ends = [h for h in batch_end if h not in deferred_ends]
        with PreemptionGuard() as guard:
            while not stop:
                for h in epoch_begin:
                    h.epoch_begin(self)
                # explicit iteration so the loader wait is a measured
                # phase: per-step time splits into data-wait (next(it)),
                # dispatch (forward/backward/update — returns with
                # device work still in flight), and device-sync (the
                # ONE-STEP-LATE batch_end handlers: batch N's metric /
                # logging reads run while step N+1 is in flight, so the
                # asnumpy() that used to serialize the device every
                # batch now lands on an already-transferred value)
                pending = None      # batch_end kwargs for batch N-1
                it = iter(train_data)
                while True:
                    if self.max_batch is not None \
                            and dispatched >= self.max_batch:
                        # belt-and-braces: batches-mode must stay EXACT
                        # even if a custom stopping handler is built on
                        # the deferred (one-step-late) read path
                        break
                    t0 = time.perf_counter()
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                    data, label = _as_nd(batch[0]), _as_nd(batch[1])
                    t_data = time.perf_counter()
                    for h in batch_begin:
                        h.batch_begin(self, batch=batch)
                    with (health_guard.watch("trainer.step")
                          if health_guard is not None
                          else contextlib.nullcontext()):
                        with autograd.record():
                            pred = self.net(data)
                            loss = self.loss(pred, label)
                        loss.backward()
                        if health_guard is not None:
                            # the installed _step_impl hook folds this
                            # loss into its fused gradient check (after
                            # the trainer.step fault site) — one
                            # reduction, one readback per step; a bad
                            # step skips/rewinds inside the hook
                            health_guard.note_loss(loss)
                        self.trainer.step(data.shape[0])
                    dispatched += 1
                    t_dispatch = time.perf_counter()
                    _start_async_read(loss, pred, label)
                    for h in immediate_ends:
                        if h.batch_end(self, batch=batch, pred=pred,
                                       label=label, loss=loss):
                            stop = True
                    if pending is not None:
                        for h in deferred_ends:
                            if h.batch_end(self, **pending):
                                stop = True
                    pending = (dict(batch=batch, pred=pred, label=label,
                                    loss=loss)
                               if deferred_ends else None)
                    t_end = time.perf_counter()
                    _metrics.record_step(t_end - t0,
                                         data=t_data - t0,
                                         dispatch=t_dispatch - t_data,
                                         sync=t_end - t_dispatch)
                    _metrics.record_device_highwater()
                    if guard.requested:
                        # preemption: the in-flight batch finished —
                        # checkpoint and leave cleanly; the next
                        # incarnation of fit() resumes here
                        if checkpoint_manager is not None:
                            _save_checkpoint()
                        stop = True
                    elif (checkpoint_manager is not None
                          and checkpoint_every > 0
                          and int(self.trainer._optimizer.num_update)
                          % checkpoint_every == 0):
                        _save_checkpoint()
                    if stop:
                        break
                if pending is not None:
                    # drain the deferred batch so epoch-end metrics and
                    # logging cover EVERY batch, including the last
                    for h in deferred_ends:
                        if h.batch_end(self, **pending):
                            stop = True
                    pending = None
                for h in epoch_end:
                    if h.epoch_end(self):
                        stop = True
                if self.max_epoch is None and self.max_batch is None:
                    break
            if checkpoint_manager is not None:
                # final checkpoint (dedup'd by step): covers BOTH normal
                # completion and a signal landing after the last batch's
                # in-loop guard check — the run must never finish N
                # batches yet leave zero checkpoints behind
                _save_checkpoint()

        for h in train_end:
            h.train_end(self)
