"""gluon.contrib — estimator and experimental blocks (reference:
``python/mxnet/gluon/contrib/``)."""
from . import estimator

__all__ = ["estimator"]
