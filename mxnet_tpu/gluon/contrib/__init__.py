"""gluon.contrib — estimator and experimental blocks (reference:
``python/mxnet/gluon/contrib/``)."""
from . import estimator
from . import nn

__all__ = ["estimator", "nn"]
