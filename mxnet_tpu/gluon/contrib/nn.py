"""Experimental gluon layers.

Reference parity (leezu/mxnet): ``python/mxnet/gluon/contrib/nn/
basic_layers.py`` — ``Concurrent``, ``HybridConcurrent``, ``Identity``,
``PixelShuffle1D/2D/3D`` (SyncBatchNorm lives in ``gluon.nn`` here, as in
2.x).
"""
from __future__ import annotations

from typing import Any, Optional

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ...ndarray import ops as ndops
from ..block import HybridBlock
from ..nn.basic_layers import Identity  # re-export (reference location)

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "PixelShuffle1D",
           "PixelShuffle2D", "PixelShuffle3D"]


class HybridConcurrent(HybridBlock):
    """Runs children on the same input, concatenates outputs along
    ``axis`` (Inception-style branches)."""

    def __init__(self, axis: int = -1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.axis = axis

    def add(self, *blocks: HybridBlock) -> None:
        for b in blocks:
            self.register_child(b)

    def forward(self, x: NDArray) -> NDArray:
        outs = [child(x) for child in self._children.values()]
        return ndops.concat(*outs, axis=self.axis)


class Concurrent(HybridConcurrent):
    """Imperative alias of :class:`HybridConcurrent` (reference keeps
    both names)."""


class _PixelShuffle(HybridBlock):
    def __init__(self, factor, ndim: int, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if isinstance(factor, int):
            factor = (factor,) * ndim
        self._factor = tuple(int(f) for f in factor)
        if len(self._factor) != ndim:
            raise MXNetError(f"factor must have {ndim} elements")
        self._ndim = ndim

    def forward(self, x: NDArray) -> NDArray:
        f = self._factor
        shape = x.shape
        C = shape[1]
        prod = 1
        for v in f:
            prod *= v
        if C % prod:
            raise MXNetError(
                f"channels {C} not divisible by shuffle factor {f}")
        Cout = C // prod
        spatial = shape[2:]
        # (N, Cout, f1..fn, d1..dn) -> interleave -> (N, Cout, d1*f1, ...)
        x = x.reshape((shape[0], Cout) + f + tuple(spatial))
        # build permutation: N, Cout, d1, f1, d2, f2, ...
        perm = [0, 1]
        for i in range(self._ndim):
            perm += [2 + self._ndim + i, 2 + i]
        x = x.transpose(tuple(perm))
        out_spatial = tuple(d * fi for d, fi in zip(spatial, f))
        return x.reshape((shape[0], Cout) + out_spatial)


class PixelShuffle1D(_PixelShuffle):
    """(N, C·f, W) -> (N, C, W·f) sub-pixel upsample."""

    def __init__(self, factor, **kwargs: Any) -> None:
        super().__init__(factor, 1, **kwargs)


class PixelShuffle2D(_PixelShuffle):
    """(N, C·f1·f2, H, W) -> (N, C, H·f1, W·f2)."""

    def __init__(self, factor, **kwargs: Any) -> None:
        super().__init__(factor, 2, **kwargs)


class PixelShuffle3D(_PixelShuffle):
    """(N, C·f1·f2·f3, D, H, W) -> (N, C, D·f1, H·f2, W·f3)."""

    def __init__(self, factor, **kwargs: Any) -> None:
        super().__init__(factor, 3, **kwargs)
