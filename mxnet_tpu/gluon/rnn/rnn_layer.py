"""Fused recurrent layers: RNN / LSTM / GRU.

Reference parity (leezu/mxnet): ``python/mxnet/gluon/rnn/rnn_layer.py``
(``_RNNLayer`` -> the stateful fused ``RNN`` op, ``src/operator/rnn-inl.h``
/ ``cudnn_rnn-inl.h``). Multi-layer, bidirectional, TNC/NTC layouts, same
parameter naming (``l0_i2h_weight`` ...), same gate orderings (LSTM:
i,f,g,o; GRU: r,z,n with separate i2h/h2h bias like cuDNN).

Design (tpu-first): the cuDNN fused kernel becomes ONE ``lax.scan`` over
time per layer/direction, with the input projection for ALL timesteps
hoisted into a single batched matmul (T*N, I)x(I, 4H) that XLA tiles onto
the MXU — the same restructuring cuDNN does internally. Under hybridize
the whole stack compiles into one program.
"""
from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ...base import MXNetError  # noqa: F401  (kept: error paths below)
from ..block import HybridBlock
from ..parameter import Parameter
from ...ndarray.ndarray import NDArray, from_jax
from ...ndarray.register import invoke

__all__ = ["RNN", "LSTM", "GRU"]


def _gates(mode: str) -> int:
    return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]


def _cell_step(mode: str):
    """One timestep: (h[, c]), preactivations -> new states + output."""
    if mode in ("rnn_relu", "rnn_tanh"):
        act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh

        def step(carry, gi, gh):
            (h,) = carry
            h_new = act(gi + gh)
            return (h_new,), h_new
        return step
    if mode == "lstm":
        def step(carry, gi, gh):
            h, c = carry
            g = gi + gh
            i_, f_, g_, o_ = jnp.split(g, 4, axis=-1)
            i_ = jax.nn.sigmoid(i_)
            f_ = jax.nn.sigmoid(f_)
            g_ = jnp.tanh(g_)
            o_ = jax.nn.sigmoid(o_)
            c_new = f_ * c + i_ * g_
            h_new = o_ * jnp.tanh(c_new)
            return (h_new, c_new), h_new
        return step
    if mode == "gru":
        def step(carry, gi, gh):
            (h,) = carry
            ir, iz, in_ = jnp.split(gi, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(in_ + r * hn)
            h_new = (1 - z) * n + z * h
            return (h_new,), h_new
        return step
    raise ValueError(mode)


def _run_single_direction(mode, x, h0, c0, wi, wh, bi, bh, reverse=False):
    """Scan one layer/direction. x: (T,N,I); returns (T,N,H), h_T[, c_T]."""
    T, N, _ = x.shape
    H = wh.shape[1]
    # hoist input projection: one big MXU matmul over all timesteps
    gi_all = jnp.einsum("tni,gi->tng", x, wi) + bi  # wi: (G*H, I)
    step = _cell_step(mode)

    def scan_fn(carry, gi_t):
        gh = carry[0] @ wh.T + bh
        new_carry, h_out = step(carry, gi_t, gh)
        return new_carry, h_out

    if reverse:
        gi_all = jnp.flip(gi_all, axis=0)
    carry0 = (h0, c0) if mode == "lstm" else (h0,)
    carry_T, hs = lax.scan(scan_fn, carry0, gi_all)
    if reverse:
        hs = jnp.flip(hs, axis=0)
    return hs, carry_T


def _seq_reverse(x, lens):
    """Reverse each batch column's first ``lens[n]`` steps and ZERO the
    rest — the varlen-scan helper (NOT the reference ``SequenceReverse``
    op, which preserves padded values; see ops/nn.py sequence_reverse).
    x: (T, N, C)."""
    T = x.shape[0]
    idx = jnp.arange(T)[:, None]                    # (T, 1)
    src = jnp.clip(lens[None, :] - 1 - idx, 0, T - 1)
    valid = idx < lens[None, :]
    g = jnp.take_along_axis(
        x, jnp.broadcast_to(src[:, :, None], x.shape), axis=0)
    return jnp.where(valid[:, :, None], g, 0).astype(x.dtype)


def _run_single_direction_varlen(mode, x, lens, h0, c0, wi, wh, bi, bh,
                                 reverse=False):
    """Variable-length scan (the cuDNN packed-sequence analog): the carry
    FREEZES once t >= lens[n], so the returned final state is exactly the
    state after each sequence's true last step; padded outputs are zero.
    The reverse direction runs forward over the length-aware reversed
    sequence, so it too starts at each sequence's true end."""
    T, N, _ = x.shape
    # lengths beyond T would silently mis-index the reversed gather
    lens = jnp.minimum(lens, T)
    if reverse:
        x = _seq_reverse(x, lens)
    gi_all = jnp.einsum("tni,gi->tng", x, wi) + bi
    step = _cell_step(mode)

    def scan_fn(carry, inp):
        gi_t, t = inp
        gh = carry[0] @ wh.T + bh
        new_carry, h_out = step(carry, gi_t, gh)
        active = (t < lens)[:, None]
        new_carry = tuple(jnp.where(active, nc, oc)
                          for nc, oc in zip(new_carry, carry))
        h_out = jnp.where(active, h_out, 0).astype(h_out.dtype)
        return new_carry, h_out

    carry0 = (h0, c0) if mode == "lstm" else (h0,)
    carry_T, hs = lax.scan(scan_fn, carry0,
                           (gi_all, jnp.arange(T, dtype=jnp.int32)))
    if reverse:
        hs = _seq_reverse(hs, lens)
    return hs, carry_T


class _RNNLayer(HybridBlock):
    def __init__(self, mode: str, hidden_size: int, num_layers: int = 1,
                 layout: str = "TNC", dropout: float = 0.0,
                 bidirectional: bool = False, input_size: int = 0,
                 i2h_weight_initializer: Any = None,
                 h2h_weight_initializer: Any = None,
                 i2h_bias_initializer: Any = "zeros",
                 h2h_bias_initializer: Any = "zeros",
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            f"invalid layout {layout}; expected TNC or NTC"
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        ng = _gates(mode)
        for layer in range(num_layers):
            for d in range(self._dir):
                suffix = "_" if d == 0 else "_r_"
                in_size = input_size if layer == 0 \
                    else hidden_size * self._dir
                for name, shape, init in (
                        ("i2h_weight", (ng * hidden_size, in_size),
                         i2h_weight_initializer),
                        ("h2h_weight", (ng * hidden_size, hidden_size),
                         h2h_weight_initializer),
                        ("i2h_bias", (ng * hidden_size,),
                         i2h_bias_initializer),
                        ("h2h_bias", (ng * hidden_size,),
                         h2h_bias_initializer)):
                    pname = f"l{layer}{suffix}{name}" if d else \
                        f"l{layer}_{name}"
                    self.register_parameter(
                        pname, Parameter(pname, shape=shape, init=init))

    def state_info(self):
        raise NotImplementedError

    def _num_states(self) -> int:
        return 2 if self._mode == "lstm" else 1

    def begin_state(self, batch_size: int = 0, func=None, ctx=None,
                    **kwargs) -> List[NDArray]:
        """Initial states, shape (num_layers*dir, N, H) each."""
        from ...ndarray import ops
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [ops.zeros(shape, ctx=ctx) for _ in range(self._num_states())]

    def _ordered_params(self) -> List[Parameter]:
        return list(self._reg_params.values())

    def forward(self, inputs: NDArray, states: Optional[List[NDArray]] = None):
        ret_states = states is not None
        if self._layout == "NTC":
            inputs = inputs.swapaxes(0, 1)
        T, N, I = inputs.shape
        # finish deferred init for layer-0 weights
        ng = _gates(self._mode)
        for name, p in self._reg_params.items():
            if not p.is_initialized and p.shape is not None:
                if "l0" in name and "i2h_weight" in name:
                    p._finish_deferred_init((ng * self._hidden_size, I))
                else:
                    p._finish_deferred_init(p.shape)
        if states is None:
            states = self.begin_state(N)
        states_nd = list(states)
        params = self._ordered_params()
        mode = self._mode
        num_layers, ndir, H = self._num_layers, self._dir, self._hidden_size
        dropout = self._dropout
        from ..._tape import is_training
        train = is_training()
        from ...ndarray import random as _random
        drop_key = _random.split_key() if (dropout and train) else None

        def impl(x, *arrs):
            ns = self._num_states()
            state_arrs = arrs[:ns]
            weights = arrs[ns:]
            h_all = state_arrs[0]
            c_all = state_arrs[1] if ns == 2 else None
            out = x
            h_finals, c_finals = [], []
            widx = 0
            for layer in range(num_layers):
                layer_outs = []
                for d in range(ndir):
                    wi, wh, bi, bh = weights[widx:widx + 4]
                    widx += 4
                    sidx = layer * ndir + d
                    h0 = h_all[sidx]
                    c0 = c_all[sidx] if c_all is not None else None
                    hs, carry_T = _run_single_direction(
                        mode, out, h0, c0, wi, wh, bi, bh, reverse=(d == 1))
                    layer_outs.append(hs)
                    h_finals.append(carry_T[0])
                    if c_all is not None:
                        c_finals.append(carry_T[1])
                out = layer_outs[0] if ndir == 1 else \
                    jnp.concatenate(layer_outs, axis=-1)
                if dropout and train and layer != num_layers - 1:
                    keep = jax.random.bernoulli(
                        jax.random.fold_in(drop_key, layer),
                        1.0 - dropout, out.shape)
                    out = jnp.where(keep, out / (1.0 - dropout), 0.0)
            new_states = [jnp.stack(h_finals)]
            if c_all is not None:
                new_states.append(jnp.stack(c_finals))
            return (out, *new_states)

        inputs_list = [inputs] + states_nd + [p.data() for p in params]
        results = invoke(f"rnn_{mode}", impl, inputs_list)
        out = results[0]
        new_states = list(results[1:])
        if self._layout == "NTC":
            out = out.swapaxes(0, 1)
        if ret_states:
            return out, new_states
        return out

    def __repr__(self) -> str:
        return (f"{type(self).__name__}({self._hidden_size}, "
                f"num_layers={self._num_layers}, "
                f"bidirectional={self._dir == 2})")


class RNN(_RNNLayer):
    """Elman RNN with relu/tanh (reference: ``gluon.rnn.RNN``)."""

    def __init__(self, hidden_size: int, num_layers: int = 1,
                 activation: str = "relu", **kwargs: Any) -> None:
        super().__init__(f"rnn_{activation}", hidden_size, num_layers,
                         **kwargs)


class LSTM(_RNNLayer):
    """Multi-layer (bi)LSTM (reference: ``gluon.rnn.LSTM``; BASELINE
    config 4's model)."""

    def __init__(self, hidden_size: int, num_layers: int = 1,
                 **kwargs: Any) -> None:
        super().__init__("lstm", hidden_size, num_layers, **kwargs)


class GRU(_RNNLayer):
    """Multi-layer (bi)GRU with cuDNN-style separate reset-gate bias."""

    def __init__(self, hidden_size: int, num_layers: int = 1,
                 **kwargs: Any) -> None:
        super().__init__("gru", hidden_size, num_layers, **kwargs)
