"""Unfused recurrent cells (reference: ``python/mxnet/gluon/rnn/rnn_cell.py``).

RNNCell/LSTMCell/GRUCell + modifiers (Residual/Zoneout/Dropout),
SequentialRNNCell, BidirectionalCell, HybridSequentialRNNCell, and
``unroll`` — the explicit-stepping API whose fused equivalent lives in
``rnn_layer.py``. The reference's equivalence test (fused RNN vs stacked
cells) is mirrored in tests/test_rnn.py.
"""
from __future__ import annotations

from typing import Any, List, Optional, Tuple

from ... import npx
from ... import numpy as mxnp
from ...ndarray.ndarray import NDArray
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "HybridSequentialRNNCell", "DropoutCell",
           "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell"]


class RecurrentCell(Block):
    """Base cell: ``__call__(input, states) -> (output, new_states)``."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._modified = False

    def state_info(self, batch_size: int = 0):
        raise NotImplementedError

    def begin_state(self, batch_size: int = 0, func=None,
                    ctx=None, **kwargs) -> List[NDArray]:
        from ...ndarray import ops
        # full state_info shape: conv cells carry (N, C, H, W) states
        return [ops.zeros(tuple(info["shape"]), ctx=ctx)
                for info in self.state_info(batch_size)]

    def reset(self) -> None:
        pass

    def unroll(self, length: int, inputs: NDArray,
               begin_state: Optional[List[NDArray]] = None,
               layout: str = "NTC", merge_outputs: Optional[bool] = None,
               valid_length: Optional[NDArray] = None):
        """Unroll the cell over ``length`` steps (reference semantics)."""
        axis = layout.find("T")
        batch_axis = layout.find("N")
        batch_size = inputs.shape[batch_axis]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        states = begin_state
        outputs = []
        from ...ndarray import ops
        for t in range(length):
            step = ops.slice_axis(inputs, axis=axis, begin=t, end=t + 1) \
                .squeeze(axis)
            out, states = self(step, states)
            outputs.append(out)
        if valid_length is not None:
            stacked = ops.stack(outputs, axis=axis)
            stacked = npx.sequence_mask(
                stacked, valid_length, use_sequence_length=True,
                axis=axis if axis == 0 else 1)
            if merge_outputs is False:
                # match the no-valid_length path: per-step (N, C) outputs
                outputs = [o.squeeze(axis)
                           for o in stacked.split(length, axis=axis)]
            else:
                return stacked, states
        if merge_outputs is None or merge_outputs:
            return ops.stack(outputs, axis=axis), states
        return outputs, states


class _BaseGatedCell(RecurrentCell):
    def __init__(self, hidden_size: int, num_gates: int,
                 input_size: int = 0,
                 i2h_weight_initializer: Any = None,
                 h2h_weight_initializer: Any = None,
                 i2h_bias_initializer: Any = "zeros",
                 h2h_bias_initializer: Any = "zeros",
                 recurrent_size: Optional[int] = None,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._input_size = input_size
        # the recurrent input may be narrower than hidden_size
        # (LSTMPCell feeds back a projection)
        self._recurrent_size = recurrent_size or hidden_size
        ng = num_gates
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(ng * hidden_size, input_size),
                                    init=i2h_weight_initializer)
        self.h2h_weight = Parameter(
            "h2h_weight", shape=(ng * hidden_size, self._recurrent_size),
            init=h2h_weight_initializer)
        self.i2h_bias = Parameter("i2h_bias", shape=(ng * hidden_size,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter("h2h_bias", shape=(ng * hidden_size,),
                                  init=h2h_bias_initializer)
        self._ng = ng

    def _proj(self, x: NDArray, h: NDArray) -> Tuple[NDArray, NDArray]:
        if not self.i2h_weight.is_initialized:
            self.i2h_weight._finish_deferred_init(
                (self._ng * self._hidden_size, x.shape[-1]))
        for p in (self.h2h_weight, self.i2h_bias, self.h2h_bias):
            if not p.is_initialized:
                p._finish_deferred_init(p.shape)
        gi = npx.fully_connected(x, self.i2h_weight.data(),
                                 self.i2h_bias.data(),
                                 num_hidden=self._ng * self._hidden_size,
                                 flatten=False)
        gh = npx.fully_connected(h, self.h2h_weight.data(),
                                 self.h2h_bias.data(),
                                 num_hidden=self._ng * self._hidden_size,
                                 flatten=False)
        return gi, gh


class RNNCell(_BaseGatedCell):
    def __init__(self, hidden_size: int, activation: str = "tanh",
                 **kwargs: Any) -> None:
        super().__init__(hidden_size, 1, **kwargs)
        self._activation = activation

    def state_info(self, batch_size: int = 0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def forward(self, inputs: NDArray, states: List[NDArray]):
        gi, gh = self._proj(inputs, states[0])
        h = npx.activation(gi + gh, self._activation)
        return h, [h]


class LSTMCell(_BaseGatedCell):
    """Gate order i,f,g,o (reference LSTMCell)."""

    def __init__(self, hidden_size: int, **kwargs: Any) -> None:
        super().__init__(hidden_size, 4, **kwargs)

    def state_info(self, batch_size: int = 0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def forward(self, inputs: NDArray, states: List[NDArray]):
        h_prev, c_prev = states
        gi, gh = self._proj(inputs, h_prev)
        g = gi + gh
        parts = mxnp.split(g, 4, axis=-1)
        i = parts[0].sigmoid()
        f = parts[1].sigmoid()
        gg = parts[2].tanh()
        o = parts[3].sigmoid()
        c = f * c_prev + i * gg
        h = o * c.tanh()
        return h, [h, c]


class GRUCell(_BaseGatedCell):
    """Gate order r,z,n with cuDNN-style separate h2h bias."""

    def __init__(self, hidden_size: int, **kwargs: Any) -> None:
        super().__init__(hidden_size, 3, **kwargs)

    def state_info(self, batch_size: int = 0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def forward(self, inputs: NDArray, states: List[NDArray]):
        h_prev = states[0]
        gi, gh = self._proj(inputs, h_prev)
        ir, iz, in_ = mxnp.split(gi, 3, axis=-1)
        hr, hz, hn = mxnp.split(gh, 3, axis=-1)
        r = (ir + hr).sigmoid()
        z = (iz + hz).sigmoid()
        n = (in_ + r * hn).tanh()
        h = (1 - z) * n + z * h_prev
        return h, [h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells; states concatenate across cells."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)

    def add(self, cell: RecurrentCell) -> None:
        self.register_child(cell)

    def state_info(self, batch_size: int = 0):
        infos = []
        for cell in self._children.values():
            infos.extend(cell.state_info(batch_size))
        return infos

    def begin_state(self, batch_size: int = 0, **kwargs) -> List[NDArray]:
        states = []
        for cell in self._children.values():
            states.extend(cell.begin_state(batch_size, **kwargs))
        return states

    def forward(self, inputs: NDArray, states: List[NDArray]):
        next_states = []
        pos = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            inputs, cell_states = cell(inputs, states[pos:pos + n])
            next_states.extend(cell_states)
            pos += n
        return inputs, next_states

    def __len__(self) -> int:
        return len(self._children)

    def __getitem__(self, i: int) -> RecurrentCell:
        return list(self._children.values())[i]


HybridSequentialRNNCell = SequentialRNNCell


class DropoutCell(RecurrentCell):
    def __init__(self, rate: float, axes: Tuple[int, ...] = (),
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size: int = 0):
        return []

    def forward(self, inputs: NDArray, states: List[NDArray]):
        if self._rate:
            inputs = npx.dropout(inputs, self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(RecurrentCell):
    def __init__(self, base_cell: RecurrentCell, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        base_cell._modified = True
        self.base_cell = base_cell

    def state_info(self, batch_size: int = 0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size: int = 0, **kwargs) -> List[NDArray]:
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference: ZoneoutCell)."""

    def __init__(self, base_cell: RecurrentCell, zoneout_outputs: float = 0.0,
                 zoneout_states: float = 0.0, **kwargs: Any) -> None:
        super().__init__(base_cell, **kwargs)
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output: Optional[NDArray] = None

    def reset(self) -> None:
        self._prev_output = None

    def forward(self, inputs: NDArray, states: List[NDArray]):
        from ..._tape import is_training
        out, new_states = self.base_cell(inputs, states)
        if not is_training():
            return out, new_states
        from ...ndarray import random as rnd

        def mask(p, like):
            return rnd.bernoulli(1 - p, shape=like.shape)

        prev = self._prev_output
        if prev is None:
            prev = out.zeros_like()
        if self._zo:
            m = mask(self._zo, out)
            out = m * out + (1 - m) * prev
        self._prev_output = out
        if self._zs:
            masked = []
            for ns, s in zip(new_states, states):
                m = mask(self._zs, ns)  # ONE shared mask selects new vs old
                masked.append(m * ns + (1 - m) * s)
            new_states = masked
        return out, new_states


class ResidualCell(ModifierCell):
    def forward(self, inputs: NDArray, states: List[NDArray]):
        out, new_states = self.base_cell(inputs, states)
        return out + inputs, new_states


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell: RecurrentCell, r_cell: RecurrentCell,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.l_cell = l_cell
        self.r_cell = r_cell

    def state_info(self, batch_size: int = 0):
        return self.l_cell.state_info(batch_size) + \
            self.r_cell.state_info(batch_size)

    def begin_state(self, batch_size: int = 0, **kwargs) -> List[NDArray]:
        return self.l_cell.begin_state(batch_size, **kwargs) + \
            self.r_cell.begin_state(batch_size, **kwargs)

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "BidirectionalCell supports unroll() only (step-by-step "
            "execution cannot see the future)")

    def unroll(self, length: int, inputs: NDArray,
               begin_state: Optional[List[NDArray]] = None,
               layout: str = "NTC", merge_outputs: Optional[bool] = None,
               valid_length: Optional[NDArray] = None):
        axis = layout.find("T")
        batch_axis = layout.find("N")
        batch_size = inputs.shape[batch_axis]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        nl = len(self.l_cell.state_info())
        l_out, l_states = self.l_cell.unroll(
            length, inputs, begin_state[:nl], layout, True, valid_length)
        from ...ndarray import ops
        rev = npx.sequence_reverse(
            inputs, valid_length, use_sequence_length=valid_length is not None,
            axis=axis)
        r_out, r_states = self.r_cell.unroll(
            length, rev, begin_state[nl:], layout, True, valid_length)
        r_out = npx.sequence_reverse(
            r_out, valid_length, use_sequence_length=valid_length is not None,
            axis=axis)
        out = mxnp.concatenate([l_out, r_out], axis=-1)
        return out, l_states + r_states
