"""Deploy-graph emission: an SSA op list the native C runtime can run.

Reference parity (leezu/mxnet): ``HybridBlock.export`` wrote an NNVM
graph json that ``src/c_predict_api.cc`` executed from C with no Python.
Here the primary export payload is a StableHLO artifact (the TPU-era
graph format), which the C runtime cannot interpret — so export()
ADDITIONALLY emits this small declarative op list whenever the block is
composed of layers the native runtime implements (dense / conv2d /
batchnorm / pooling / activation / flatten / dropout-as-identity, and —
r4 — elementwise ``add`` and channel ``concat``, which makes residual
nets (ResNet) and concat trunks (Inception) C-runnable).
``src/predict.cc`` (MXPredCreate/MXPredForward) parses it, loads the
.params file, and executes the graph through MXImperativeInvoke.

Dataflow: value 0 is the network input; node k (0-based) produces value
k+1; every node lists its input values under ``"in"`` (a node without
``"in"`` consumes the previous node's output — the pre-r4 sequential
format, which the C runtime still accepts).

Blocks whose forward is not a plain child chain make themselves
deployable by defining ``deploy_emit(self, em, prefix, vid) -> vid``
(see :class:`DeployEmitter`) — the model zoo's residual and concat
blocks do; user blocks can too. The hook must mirror the block's
``forward`` exactly (guard against subclasses that override forward).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional


class _Unmappable(Exception):
    pass


class DeployEmitter:
    """The SSA-builder surface handed to a block's ``deploy_emit`` hook.

    * ``emit(child, prefix, vid) -> vid`` — recursively emit a child
      block applied to value ``vid``; parameter names are keyed
      ``prefix + <param name>``.
    * ``push(node, ins) -> vid`` — append one raw graph node reading
      the value ids ``ins``; returns the produced value id.
    * ``bn(block, prefix)`` — a batchnorm (inference) node dict for a
      BatchNorm block.
    * ``act_ok(name)`` — validate an activation against the native set.
    * ``fail(reason)`` — abort emission; export falls back to
      ``deploy_graph = null`` (Python/StableHLO-only model).
    """

    def __init__(self) -> None:
        self.nodes: List[Dict[str, Any]] = []

    def push(self, node: Dict[str, Any], ins: List[int]) -> int:
        node["in"] = list(ins)
        self.nodes.append(node)
        return len(self.nodes)          # produced value id (0 = input)

    def fail(self, reason: str) -> None:
        raise _Unmappable(reason)

    def act_ok(self, a: Optional[str]) -> Optional[str]:
        # the native runtime implements exactly these (src/ndarray.cc)
        if a not in (None, "relu", "sigmoid", "tanh"):
            raise _Unmappable(f"activation {a!r}")
        return a

    def bn(self, b, pfx: str) -> Dict[str, Any]:
        if b._axis not in (1, -3):
            raise _Unmappable(repr(b))
        return {"op": "batchnorm", "gamma": pfx + "gamma",
                "beta": pfx + "beta", "mean": pfx + "running_mean",
                "var": pfx + "running_var", "eps": float(b._epsilon)}

    def emit(self, b, prefix: str, vid: int) -> int:
        """Emit ops computing ``b(value vid)``; returns the output id."""
        from .nn.basic_layers import (Dense, Dropout, Flatten, BatchNorm,
                                      HybridSequential)
        from .nn.activations import Activation
        from .nn.conv_layers import (Conv2D, MaxPool2D, AvgPool2D,
                                     GlobalMaxPool2D, GlobalAvgPool2D)

        hook = getattr(type(b), "deploy_emit", None)
        if hook is not None:
            return hook(b, self, prefix, vid)
        if isinstance(b, HybridSequential):
            if type(b).forward is not HybridSequential.forward:
                raise _Unmappable(type(b).__name__)   # custom dataflow
            for name, child in b._children.items():
                vid = self.emit(child, f"{prefix}{name}.", vid)
            return vid
        if isinstance(b, Dense):
            return self.push({
                "op": "dense", "weight": prefix + "weight",
                "bias": prefix + "bias" if b.bias is not None else None,
                "flatten": int(b._flatten),
                "activation": self.act_ok(b._activation)}, [vid])
        if isinstance(b, Conv2D):
            if (b._transpose or b._groups != 1 or b._layout != "NCHW"
                    or tuple(b._dilation) != (1, 1)):
                raise _Unmappable(repr(b))
            return self.push({
                "op": "conv2d", "weight": prefix + "weight",
                "bias": prefix + "bias" if b.bias is not None else None,
                "stride": list(b._strides), "pad": list(b._padding),
                "activation": self.act_ok(b._activation)}, [vid])
        if isinstance(b, (MaxPool2D, AvgPool2D, GlobalMaxPool2D,
                          GlobalAvgPool2D)):
            if b._layout != "NCHW":
                raise _Unmappable(repr(b))
            return self.push({
                "op": "maxpool2d" if b._pool_type == "max"
                else "avgpool2d",
                "kernel": list(b._kernel), "stride": list(b._strides),
                "pad": list(b._padding), "global": int(b._global),
                "count_include_pad": int(b._count_include_pad)}, [vid])
        if isinstance(b, BatchNorm):
            return self.push(self.bn(b, prefix), [vid])
        if isinstance(b, Activation):
            return self.push({"op": "activation",
                              "act": self.act_ok(b._act)}, [vid])
        if isinstance(b, Flatten):
            return self.push({"op": "flatten"}, [vid])
        if isinstance(b, Dropout):
            return vid                  # identity at inference
        raise _Unmappable(type(b).__name__)


def deploy_graph(block) -> Optional[List[Dict[str, Any]]]:
    """Best-effort SSA op list for ``block``; None when any layer has
    no native-runtime mapping (the StableHLO payload still covers it)."""
    em = DeployEmitter()
    try:
        out = em.emit(block, "", 0)
        if out != len(em.nodes):
            # the C runtime returns the LAST node's value; when the
            # logical output is an earlier value (trailing Dropout
            # identity), alias it through a no-op activation
            em.push({"op": "activation", "act": None}, [out])
    except _Unmappable:
        return None
    return em.nodes
