"""Deploy-graph emission: a layer-op list the native C runtime can run.

Reference parity (leezu/mxnet): ``HybridBlock.export`` wrote an NNVM
graph json that ``src/c_predict_api.cc`` executed from C with no Python.
Here the primary export payload is a StableHLO artifact (the TPU-era
graph format), which the C runtime cannot interpret — so export()
ADDITIONALLY emits this small declarative op list whenever the block is
composed of layers the native runtime implements (dense / conv2d /
batchnorm / pooling / activation / flatten / dropout-as-identity).
``src/predict.cc`` (MXPredCreate/MXPredForward) parses it, loads the
.params file, and executes the graph through MXImperativeInvoke.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional


class _Unmappable(Exception):
    pass


def deploy_graph(block) -> Optional[List[Dict[str, Any]]]:
    """Best-effort layer-op list for ``block``; None when any layer has
    no native-runtime mapping (the StableHLO payload still covers it)."""
    from .nn.basic_layers import (Dense, Dropout, Flatten, BatchNorm,
                                  HybridSequential)
    from .nn.activations import Activation
    from .nn.conv_layers import (Conv2D, MaxPool2D, AvgPool2D,
                                 GlobalMaxPool2D, GlobalAvgPool2D)

    nodes: List[Dict[str, Any]] = []

    def act_ok(a: Optional[str]) -> Optional[str]:
        # the native runtime implements exactly these (src/ndarray.cc)
        if a not in (None, "relu", "sigmoid", "tanh"):
            raise _Unmappable(f"activation {a!r}")
        return a

    def emit(b, prefix: str) -> None:
        if isinstance(b, HybridSequential):
            for name, child in b._children.items():
                emit(child, f"{prefix}{name}.")
            return
        if isinstance(b, Dense):
            nodes.append({
                "op": "dense", "weight": prefix + "weight",
                "bias": prefix + "bias" if b.bias is not None else None,
                "flatten": int(b._flatten),
                "activation": act_ok(b._activation)})
            return
        if isinstance(b, Conv2D):
            if (b._transpose or b._groups != 1 or b._layout != "NCHW"
                    or tuple(b._dilation) != (1, 1)):
                raise _Unmappable(repr(b))
            nodes.append({
                "op": "conv2d", "weight": prefix + "weight",
                "bias": prefix + "bias" if b.bias is not None else None,
                "stride": list(b._strides), "pad": list(b._padding),
                "activation": act_ok(b._activation)})
            return
        if isinstance(b, (MaxPool2D, AvgPool2D, GlobalMaxPool2D,
                          GlobalAvgPool2D)):
            if b._layout != "NCHW":
                raise _Unmappable(repr(b))
            nodes.append({
                "op": "maxpool2d" if b._pool_type == "max" else "avgpool2d",
                "kernel": list(b._kernel), "stride": list(b._strides),
                "pad": list(b._padding), "global": int(b._global),
                "count_include_pad": int(b._count_include_pad)})
            return
        if isinstance(b, BatchNorm):
            if b._axis not in (1, -3):
                raise _Unmappable(repr(b))
            nodes.append({
                "op": "batchnorm", "gamma": prefix + "gamma",
                "beta": prefix + "beta",
                "mean": prefix + "running_mean",
                "var": prefix + "running_var", "eps": float(b._epsilon)})
            return
        if isinstance(b, Activation):
            nodes.append({"op": "activation", "act": act_ok(b._act)})
            return
        if isinstance(b, Flatten):
            nodes.append({"op": "flatten"})
            return
        if isinstance(b, Dropout):
            return                      # identity at inference
        raise _Unmappable(type(b).__name__)

    try:
        emit(block, "")
    except _Unmappable:
        return None
    return nodes
