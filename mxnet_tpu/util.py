"""Utility helpers (``python/mxnet/util.py`` parity: set_np and friends)."""
from __future__ import annotations

_np_shape = True  # numpy semantics are the default and only mode
_np_array = True


def set_np(shape: bool = True, array: bool = True, dtype: bool = False) -> None:
    """Enable numpy semantics (``mx.npx.set_np``). Always on here."""
    global _np_shape, _np_array
    _np_shape, _np_array = shape, array


def reset_np() -> None:
    set_np(True, True)


def is_np_shape() -> bool:
    return _np_shape


def is_np_array() -> bool:
    return _np_array


def use_np(func):
    """Decorator parity shim — numpy semantics are always active."""
    return func


def np_shape(active: bool = True):
    class _Scope:
        def __enter__(self):
            return self
        def __exit__(self, *a):
            return False
    return _Scope()


np_array = np_shape
