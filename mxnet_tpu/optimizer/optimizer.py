"""Optimizers — fused, jit-compiled update steps.

Reference parity (leezu/mxnet): python registry/hyperparam layer
``python/mxnet/optimizer/optimizer.py`` (lr/wd multipliers, rescale_grad,
clip_gradient, multi-precision) and the fused C++/CUDA update kernels
``src/operator/optimizer_op.cc`` (`sgd_mom_update`, `adam_update`,
`lamb_update`, `multi_lars`, ...) and the leezu-authored
``src/operator/contrib/adamw.cc`` (decoupled weight decay).

Design (tpu-first): every optimizer's math is ONE pure function
``_step(w, g, states, lr, wd) -> (new_w, new_states)`` compiled once per
(optimizer, shape/dtype) with ``jax.jit`` and buffer donation — the analog
of the reference's fused FMutateInputs kernels, with XLA fusing the whole
update chain. lr/wd enter as device scalars so schedule changes never
retrigger compilation. Multi-precision (fp32 master weights for bf16/fp16
params) follows the reference's ``mp_sgd_*`` pattern.
"""
from __future__ import annotations

import math
from typing import (Any, Callable, Dict, List, NamedTuple, Optional,
                    Sequence, Tuple)

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError, getenv, register_env
from ..ndarray.ndarray import NDArray
from .. import engine

__all__ = ["Optimizer", "MasterWeightState", "register", "create"]


class MasterWeightState(NamedTuple):
    """fp32 master-weight wrapper for low-precision training state
    (reference: the ``mp_*_update`` multi-precision optimizer ops keep an
    fp32 copy beside fp16 weights).  A dedicated type — NamedTuples are
    jax pytrees — so the master-weight layout is recognized by
    ``isinstance`` rather than guessed from state structure."""
    master: Any
    inner: Any

_OPT_REGISTRY: Dict[str, type] = {}

register_env("MXNET_OPTIMIZER_AGGREGATION_SIZE", 4,
             "Number of parameters fused per multi-tensor update batch.")


def register(cls: type) -> type:
    _OPT_REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(name: str, **kwargs: Any) -> "Optimizer":
    """Instantiate a registered optimizer by name
    (``Optimizer.create_optimizer``)."""
    if name.lower() not in _OPT_REGISTRY:
        raise MXNetError(f"unknown optimizer {name!r}; "
                         f"known: {sorted(_OPT_REGISTRY)}")
    return _OPT_REGISTRY[name.lower()](**kwargs)


class Optimizer:
    """Base optimizer.

    Subclasses define ``create_state(index, weight)`` and the pure
    ``_step``; the base class owns hyperparams, schedules, multipliers,
    gradient rescale/clip, and the jit cache.
    """

    def __init__(self, learning_rate: float = 0.01,
                 rescale_grad: float = 1.0, clip_gradient: Optional[float] = None,
                 wd: float = 0.0, lr_scheduler: Any = None,
                 multi_precision: bool = False,
                 param_dict: Optional[Dict[int, Any]] = None,
                 begin_num_update: int = 0, **kwargs: Any) -> None:
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None and hasattr(lr_scheduler, "base_lr"):
            self.lr_scheduler.base_lr = learning_rate
        self.rescale_grad = rescale_grad
        self.clip_gradient = clip_gradient
        self.wd = wd
        self.multi_precision = multi_precision
        self.num_update = begin_num_update
        self.begin_num_update = begin_num_update
        self._index_update_count: Dict[int, int] = {}
        self._lr_mult: Dict[Any, float] = {}
        self._wd_mult: Dict[Any, float] = {}
        self.param_dict = param_dict or {}
        self._jit_cache: Dict[Any, Callable] = {}
        self.aggregate_num = getenv("MXNET_OPTIMIZER_AGGREGATION_SIZE", 4)

    # -- hyperparam plumbing (reference API) -------------------------------
    def set_learning_rate(self, lr: float) -> None:
        self.lr = lr

    @property
    def learning_rate(self) -> float:
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult: Dict[Any, float]) -> None:
        self._lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult: Dict[Any, float]) -> None:
        self._wd_mult = dict(args_wd_mult)

    def _get_lr(self, index: Any) -> float:
        lr = self.learning_rate
        param = self.param_dict.get(index)
        if param is not None:
            lr *= getattr(param, "lr_mult", 1.0)
        else:
            lr *= self._lr_mult.get(index, 1.0)
        return lr

    def _get_wd(self, index: Any) -> float:
        wd = self.wd
        param = self.param_dict.get(index)
        if param is not None:
            wd *= getattr(param, "wd_mult", 1.0)
        else:
            wd *= self._wd_mult.get(index, 1.0)
        return wd

    def _update_count(self, index: Any) -> None:
        self._index_update_count[index] = \
            self._index_update_count.get(index, self.begin_num_update) + 1
        self.num_update = max(self.num_update,
                              self._index_update_count[index])

    # -- state -------------------------------------------------------------
    def create_state(self, index: Any, weight: NDArray) -> Any:
        return ()

    def create_state_multi_precision(self, index: Any, weight: NDArray) -> Any:
        if self.multi_precision and weight.dtype in (_np.float16,) or \
                (self.multi_precision and "bfloat16" in str(weight.dtype)):
            master = weight._data.astype(jnp.float32)
            return MasterWeightState(master, self.create_state(index, weight))
        return self.create_state(index, weight)

    # -- the pure math; subclasses override --------------------------------
    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):  # pragma: no cover - abstract
        raise NotImplementedError

    def _hyper(self, index: Any) -> tuple:
        """Static (trace-baked) hyperparams; device scalars go via lr/wd."""
        return ()

    # -- update ------------------------------------------------------------
    def update(self, index: Any, weight: NDArray, grad: NDArray,
               state: Any) -> Any:
        """Apply one update in place on ``weight``; returns the new state.

        Equivalent of the reference's fused update op with
        FMutateInputs — mutation realized by rebinding the weight buffer.
        """
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        hp = self._hyper(index)
        cls = type(self)
        if getattr(grad, "stype", "default") == "row_sparse":
            done = self._sparse_update(index, weight, grad, state,
                                       lr, wd, hp)
            if done is not NotImplemented:
                return done
        cache_key = (cls, tuple(weight.shape), str(weight.dtype), hp,
                     self.clip_gradient is not None)
        stepfn = self._jit_cache.get(cache_key)
        if stepfn is None:
            has_clip = self.clip_gradient is not None

            def raw(w, g, states, lr_, wd_, t_, rescale_, clip_):
                g = g.astype(jnp.float32) if w.dtype != g.dtype else g
                g = g * rescale_
                if has_clip:
                    g = jnp.clip(g, -clip_, clip_)
                return cls._step(w, g, states, lr_, wd_, t_, hp)

            stepfn = jax.jit(raw, donate_argnums=(0, 2))
            self._jit_cache[cache_key] = stepfn

        t = self._index_update_count.get(index, self.begin_num_update)
        clip_val = self.clip_gradient if self.clip_gradient is not None else 0.0
        # stepfn donates the weight/state buffers: a pending bulked
        # segment still holding the old weight BY VALUE must materialize
        # first (targeted — unrelated threads' segments keep building)
        from .. import bulk as _bulk
        _bulk.flush_holding(
            [weight._data] + jax.tree_util.tree_leaves(state), "mutation")
        new_w, new_state = stepfn(weight._data, grad._data, state,
                                  jnp.float32(lr), jnp.float32(wd),
                                  jnp.float32(t),
                                  jnp.float32(self.rescale_grad),
                                  jnp.float32(clip_val))
        weight._data = new_w
        engine.track(new_w)
        return new_state

    def _sparse_update(self, index: Any, weight: NDArray, grad: Any,
                       state: Any, lr: float, wd: float, hp: tuple) -> Any:
        """Lazy row-sparse update: apply ``_step`` only on the touched rows
        (reference: the ``lazy_update`` row_sparse optimizer kernels).
        Returns NotImplemented when the state layout prevents row slicing
        (caller then densifies via the storage-fallback path)."""
        rows_dim = weight.shape[0]
        leaves, treedef = jax.tree_util.tree_flatten(state)
        if any(not hasattr(s, "shape") or not s.shape or
               s.shape[0] != rows_dim for s in leaves):
            return NotImplemented
        rsp = grad._canonical()
        rows = rsp._sp_indices
        if rows.shape[0] == 0:
            return state
        cls = type(self)
        g = rsp._sp_values
        w_rows = weight._data[rows]
        if w_rows.dtype != g.dtype:
            g = g.astype(jnp.float32)
        g = g * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        state_rows = jax.tree_util.tree_map(lambda s: s[rows], state)
        t = self._index_update_count.get(index, self.begin_num_update)
        new_w_rows, new_state_rows = cls._step(
            w_rows, g, state_rows, jnp.float32(lr), jnp.float32(wd),
            jnp.float32(t), hp)
        weight._data = weight._data.at[rows].set(
            new_w_rows.astype(weight._data.dtype))
        engine.track(weight._data)
        new_leaves = jax.tree_util.tree_leaves(new_state_rows)
        updated = [s.at[rows].set(nl.astype(s.dtype))
                   for s, nl in zip(leaves, new_leaves)]
        return jax.tree_util.tree_unflatten(treedef, updated)

    def update_multi_precision(self, index: Any, weight: NDArray,
                               grad: NDArray, state: Any) -> Any:
        # the master-weight layout is identified by TYPE, not structure:
        # guessing from (fp32-array, ...) tuples false-positives on
        # Adam-style (m, v) fp32 state under bf16 weights and silently
        # corrupts the update
        if isinstance(state, MasterWeightState):
            master_nd = NDArray(state.master, _wrap=True)
            new_inner = self.update(index, master_nd, grad, state.inner)
            weight._data = master_nd._data.astype(weight._data.dtype)
            engine.track(weight._data)
            return MasterWeightState(master_nd._data, new_inner)
        return self.update(index, weight, grad, state)

    def __repr__(self) -> str:
        return f"{type(self).__name__}(lr={self.learning_rate})"


@register
class SGD(Optimizer):
    """SGD with momentum (reference: ``sgd_update``/``sgd_mom_update``).

    state = momentum buffer. Math (reference optimizer_op-inl.h):
      m = mu*m + grad + wd*w ;  w -= lr*m    (mom)
      w -= lr*(grad + wd*w)                  (no mom)
    """

    def __init__(self, momentum: float = 0.0, lazy_update: bool = False,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return ()
        return (jnp.zeros_like(weight._data),)

    def _hyper(self, index):
        return (self.momentum,)

    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):
        (momentum,) = hp
        g = g + wd * w
        if momentum == 0.0:
            return w - lr * g.astype(w.dtype), ()
        (m,) = states
        m = momentum * m + g
        return (w - lr * m).astype(w.dtype), (m,)


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference: ``nag_mom_update``)."""

    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):
        (momentum,) = hp
        g = g + wd * w
        if momentum == 0.0:
            return (w - lr * g).astype(w.dtype), ()
        (m,) = states
        m = momentum * m + g
        return (w - lr * (g + momentum * m)).astype(w.dtype), (m,)


@register
class Adam(Optimizer):
    """Adam (reference: ``adam_update``). L2 via wd folded into grad."""

    def __init__(self, learning_rate: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8,
                 lazy_update: bool = False, **kwargs: Any) -> None:
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        def z():
            return jnp.zeros_like(weight._data, dtype=jnp.float32)
        return (z(), z())

    def _hyper(self, index):
        return (self.beta1, self.beta2, self.epsilon)

    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):
        beta1, beta2, eps = hp
        m, v = states
        g = g + wd * w
        m = beta1 * m + (1 - beta1) * g
        v = beta2 * v + (1 - beta2) * jnp.square(g)
        lr = lr * jnp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
        return (w - lr * m / (jnp.sqrt(v) + eps)).astype(w.dtype), (m, v)


@register
class AdamW(Adam):
    """Adam with decoupled weight decay — the leezu-authored
    ``_contrib_adamw_update`` (src/operator/contrib/adamw.cc)."""

    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):
        beta1, beta2, eps = hp
        m, v = states
        m = beta1 * m + (1 - beta1) * g
        v = beta2 * v + (1 - beta2) * jnp.square(g)
        mhat = m / (1 - beta1 ** t)
        vhat = v / (1 - beta2 ** t)
        upd = mhat / (jnp.sqrt(vhat) + eps) + wd * w
        return (w - lr * upd).astype(w.dtype), (m, v)


@register
class LAMB(Optimizer):
    """LAMB (BERT-era large-batch; reference: ``lamb_update_phase1/2``)."""

    def __init__(self, learning_rate: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-6,
                 lower_bound: Optional[float] = None,
                 upper_bound: Optional[float] = None,
                 bias_correction: bool = True, **kwargs: Any) -> None:
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        def z():
            return jnp.zeros_like(weight._data, dtype=jnp.float32)
        return (z(), z())

    def _hyper(self, index):
        return (self.beta1, self.beta2, self.epsilon,
                self.bias_correction, self.lower_bound, self.upper_bound)

    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):
        beta1, beta2, eps, bias_corr, lo, hi = hp
        m, v = states
        m = beta1 * m + (1 - beta1) * g
        v = beta2 * v + (1 - beta2) * jnp.square(g)
        mhat, vhat = m, v
        if bias_corr:
            mhat = m / (1 - beta1 ** t)
            vhat = v / (1 - beta2 ** t)
        r = mhat / (jnp.sqrt(vhat) + eps) + wd * w
        w_norm = jnp.linalg.norm(w.astype(jnp.float32))
        r_norm = jnp.linalg.norm(r.astype(jnp.float32))
        if lo is not None:
            w_norm = jnp.maximum(w_norm, lo)
        if hi is not None:
            w_norm = jnp.minimum(w_norm, hi)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (w - lr * trust * r).astype(w.dtype), (m, v)


@register
class LARS(Optimizer):
    """LARS layer-wise adaptive rate scaling (reference: ``multi_lars`` +
    ``preloaded_sgd_*``)."""

    def __init__(self, momentum: float = 0.0, eta: float = 0.001,
                 epsilon: float = 1e-8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.momentum, self.eta, self.epsilon = momentum, eta, epsilon

    def create_state(self, index, weight):
        return (jnp.zeros_like(weight._data),)

    def _hyper(self, index):
        return (self.momentum, self.eta, self.epsilon)

    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):
        momentum, eta, eps = hp
        (m,) = states
        w_norm = jnp.linalg.norm(w.astype(jnp.float32))
        g_norm = jnp.linalg.norm(g.astype(jnp.float32))
        trust = jnp.where((w_norm > 0) & (g_norm > 0),
                          eta * w_norm / (g_norm + wd * w_norm + eps), 1.0)
        g = g + wd * w
        m = momentum * m + trust * g
        return (w - lr * m).astype(w.dtype), (m,)


@register
class RMSProp(Optimizer):
    """RMSProp (reference: ``rmsprop_update`` / ``rmspropalex_update``)."""

    def __init__(self, learning_rate: float = 0.001, rho: float = 0.9,
                 momentum: float = 0.9, epsilon: float = 1e-8,
                 centered: bool = False, **kwargs: Any) -> None:
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho, self.momentum, self.epsilon = rho, momentum, epsilon
        self.centered = centered

    def create_state(self, index, weight):
        def z():
            return jnp.zeros_like(weight._data, dtype=jnp.float32)
        if self.centered:
            return (z(), z(), z())  # n, g_avg, delta
        return (z(),)

    def _hyper(self, index):
        return (self.rho, self.momentum, self.epsilon, self.centered)

    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):
        rho, momentum, eps, centered = hp
        g = g + wd * w
        if centered:
            n, gavg, delta = states
            n = rho * n + (1 - rho) * jnp.square(g)
            gavg = rho * gavg + (1 - rho) * g
            delta = momentum * delta - lr * g / jnp.sqrt(
                n - jnp.square(gavg) + eps)
            return (w + delta).astype(w.dtype), (n, gavg, delta)
        (n,) = states
        n = rho * n + (1 - rho) * jnp.square(g)
        return (w - lr * g / (jnp.sqrt(n) + eps)).astype(w.dtype), (n,)


@register
class AdaGrad(Optimizer):
    def __init__(self, learning_rate: float = 0.01, epsilon: float = 1e-7,
                 **kwargs: Any) -> None:
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (jnp.zeros_like(weight._data, dtype=jnp.float32),)

    def _hyper(self, index):
        return (self.epsilon,)

    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):
        (eps,) = hp
        (h,) = states
        g = g + wd * w
        h = h + jnp.square(g)
        return (w - lr * g / (jnp.sqrt(h) + eps)).astype(w.dtype), (h,)


@register
class AdaDelta(Optimizer):
    def __init__(self, learning_rate: float = 1.0, rho: float = 0.9,
                 epsilon: float = 1e-5, **kwargs: Any) -> None:
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        def z():
            return jnp.zeros_like(weight._data, dtype=jnp.float32)
        return (z(), z())

    def _hyper(self, index):
        return (self.rho, self.epsilon)

    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):
        rho, eps = hp
        acc_g, acc_d = states
        g = g + wd * w
        acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
        d = jnp.sqrt(acc_d + eps) / jnp.sqrt(acc_g + eps) * g
        acc_d = rho * acc_d + (1 - rho) * jnp.square(d)
        return (w - lr * d).astype(w.dtype), (acc_g, acc_d)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate: float = 0.002, beta1: float = 0.9,
                 beta2: float = 0.999, **kwargs: Any) -> None:
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        def z():
            return jnp.zeros_like(weight._data, dtype=jnp.float32)
        return (z(), z())

    def _hyper(self, index):
        return (self.beta1, self.beta2)

    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):
        beta1, beta2 = hp
        m, u = states
        g = g + wd * w
        m = beta1 * m + (1 - beta1) * g
        u = jnp.maximum(beta2 * u, jnp.abs(g))
        lr = lr / (1 - beta1 ** t)
        return (w - lr * m / (u + 1e-8)).astype(w.dtype), (m, u)


@register
class Ftrl(Optimizer):
    def __init__(self, learning_rate: float = 0.1, lamda1: float = 0.01,
                 beta: float = 1.0, **kwargs: Any) -> None:
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        def z():
            return jnp.zeros_like(weight._data, dtype=jnp.float32)
        return (z(), z())  # z, n

    def _hyper(self, index):
        return (self.lamda1, self.beta)

    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):
        lamda1, beta = hp
        z, n = states
        sigma = (jnp.sqrt(n + jnp.square(g)) - jnp.sqrt(n)) / lr
        z = z + g - sigma * w
        n = n + jnp.square(g)
        new_w = jnp.where(
            jnp.abs(z) <= lamda1, jnp.zeros_like(w),
            -(z - jnp.sign(z) * lamda1) /
            ((beta + jnp.sqrt(n)) / lr + wd))
        return new_w.astype(w.dtype), (z, n)


@register
class FTML(Optimizer):
    def __init__(self, learning_rate: float = 0.0025, beta1: float = 0.6,
                 beta2: float = 0.999, epsilon: float = 1e-8,
                 **kwargs: Any) -> None:
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        def z():
            return jnp.zeros_like(weight._data, dtype=jnp.float32)
        return (z(), z(), z())  # d, v, z

    def _hyper(self, index):
        return (self.beta1, self.beta2, self.epsilon)

    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):
        beta1, beta2, eps = hp
        d, v, z = states
        g = g + wd * w
        v = beta2 * v + (1 - beta2) * jnp.square(g)
        d_t = (1 - beta1 ** t) / lr * (
            jnp.sqrt(v / (1 - beta2 ** t)) + eps)
        sigma = d_t - beta1 * d
        z = beta1 * z + (1 - beta1) * g - sigma * w
        return (-z / d_t).astype(w.dtype), (d_t, v, z)


@register
class Signum(Optimizer):
    """signSGD with momentum (reference: ``signum_update``)."""

    def __init__(self, learning_rate: float = 0.01, momentum: float = 0.9,
                 wd_lh: float = 0.0, **kwargs: Any) -> None:
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.wd_lh = momentum, wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return ()
        return (jnp.zeros_like(weight._data),)

    def _hyper(self, index):
        return (self.momentum, self.wd_lh)

    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):
        momentum, wd_lh = hp
        if momentum == 0.0:
            return (w * (1 - lr * wd_lh) - lr * jnp.sign(g)).astype(w.dtype), ()
        (m,) = states
        m = momentum * m - (1 - momentum) * (g + wd * w)
        return (w * (1 - lr * wd_lh) + lr * jnp.sign(m)).astype(w.dtype), (m,)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference: sgld).

    Noise is drawn per step from the global threefry stream (eagerly, so
    every update gets a fresh subkey) and added outside the jitted step.
    """

    def create_state(self, index, weight):
        return ()

    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):
        return (w - lr / 2 * (g + wd * w)).astype(w.dtype), ()

    def update(self, index, weight, grad, state):
        from ..ndarray import random as _random
        state = super().update(index, weight, grad, state)
        lr = self._get_lr(index)
        noise = jax.random.normal(_random.split_key(), weight.shape,
                                  dtype=jnp.float32) * math.sqrt(lr)
        weight._data = (weight._data + noise.astype(weight._data.dtype))
        return state


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: dcasgd)."""

    def __init__(self, momentum: float = 0.0, lamda: float = 0.04,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.momentum, self.lamda = momentum, lamda

    def create_state(self, index, weight):
        # copy=True: the snapshot must be a DISTINCT buffer from the
        # weight, or donation of both in one Execute() is rejected
        return (jnp.zeros_like(weight._data),
                jnp.array(weight._data, dtype=jnp.float32,
                          copy=True))  # mom, prev_weight

    def _hyper(self, index):
        return (self.momentum, self.lamda)

    @staticmethod
    def _step(w, g, states, lr, wd, t, hp):
        momentum, lamda = hp
        m, prev_w = states
        g = g + wd * w
        comp = g + lamda * g * g * (w - prev_w)
        m = momentum * m - lr * comp
        return (w + m).astype(w.dtype), (m, w.astype(jnp.float32))


@register
class LBSGD(LARS):
    """Large-batch SGD (reference: lbsgd) — momentum SGD with the LARS
    layer-wise trust ratio, which is exactly the LARS update here."""

    def __init__(self, eta: float = 0.001, momentum: float = 0.9,
                 **kwargs: Any) -> None:
        super().__init__(momentum=momentum, eta=eta, **kwargs)


class Updater:
    """Stateful per-index updater (reference: ``get_updater`` — the object
    shipped to KVStore servers to apply updates)."""

    def __init__(self, optimizer: Optimizer) -> None:
        self.optimizer = optimizer
        self.states: Dict[Any, Any] = {}

    def __call__(self, index: Any, grad: NDArray, weight: NDArray) -> None:
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
        self.states[index] = self.optimizer.update_multi_precision(
            index, weight, grad, self.states[index])

    def get_states(self) -> Dict[Any, Any]:
        return self.states


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
