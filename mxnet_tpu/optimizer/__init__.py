"""Optimizer registry and implementations.

Reference parity: ``python/mxnet/optimizer/optimizer.py`` +
``src/operator/optimizer_op.cc`` / ``src/operator/contrib/adamw.cc``.
"""
from .optimizer import (Optimizer, MasterWeightState, register, create, SGD, NAG, Adam, AdamW,
                        LAMB, LARS, RMSProp, AdaGrad, AdaDelta, Adamax, Ftrl,
                        FTML, Signum, SGLD, DCASGD, LBSGD, Updater,
                        get_updater)

__all__ = ["Optimizer", "MasterWeightState", "register", "create", "SGD", "NAG", "Adam", "AdamW",
           "LAMB", "LARS", "RMSProp", "AdaGrad", "AdaDelta", "Adamax",
           "Ftrl", "FTML", "Signum", "SGLD", "DCASGD", "LBSGD", "Updater",
           "get_updater"]
