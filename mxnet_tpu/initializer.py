"""Weight initializers (reference: ``python/mxnet/initializer.py``).

Registry + the standard zoo: Zero/One/Constant/Uniform/Normal/Orthogonal/
Xavier/MSRAPrelu/LSTMBias/Bilinear. Initializers draw from the global
threefry stream (``mx.random``) so ``mx.random.seed`` reproduces networks.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as _np

from .context import Context
from .ndarray.ndarray import NDArray
from .ndarray import random as _random

__all__ = ["Initializer", "Zero", "One", "Constant", "Uniform", "Normal",
           "Orthogonal", "Xavier", "MSRAPrelu", "LSTMBias", "Bilinear",
           "register", "get"]

_REGISTRY: Dict[str, type] = {}


def register(cls: type) -> type:
    """Register an initializer under its lowercased class name."""
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def get(name: Any) -> "Initializer":
    """Resolve a name/instance to an Initializer (string kwargs parity)."""
    if isinstance(name, Initializer):
        return name
    if isinstance(name, str):
        key = name.lower()
        if key not in _REGISTRY:
            raise ValueError(f"unknown initializer {name!r}; "
                             f"known: {sorted(_REGISTRY)}")
        return _REGISTRY[key]()
    raise TypeError(f"cannot interpret {name!r} as an initializer")


class Initializer:
    """Base initializer. Subclasses implement `_init(shape, dtype, key)`
    returning a jax array."""

    def __call__(self, shape, dtype="float32", ctx: Optional[Context] = None
                 ) -> NDArray:
        data = self._init(tuple(shape), dtype)
        nd = NDArray(data, ctx=ctx)
        return nd

    # legacy signature: init(name, arr) mutating arr — supported via
    # init_array
    def init_array(self, name: str, arr: NDArray) -> None:
        arr._data = self._init(arr.shape, str(arr.dtype))

    def _init(self, shape, dtype):
        raise NotImplementedError

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"


@register
class Zero(Initializer):
    def _init(self, shape, dtype):
        return jnp.zeros(shape, dtype=dtype)


@register
class One(Initializer):
    def _init(self, shape, dtype):
        return jnp.ones(shape, dtype=dtype)


zeros = Zero
ones = One
_REGISTRY["zeros"] = Zero
_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value: float = 0.0) -> None:
        self.value = value

    def _init(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


@register
class Uniform(Initializer):
    """U(-scale, scale); the reference's default for weights."""

    def __init__(self, scale: float = 0.07) -> None:
        self.scale = scale

    def _init(self, shape, dtype):
        k = _random.split_key()
        return jax.random.uniform(k, shape, dtype=dtype,
                                  minval=-self.scale, maxval=self.scale)


@register
class Normal(Initializer):
    def __init__(self, sigma: float = 0.01) -> None:
        self.sigma = sigma

    def _init(self, shape, dtype):
        k = _random.split_key()
        return self.sigma * jax.random.normal(k, shape, dtype=dtype)


@register
class Orthogonal(Initializer):
    def __init__(self, scale: float = 1.414, rand_type: str = "uniform") -> None:
        self.scale = scale
        self.rand_type = rand_type

    def _init(self, shape, dtype):
        k = _random.split_key()
        nout = shape[0]
        nin = 1
        for s in shape[1:]:
            nin *= s
        if self.rand_type == "uniform":
            a = jax.random.uniform(k, (nout, nin), minval=-1.0, maxval=1.0)
        else:
            a = jax.random.normal(k, (nout, nin))
        u, _, v = jnp.linalg.svd(a, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        return (self.scale * q.reshape(shape)).astype(dtype)


@register
class Xavier(Initializer):
    """Xavier/Glorot; matches reference semantics incl. conv fan
    computation (python/mxnet/initializer.py Xavier)."""

    def __init__(self, rnd_type: str = "uniform",
                 factor_type: str = "avg", magnitude: float = 3.0) -> None:
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = magnitude

    def _init(self, shape, dtype):
        if len(shape) < 2:
            return jnp.zeros(shape, dtype=dtype)
        hw_scale = 1.0
        for s in shape[2:]:
            hw_scale *= s
        fan_in = shape[1] * hw_scale
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        else:
            factor = fan_out
        scale = math.sqrt(self.magnitude / factor)
        k = _random.split_key()
        if self.rnd_type == "uniform":
            return jax.random.uniform(k, shape, dtype=dtype,
                                      minval=-scale, maxval=scale)
        return scale * jax.random.normal(k, shape, dtype=dtype)


@register
class MSRAPrelu(Xavier):
    """Kaiming-He init (reference: MSRAPrelu)."""

    def __init__(self, factor_type: str = "avg", slope: float = 0.25) -> None:
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)


@register
class LSTMBias(Initializer):
    """Zeros except forget-gate bias = 1 (reference: LSTMBias)."""

    def __init__(self, forget_bias: float = 1.0) -> None:
        self.forget_bias = forget_bias

    def _init(self, shape, dtype):
        b = jnp.zeros(shape, dtype=dtype)
        n = shape[0] // 4
        return b.at[n:2 * n].set(self.forget_bias)


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel for deconvolution."""

    def _init(self, shape, dtype):
        weight = _np.zeros(shape, dtype="float32")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        flat = weight.reshape(-1)
        for i in range(flat.size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return jnp.asarray(weight.reshape(shape), dtype=dtype)
