"""Learning-rate schedulers (reference: ``python/mxnet/lr_scheduler.py``).

FactorScheduler, MultiFactorScheduler, PolyScheduler, CosineScheduler —
all with linear warmup, same call protocol ``lr = sched(num_update)``.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler"]


class LRScheduler:
    def __init__(self, base_lr: float = 0.01, warmup_steps: int = 0,
                 warmup_begin_lr: float = 0.0,
                 warmup_mode: str = "linear") -> None:
        self.base_lr = base_lr
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr
        self.warmup_final_lr = base_lr
        self.warmup_mode = warmup_mode

    def get_warmup_lr(self, num_update: int) -> float:
        if self.warmup_mode == "linear":
            inc = (self.warmup_final_lr - self.warmup_begin_lr) \
                * num_update / max(self.warmup_steps, 1)
            return self.warmup_begin_lr + inc
        return self.warmup_final_lr  # constant

    def __call__(self, num_update: int) -> float:
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    """lr *= factor every ``step`` updates (with optional floor)."""

    def __init__(self, step: int, factor: float = 1.0,
                 stop_factor_lr: float = 1e-8, base_lr: float = 0.01,
                 **kwargs) -> None:
        super().__init__(base_lr, **kwargs)
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr

    def __call__(self, num_update: int) -> float:
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        exp = (num_update - self.warmup_steps) // self.step
        lr = self.base_lr * (self.factor ** exp)
        return max(lr, self.stop_factor_lr)


class MultiFactorScheduler(LRScheduler):
    """lr *= factor at each listed step (the classic ResNet schedule)."""

    def __init__(self, step: Sequence[int], factor: float = 1.0,
                 base_lr: float = 0.01, **kwargs) -> None:
        super().__init__(base_lr, **kwargs)
        self.step = sorted(step)
        self.factor = factor

    def __call__(self, num_update: int) -> float:
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        lr = self.base_lr
        for s in self.step:
            if num_update >= s:
                lr *= self.factor
        return lr


class PolyScheduler(LRScheduler):
    def __init__(self, max_update: int, base_lr: float = 0.01,
                 pwr: float = 2, final_lr: float = 0.0, **kwargs) -> None:
        super().__init__(base_lr, **kwargs)
        self.max_update = max_update
        self.power = pwr
        self.final_lr = final_lr

    def __call__(self, num_update: int) -> float:
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        n = min(num_update, self.max_update) - self.warmup_steps
        span = max(self.max_update - self.warmup_steps, 1)
        return self.final_lr + (self.base_lr - self.final_lr) * \
            (1 - n / span) ** self.power


class CosineScheduler(LRScheduler):
    def __init__(self, max_update: int, base_lr: float = 0.01,
                 final_lr: float = 0.0, **kwargs) -> None:
        super().__init__(base_lr, **kwargs)
        self.max_update = max_update
        self.final_lr = final_lr

    def __call__(self, num_update: int) -> float:
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        n = min(num_update, self.max_update) - self.warmup_steps
        span = max(self.max_update - self.warmup_steps, 1)
        return self.final_lr + (self.base_lr - self.final_lr) * \
            (1 + math.cos(math.pi * n / span)) / 2
