"""Runtime metrics — process-wide counters, gauges, and histograms.

NEW capability beyond the reference (no leezu/mxnet analog): the
reference's observability stops at the profiler (one traced window) and
``Monitor`` (per-op stats for one tic/toc span).  Neither answers "what
has the runtime been doing over this whole training run" — recompiles,
collective traffic, step-time composition.  This module is that
substrate: a process-wide, thread-safe registry of labeled metric
families, instrumented at the framework's existing choke points:

* **dispatch** (``ndarray/register.py``): every op invocation counts
  into ``mxnet_ops_dispatched_total{op=...}``; the per-op executable
  cache reports hits (``mxnet_compile_hits_total``), and a
  ``jax.monitoring`` listener counts real XLA backend compiles into
  ``mxnet_compile_misses_total`` + ``mxnet_compile_seconds`` — a silent
  recompile storm becomes a visible counter, not a mystery slowdown.
* **engine** (``engine.py``): waitall barriers (count + latency),
  live-buffer registry size and sweeps, async-error translations.
* **collectives** (``kvstore.py`` / ``parallel/ring.py``): allreduce /
  allgather calls, wire bytes, wall time.  Eager collectives (kvstore)
  count per execution; traced collectives (ring attention inside a
  compiled step) count at trace time — one count per compiled program,
  noted under the ``traced="1"`` label.
* **training loop** (``gluon/trainer.py``, ``parallel/spmd.py``, the
  contrib estimator): per-step histograms split into data-wait /
  dispatch / device-sync, a steps/sec gauge, and the device-memory
  high-watermark where the backend exposes it.

Exposition: :func:`dump_json` (machine-readable), :func:`render_text`
(Prometheus text format), and an optional background logger thread
(``MXNET_METRICS_LOG_INTERVAL`` seconds; 0 = off).  ``reset()`` zeroes
every series so test suites stay order-independent.

The registry is always on: an increment is a dict lookup plus a locked
float add, orders of magnitude below the cost of the op dispatch it
counts.  Label cardinality is bounded per family
(``MXNET_METRICS_MAX_SERIES``): past the cap, new label combinations
collapse into a single ``_other_`` series rather than growing without
bound (a user loop dispatching generated op names must not OOM the
registry).
"""
from __future__ import annotations

import bisect
import json
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .base import MXNetError, getenv, register_env

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "counter", "gauge", "histogram", "dump_json", "render_text",
    "reset", "value", "start_logger", "stop_logger",
    "DEFAULT_BUCKETS", "exponential_buckets",
]

register_env("MXNET_METRICS_LOG_INTERVAL", 0,
             "Seconds between background dumps of the runtime metrics "
             "registry to the 'mxnet_tpu.metrics' logger (JSON, non-zero "
             "series only). 0 (default) disables the logger thread.")
register_env("MXNET_METRICS_MAX_SERIES", 512,
             "Per-family label-cardinality bound for the runtime metrics "
             "registry: past this many distinct label combinations, new "
             "ones collapse into a single '_other_' series (guards "
             "against unbounded registry growth from generated names).")

# Fixed exponential buckets: 100us .. ~52s, factor 2 — wide enough for
# everything from a single eager dispatch to a cold-compile train step.
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(
    1e-4 * (2.0 ** i) for i in range(20))


def exponential_buckets(start: float, factor: float,
                        count: int) -> Tuple[float, ...]:
    """``count`` bucket bounds ``start, start*factor, ...`` — the
    prometheus-client helper, for histograms whose domain is not the
    DEFAULT_BUCKETS seconds range (e.g. serving batch sizes)."""
    if start <= 0 or factor <= 1 or count < 1:
        raise MXNetError(
            f"exponential_buckets needs start>0, factor>1, count>=1; "
            f"got ({start}, {factor}, {count})")
    return tuple(start * (factor ** i) for i in range(count))


def _validate_name(name: str) -> None:
    import re
    if not re.match(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$", name):
        raise MXNetError(f"invalid metric name {name!r}")


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Family:
    """Base: a named metric with a fixed label-key tuple and one child
    per observed label-value combination."""

    kind = "untyped"

    def __init__(self, name: str, doc: str,
                 labels: Sequence[str] = ()) -> None:
        _validate_name(name)
        self.name = name
        self.doc = " ".join(doc.split())
        self.label_names = tuple(labels)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Any] = {}
        if not self.label_names:
            self._children[()] = self._new_child()

    # -- child management --------------------------------------------------
    def _new_child(self) -> Any:
        raise NotImplementedError

    def labels(self, *values: Any, **kv: Any) -> "_Family":
        """Return a bound single-series view (prometheus-client style)."""
        if kv:
            if values:
                raise MXNetError("pass label values positionally OR by "
                                 "keyword, not both")
            if set(kv) != set(self.label_names):
                raise MXNetError(
                    f"metric {self.name!r} expects labels "
                    f"{self.label_names}, got {sorted(kv)}")
            values = tuple(kv[k] for k in self.label_names)
        vals = tuple(str(v) for v in values)
        if len(vals) != len(self.label_names):
            raise MXNetError(
                f"metric {self.name!r} expects {len(self.label_names)} "
                f"label values {self.label_names}, got {len(vals)}")
        return _Bound(self, self._child(vals))

    def _child(self, vals: Tuple[str, ...]) -> Any:
        child = self._children.get(vals)
        if child is None:
            with self._lock:
                child = self._children.get(vals)
                if child is None:
                    cap = int(getenv("MXNET_METRICS_MAX_SERIES", 512))
                    if len(self._children) >= cap:
                        # cardinality guard: collapse the overflow into
                        # one sentinel series instead of growing forever
                        vals = ("_other_",) * len(self.label_names)
                        child = self._children.get(vals)
                        if child is not None:
                            return child
                    child = self._children[vals] = self._new_child()
        return child

    def _default(self) -> Any:
        if self.label_names:
            raise MXNetError(
                f"metric {self.name!r} has labels {self.label_names}; "
                "bind them with .labels(...) first")
        return self._children[()]

    def reset(self) -> None:
        with self._lock:
            if self.label_names:
                self._children.clear()
            else:
                self._children = {(): self._new_child()}

    # -- exposition --------------------------------------------------------
    def _series(self) -> List[Tuple[Tuple[str, ...], Any]]:
        with self._lock:
            return sorted(self._children.items())

    def to_json(self) -> Dict[str, Any]:
        out = {"type": self.kind, "doc": self.doc,
               "labels": list(self.label_names), "series": []}
        for vals, child in self._series():
            out["series"].append(
                {"labels": dict(zip(self.label_names, vals)),
                 **child.to_json()})
        return out

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.doc}",
                 f"# TYPE {self.name} {self.kind}"]
        for vals, child in self._series():
            lines.extend(child.render(self.name, self.label_names, vals))
        return lines


def _label_str(names: Sequence[str], vals: Sequence[str],
               extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in zip(names, vals)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Bound:
    """One series of a family, with the value methods of its kind."""

    __slots__ = ("_family", "_child")

    def __init__(self, family: _Family, child: Any) -> None:
        self._family = family
        self._child = child

    def inc(self, amount: float = 1.0) -> None:
        self._child.inc(self._family._lock, amount)

    def dec(self, amount: float = 1.0) -> None:
        self._child.inc(self._family._lock, -amount)

    def set(self, v: float) -> None:
        self._child.set(self._family._lock, v)

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        self._child.observe(self._family._lock, v, exemplar)

    @property
    def value(self) -> float:
        return self._child.value


class _CounterChild:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, lock: threading.Lock, amount: float) -> None:
        if amount < 0:
            raise MXNetError("counters only go up; use a gauge")
        with lock:
            self.value += amount

    def to_json(self) -> Dict[str, Any]:
        return {"value": self.value}

    def render(self, name, label_names, vals) -> List[str]:
        return [f"{name}{_label_str(label_names, vals)} "
                f"{_format_value(self.value)}"]


class _GaugeChild(_CounterChild):
    def inc(self, lock: threading.Lock, amount: float) -> None:
        with lock:
            self.value += amount

    def set(self, lock: threading.Lock, v: float) -> None:
        with lock:
            self.value = float(v)


#: an exemplar older than this is replaced by the next offered one even
#: when slower observations were seen since — "most recent slow", not
#: "all-time max", so a bad p99 points at a trace that still exists
_EXEMPLAR_TTL_S = 60.0


class _HistogramChild:
    __slots__ = ("bounds", "counts", "sum", "count", "exemplar", "pref")

    def __init__(self, bounds: Tuple[float, ...],
                 pref: str = "max") -> None:
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0
        # (trace_id, value, unix time) of the most-extreme recent
        # observation that carried a trace id (tracing exemplar
        # linkage).  ``pref`` picks the direction: "max" keeps the
        # slowest/largest recent value (latency histograms), "min" the
        # smallest (e.g. the worst-accepting speculative step, where
        # LOW is the pathology worth a trace)
        self.exemplar: Optional[Tuple[str, float, float]] = None
        self.pref = pref

    def observe(self, lock: threading.Lock, v: float,
                exemplar: Optional[str] = None) -> None:
        v = float(v)
        idx = bisect.bisect_left(self.bounds, v)
        with lock:
            self.counts[idx] += 1
            self.sum += v
            self.count += 1
            if exemplar is not None:
                ex = self.exemplar
                now = time.time()
                extreme = ex is not None and (
                    v <= ex[1] if self.pref == "min" else v >= ex[1])
                if ex is None or extreme \
                        or now - ex[2] > _EXEMPLAR_TTL_S:
                    self.exemplar = (str(exemplar), v, now)

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "buckets": [[b, c] for b, c in
                        zip(list(self.bounds) + ["+Inf"],
                            _cumulative(self.counts))],
            "sum": self.sum, "count": self.count}
        ex = self.exemplar
        if ex is not None:
            out["exemplar"] = {"trace_id": ex[0], "value": ex[1],
                               "ts": ex[2]}
        return out

    def render(self, name, label_names, vals) -> List[str]:
        lines = []
        for b, c in zip(list(self.bounds) + ["+Inf"],
                        _cumulative(self.counts)):
            le = b if isinstance(b, str) else _format_value(b)
            le_pair = 'le="%s"' % le
            lines.append(
                f"{name}_bucket"
                f"{_label_str(label_names, vals, le_pair)} {c}")
        lines.append(f"{name}_sum{_label_str(label_names, vals)} "
                     f"{_format_value(self.sum)}")
        lines.append(f"{name}_count{_label_str(label_names, vals)} "
                     f"{self.count}")
        return lines


def _cumulative(counts: Sequence[int]) -> List[int]:
    out, acc = [], 0
    for c in counts:
        acc += c
        out.append(acc)
    return out


def _format_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Counter(_Family):
    kind = "counter"

    def _new_child(self) -> _CounterChild:
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(self._lock, amount)

    @property
    def value(self) -> float:
        return self._default().value


class Gauge(_Family):
    kind = "gauge"

    def _new_child(self) -> _GaugeChild:
        return _GaugeChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(self._lock, amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().inc(self._lock, -amount)

    def set(self, v: float) -> None:
        self._default().set(self._lock, v)

    @property
    def value(self) -> float:
        return self._default().value


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name: str, doc: str, labels: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None,
                 exemplar_pref: str = "max") -> None:
        bounds = tuple(sorted(float(b) for b in
                              (buckets if buckets is not None
                               else DEFAULT_BUCKETS)))
        if not bounds:
            raise MXNetError("histogram needs at least one bucket bound")
        if exemplar_pref not in ("max", "min"):
            raise MXNetError(
                f"exemplar_pref must be 'max' or 'min', got "
                f"{exemplar_pref!r}")
        self.bounds = bounds
        self.exemplar_pref = exemplar_pref
        super().__init__(name, doc, labels)

    def _new_child(self) -> _HistogramChild:
        return _HistogramChild(self.bounds, self.exemplar_pref)

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        self._default().observe(self._lock, v, exemplar)

    @property
    def sum(self) -> float:
        return self._default().sum

    @property
    def count(self) -> int:
        return self._default().count


# Hot-path cache for the per-op dispatch counter: one dict lookup per
# dispatch (see inc_op).  reset() must drop it — its bound children
# point at pre-reset series objects.
_OP_CHILDREN: Dict[str, _Bound] = {}


class MetricsRegistry:
    """Process-wide named family registry with exposition."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: "Dict[str, _Family]" = {}

    def _register(self, cls, name: str, doc: str, labels=(),
                  **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or \
                        fam.label_names != tuple(labels):
                    raise MXNetError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind} with labels {fam.label_names}")
                return fam
            fam = cls(name, doc, labels, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, doc: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._register(Counter, name, doc, labels)

    def gauge(self, name: str, doc: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge, name, doc, labels)

    def histogram(self, name: str, doc: str = "",
                  labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None,
                  exemplar_pref: str = "max") -> Histogram:
        return self._register(Histogram, name, doc, labels,
                              buckets=buckets,
                              exemplar_pref=exemplar_pref)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def reset(self) -> None:
        """Zero every series (registrations survive) — test isolation."""
        with self._lock:
            fams = list(self._families.values())
        for f in fams:
            f.reset()
        _OP_CHILDREN.clear()
        _BULK_REASON_CHILDREN.clear()
        _BWD_SEG_CHILDREN.clear()

    def dump_json(self) -> Dict[str, Any]:
        with self._lock:
            fams = sorted(self._families.items())
        return {name: fam.to_json() for name, fam in fams}

    def render_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            fams = sorted(self._families.items())
        lines: List[str] = []
        for _, fam in fams:
            lines.extend(fam.render())
        return "\n".join(lines) + "\n"


REGISTRY = MetricsRegistry()


def counter(name: str, doc: str = "",
            labels: Sequence[str] = ()) -> Counter:
    """Get-or-create a counter family on the global registry."""
    return REGISTRY.counter(name, doc, labels)


def gauge(name: str, doc: str = "", labels: Sequence[str] = ()) -> Gauge:
    """Get-or-create a gauge family on the global registry."""
    return REGISTRY.gauge(name, doc, labels)


def histogram(name: str, doc: str = "", labels: Sequence[str] = (),
              buckets: Optional[Sequence[float]] = None,
              exemplar_pref: str = "max") -> Histogram:
    """Get-or-create a histogram family on the global registry."""
    return REGISTRY.histogram(name, doc, labels, buckets, exemplar_pref)


def dump_json() -> Dict[str, Any]:
    return REGISTRY.dump_json()


def render_text() -> str:
    return REGISTRY.render_text()


def reset() -> None:
    REGISTRY.reset()


def _peek(fam: _Family, labels: Dict[str, Any]) -> Any:
    """Read-only series lookup: unlike fam.labels(...), never
    instantiates a child, so probing a never-observed combination does
    not pollute the exposition or consume a cardinality slot."""
    if labels:
        if set(labels) != set(fam.label_names):
            return None
        vals = tuple(str(labels[k]) for k in fam.label_names)
    else:
        if fam.label_names:
            return None
        vals = ()
    with fam._lock:
        return fam._children.get(vals)


def value(name: str, /, **labels: Any) -> float:
    """Current value of a counter/gauge series (0.0 if never touched,
    or if the name is a histogram — use :func:`hist_stats` there) — the
    delta-reading helper tools build breakdowns from."""
    fam = REGISTRY.get(name)
    if fam is None or isinstance(fam, Histogram):
        return 0.0
    child = _peek(fam, labels)
    return float(child.value) if child is not None else 0.0


def hist_stats(name: str, /, **labels: Any) -> Tuple[float, int]:
    """(sum, count) of a histogram series (zeros if never observed)."""
    fam = REGISTRY.get(name)
    if fam is None or not isinstance(fam, Histogram):
        return 0.0, 0
    child = _peek(fam, labels)
    if child is None:
        return 0.0, 0
    return float(child.sum), int(child.count)


# ---------------------------------------------------------------------------
# Core instrumentation families (created eagerly so exposition shows the
# full surface even before first use)
# ---------------------------------------------------------------------------

OPS_DISPATCHED = counter(
    "mxnet_ops_dispatched_total",
    "Imperative op dispatches through ndarray.register.invoke, by op "
    "name.", labels=("op",))
COMPILE_MISSES = counter(
    "mxnet_compile_misses_total",
    "XLA backend compilations (jax.monitoring backend_compile events): "
    "every one is a traced program that missed all compile caches.")
COMPILE_HITS = counter(
    "mxnet_compile_hits_total",
    "Per-op executable-cache hits on the eager dispatch path (the call "
    "reused a compiled executable instead of tracing).")
COMPILE_SECONDS = histogram(
    "mxnet_compile_seconds",
    "Wall time of XLA backend compilations (jax.monitoring).")
EXEC_CACHE_SIZE = gauge(
    "mxnet_exec_cache_size",
    "Entries in the per-op executable cache (ndarray.register).")

ENGINE_WAITALL = counter(
    "mxnet_engine_waitall_total",
    "waitall() barriers on outstanding device work.")
ENGINE_WAITALL_SECONDS = histogram(
    "mxnet_engine_waitall_seconds",
    "Wall time blocked inside waitall() barriers.")
ENGINE_LIVE_BUFFERS = gauge(
    "mxnet_engine_live_buffers",
    "Device arrays in the engine's live weak registry.")
ENGINE_SWEEPS = counter(
    "mxnet_engine_sweeps_total",
    "Dead-entry sweeps of the engine's weak registries.")
ENGINE_SYNC_ERRORS = counter(
    "mxnet_engine_sync_errors_total",
    "Async device errors translated to MXNetError at sync points.")

COLLECTIVE_CALLS = counter(
    "mxnet_collective_calls_total",
    "Collective operations by kind. Eager collectives (kvstore) count "
    "per execution; traced ones (ring attention) count per trace, "
    "marked traced=\"1\".", labels=("collective", "traced"))
COLLECTIVE_BYTES = counter(
    "mxnet_collective_bytes_total",
    "Payload bytes this process contributed to collectives, by kind.",
    labels=("collective", "traced"))
COLLECTIVE_SECONDS = histogram(
    "mxnet_collective_seconds",
    "Wall time of eager collective operations, by kind.",
    labels=("collective",))
KVSTORE_PUSHES = counter(
    "mxnet_kvstore_pushes_total",
    "KVStore push() calls (gradient reductions entering the store).")

STEP_SECONDS = histogram(
    "mxnet_step_seconds",
    "Training-step wall time at the trainer boundary (dispatch side: "
    "data placement + program dispatch; device sync is the separate "
    "mxnet_step_sync_seconds component).")
STEP_DATA_SECONDS = histogram(
    "mxnet_step_data_seconds",
    "Per-step time waiting on input data (loader wait + host->device "
    "placement).")
STEP_DISPATCH_SECONDS = histogram(
    "mxnet_step_dispatch_seconds",
    "Per-step time dispatching the training computation (returns before "
    "the device finishes).")
STEP_SYNC_SECONDS = histogram(
    "mxnet_step_sync_seconds",
    "Per-step time blocked on device results (loss fetch / metric "
    "update).")
TRAINER_STEP_SECONDS = histogram(
    "mxnet_trainer_step_seconds",
    "gluon.Trainer.step wall time (gradient reduction + optimizer "
    "update dispatch). The estimator/SPMD loop-level view is "
    "mxnet_step_seconds.")
STEPS_TOTAL = counter(
    "mxnet_steps_total", "Optimizer steps taken.")
STEPS_PER_SECOND = gauge(
    "mxnet_steps_per_second",
    "Inverse wall time of the most recent training step.")
DEVICE_MEM_HIGHWATER = gauge(
    "mxnet_device_mem_highwater_bytes",
    "Device memory high-watermark (peak_bytes_in_use) where the "
    "backend exposes memory_stats; 0 elsewhere.")

MONITOR_STAT = gauge(
    "mxnet_monitor_stat",
    "Latest scalar statistic per op output collected by mx.monitor."
    "Monitor (set at toc()).", labels=("name",))

BULK_SEGMENTS = counter(
    "mxnet_bulk_segments_total",
    "Pending eager-op segments flushed by the lazy bulking engine "
    "(mxnet_tpu/bulk.py), by flush reason: host_read (asnumpy/item/"
    "direct buffer access), max_ops (MXNET_BULK_MAX_OPS reached), "
    "unjittable (an op that cannot trace arrived), mutation (in-place "
    "write to a promised buffer), waitall (engine barrier), autograd "
    "(backward boundary / record-scope transition), cross_thread "
    "(another thread read a promised buffer), param_boundary (per-"
    "layer backward segmentation closed the recorded segment at a "
    "parameter boundary — MXNET_BULK_BACKWARD_SEGMENTS=param).",
    labels=("reason",))
BULK_CACHE_HITS = counter(
    "mxnet_bulk_seg_cache_hits_total",
    "Segment flushes that reused a compiled fused executable (segment-"
    "signature cache hit).")
BULK_CACHE_MISSES = counter(
    "mxnet_bulk_seg_cache_misses_total",
    "Segment flushes that traced + compiled a new fused executable. "
    "Steady-state training should report 0 new misses after warmup.")
BULK_CACHE_SIZE = gauge(
    "mxnet_bulk_seg_cache_size",
    "Compiled fused segment executables held by the bulking engine's "
    "signature cache (LRU-bounded).")
BULK_OPS_PER_SEGMENT = histogram(
    "mxnet_bulk_ops_per_segment",
    "Ops per flushed bulking segment (1 means the flush trigger arrived "
    "before a second op could join).",
    buckets=exponential_buckets(1.0, 2.0, 8))
BULK_BACKWARD_SEGMENTS = counter(
    "mxnet_bulk_backward_segments_total",
    "Per-layer backward-segmentation events under "
    "MXNET_BULK_BACKWARD_SEGMENTS=param (bulk.py), by reason: "
    "param_boundary (a recorded segment was cut because the op stream "
    "crossed a fresh attach_grad leaf with the coalescing floor met — "
    "its gradients will stream during backward; moves in lockstep "
    "with mxnet_bulk_segments_total{reason=param_boundary}, which "
    "counts the same cuts as flushes), coalesced (a parameter "
    "boundary was crossed but the segment's captured parameter bytes "
    "were still under the MXNET_KV_BUCKET_BYTES floor, so the layers "
    "share a segment — the decision the flush counter cannot see).",
    labels=("reason",))

# -- continuous-batching generation engine (serving/generation.py) ----------
GEN_SLOTS_ACTIVE = gauge(
    "mxnet_gen_slots_active",
    "Decode slots currently occupied by an in-flight generation "
    "sequence (<= MXNET_GEN_MAX_SLOTS).")
GEN_QUEUE_DEPTH = gauge(
    "mxnet_gen_queue_depth",
    "Generation requests waiting in the prefill admission queue (the "
    "decode 'queue' is the slot table itself — see "
    "mxnet_gen_slots_active).")
GEN_TOKENS_TOTAL = counter(
    "mxnet_gen_tokens_total",
    "Tokens produced by the generation engine, by phase: 'prefill' "
    "(the first token of each sequence, emitted by the prompt pass) "
    "and 'decode' (every token from the resident decode step).",
    labels=("phase",))
GEN_STEP_SECONDS = histogram(
    "mxnet_gen_step_seconds",
    "Wall time of one generation-engine model execution, by phase "
    "(prefill = one prompt admitted; decode = one iteration over ALL "
    "active slots) — the prefill/decode split of engine time.",
    labels=("phase",),
    buckets=exponential_buckets(0.0005, 2.0, 14))
GEN_TTFT_SECONDS = histogram(
    "mxnet_gen_ttft_seconds",
    "Time-to-first-token per generation request: submit to the first "
    "streamed token (queue wait + prefill).",
    buckets=exponential_buckets(0.001, 2.0, 14))
GEN_ITERATIONS_TOTAL = counter(
    "mxnet_gen_iterations_total",
    "Decode-loop iterations executed (each runs the resident decode "
    "step once over every active slot).")
GEN_ADMISSIONS_TOTAL = counter(
    "mxnet_gen_admissions_total",
    "Generation requests admitted into a decode slot (prefill ran).")
GEN_RETIREMENTS_TOTAL = counter(
    "mxnet_gen_retirements_total",
    "Generation sequences retired from their slot, by reason: eos / "
    "length (max-tokens) / error / cancelled.", labels=("reason",))
GEN_TOKENS_PER_SECOND = gauge(
    "mxnet_gen_tokens_per_second",
    "Aggregate decode throughput over the engine's most recent "
    "iteration window (streamed tokens across all slots).")
GEN_KV_BUCKET_LEN = gauge(
    "mxnet_gen_kv_bucket_len",
    "Current KV-cache capacity bucket (padded sequence length every "
    "slot's cache is allocated at).")
GEN_KV_MIGRATIONS_TOTAL = counter(
    "mxnet_gen_kv_migrations_total",
    "KV-cache capacity-bucket migrations (cache grew to the next "
    "power-of-two length bucket; each switches the engine to that "
    "bucket's pre-compiled decode step).")
GEN_SAMPLED_TOKENS_TOTAL = counter(
    "mxnet_gen_sampled_tokens_total",
    "Tokens emitted by the generation engine, by decode method "
    "(greedy / sample / top_k / top_p) — the on-device sampler keeps "
    "every method inside the compiled step, so the split is free to "
    "observe.", labels=("method",))
GEN_PREFIX_HITS_TOTAL = counter(
    "mxnet_gen_prefix_cache_hits_total",
    "Generation admissions that reused a resident shared-prefix KV "
    "entry (rows copied into the slot instead of re-running prefill "
    "over the prefix).")
GEN_PREFIX_MISSES_TOTAL = counter(
    "mxnet_gen_prefix_cache_misses_total",
    "Generation admissions that found no resident prefix for a "
    "cacheable prompt and ran a full cold prefill (the prefix rows "
    "are inserted for the next request).")
GEN_PREFIX_EVICTIONS_TOTAL = counter(
    "mxnet_gen_prefix_cache_evictions_total",
    "Shared-prefix KV entries evicted (LRU among unreferenced entries "
    "once the cache exceeds MXNET_GEN_PREFIX_CACHE_SLOTS).")
GEN_PREFIX_ROWS = gauge(
    "mxnet_gen_prefix_cache_rows",
    "KV positions (padded prefix rows, summed over resident entries) "
    "currently held in the shared-prefix cache — the device-memory "
    "footprint is rows x layers x heads x head_dim x 2 (K and V).")
GEN_SPEC_PROPOSED_TOKENS_TOTAL = counter(
    "mxnet_gen_spec_proposed_tokens_total",
    "Draft tokens proposed by the speculative-decoding subsystem "
    "(serving/speculation.py): k per speculative slot per iteration, "
    "before the target model's verify pass accepts or rejects them.")
GEN_SPEC_ACCEPTED_TOKENS_TOTAL = counter(
    "mxnet_gen_spec_accepted_tokens_total",
    "Draft tokens ACCEPTED by the verify pass: the draft token equaled "
    "the target's own sampled token at that position under the "
    "request's counter-PRNG key (greedy requests compare against the "
    "argmax). The accept rule makes speculative output byte-identical "
    "to non-speculative output at the same seed.")
GEN_SPEC_REJECTED_TOKENS_TOTAL = counter(
    "mxnet_gen_spec_rejected_tokens_total",
    "Draft tokens rejected by the verify pass (everything proposed "
    "after the first mismatch is discarded and the KV rows it wrote "
    "roll back — see mxnet_gen_kv_rollbacks_total).")
GEN_SPEC_ACCEPT_RATE = gauge(
    "mxnet_gen_spec_accept_rate",
    "Fraction of proposed draft tokens accepted over the engine's "
    "lifetime (accepted / proposed; 0 until the first speculative "
    "iteration). The economics dial of speculative decoding: uplift "
    "~ (1 + k * accept_rate) tokens per target step minus draft cost.")
GEN_SPEC_ACCEPTED_PER_STEP = histogram(
    "mxnet_gen_spec_accepted_per_step",
    "Tokens emitted per speculative slot-step (1 bonus token + the "
    "accepted draft prefix; 1 means every draft was rejected). The "
    "exemplar carries the trace id of the WORST-accepting recent step "
    "(lowest value), so a sagging accept rate points at a concrete "
    "iteration trace.",
    buckets=exponential_buckets(1.0, 2.0, 6), exemplar_pref="min")
GEN_KV_ROLLBACKS_TOTAL = counter(
    "mxnet_gen_kv_rollbacks_total",
    "PagedKVCache.truncate() rollbacks: slot positions rewound after "
    "the verify pass rejected draft tokens (their speculatively "
    "written KV rows become invisible to the position mask and are "
    "overwritten by the next accepted token).")

# -- async device-prefetch input pipeline (io/prefetch.py) ------------------
PREFETCH_QUEUE_DEPTH = gauge(
    "mxnet_prefetch_queue_depth",
    "Device-resident batches currently queued ahead of the training "
    "step by the DevicePrefetcher (<= MXNET_PREFETCH_DEPTH). Pinned at "
    "0 while the consumer outruns the loader — pair with "
    "mxnet_prefetch_stall_seconds to tell which side is the "
    "bottleneck.")
PREFETCH_H2D_SECONDS = histogram(
    "mxnet_prefetch_h2d_seconds",
    "Per-batch host->device placement time inside the prefetch thread "
    "(sharded device_put / commit of the already-fetched batch). This "
    "work overlaps the in-flight step; it only costs wall-clock when "
    "it exceeds the step time.",
    buckets=exponential_buckets(0.0005, 2.0, 14))
PREFETCH_STALL_SECONDS = histogram(
    "mxnet_prefetch_stall_seconds",
    "Per-step time the TRAINING LOOP spent blocked waiting for the "
    "prefetcher to produce its batch — the key input-pipeline number: "
    "~0 means input is fully hidden behind device compute; a majority "
    "share of mxnet_step_seconds means the loader (or H2D) is the "
    "bottleneck.",
    buckets=exponential_buckets(0.0005, 2.0, 14))
PREFETCH_BATCHES_TOTAL = counter(
    "mxnet_prefetch_batches_total",
    "Batches fetched, placed on device, and queued by the "
    "DevicePrefetcher background thread.")
PREFETCH_INVALIDATED = counter(
    "mxnet_prefetch_invalidated_total",
    "Prefetched-batch invalidations (the queue is flushed and the "
    "producer reseeks), by reason: 'seek' (non-consecutive step "
    "request — checkpoint restore / resume), 'salt' (HealthGuard "
    "rewind perturbed the replay salt), 'close' (pipeline shutdown).",
    labels=("reason",))

# -- serving resilience (serving/server.py + serving/replica.py) ------------
SERVING_RECOVERIES_TOTAL = counter(
    "mxnet_serving_recoveries_total",
    "Generation sequences resurrected after a fault, by recovery site: "
    "'decode' (a decode-step fault — the sequence re-prefills "
    "prompt+emitted on a healthy replica and resumes), 'worker' (a "
    "slot-resident sequence evacuated from a dead worker replica), "
    "'queue' (a not-yet-admitted request requeued from a dead "
    "replica's admission queue).", labels=("site",))
SERVING_RECOVERED_TOKENS = counter(
    "mxnet_serving_recovered_tokens_total",
    "Tokens already emitted by sequences at the moment they were "
    "resurrected (the re-prefill work recovery pays; the TokenStream "
    "index dedupe guarantees clients never see them twice).")
SERVING_RECOVERY_SECONDS = histogram(
    "mxnet_serving_recovery_seconds",
    "Per-sequence recovery latency: fault observed to the resurrected "
    "sequence's next streamed token (re-queue wait + re-prefill).",
    buckets=exponential_buckets(0.001, 2.0, 14))
SERVING_STREAM_DUPES_DROPPED = counter(
    "mxnet_serving_stream_dupes_dropped_total",
    "Duplicate tokens dropped at the TokenStream index boundary (a "
    "recovered producer re-emitted an index the consumer already has). "
    "Nonzero means the dedupe guard did real work; clients still see "
    "each index exactly once.")
SERVING_DRAINING = gauge(
    "mxnet_serving_draining",
    "1 while the serving process is draining (SIGTERM received: "
    "admissions shed with 429, resident work finishing, readiness "
    "503 / liveness 200).")


def record_step(total: float, data: float = 0.0, dispatch: float = 0.0,
                sync: Optional[float] = None, count: int = 1) -> None:
    """Observe one training step's phase breakdown (seconds).  Called by
    the loop owners (SPMDTrainer.step, the estimator fit loop); tools
    read the sums back with :func:`hist_stats`.  ``count`` > 1 marks a
    fused multi-step program (one observation, N optimizer steps)."""
    STEP_SECONDS.observe(total)
    STEP_DATA_SECONDS.observe(data)
    STEP_DISPATCH_SECONDS.observe(dispatch)
    if sync is not None:
        STEP_SYNC_SECONDS.observe(sync)
    STEPS_TOTAL.inc(count)
    if total > 0:
        STEPS_PER_SECOND.set(count / total)


_HIGHWATER_LAST = [0.0]      # monotonic seconds of the last real query
_HIGHWATER_MIN_INTERVAL_S = 1.0


def record_device_highwater() -> None:
    """Update the device-memory high-watermark gauge if the backend
    exposes memory_stats (TPU does; XLA:CPU returns None).

    Sampled at most once per second: the peak is monotonic within a
    run, and on remote backends ``memory_stats()`` is a host<->device
    round-trip — per-step it re-serializes the very loop the async
    input pipeline unblocks."""
    try:
        now = time.monotonic()
        if now - _HIGHWATER_LAST[0] < _HIGHWATER_MIN_INTERVAL_S:
            return
        _HIGHWATER_LAST[0] = now
        import jax
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            peak = stats.get("peak_bytes_in_use",
                             stats.get("bytes_in_use", 0))
            if peak:
                DEVICE_MEM_HIGHWATER.set(float(peak))
    except Exception:   # noqa: BLE001 - backend-dependent surface
        pass


def inc_op(name: str) -> None:
    """Count one op dispatch (called from ndarray.register.invoke)."""
    b = _OP_CHILDREN.get(name)
    if b is None:
        b = _OP_CHILDREN[name] = OPS_DISPATCHED.labels(op=name)
    b.inc()


# Hot-path cache for per-reason segment-flush counters (same pattern as
# _OP_CHILDREN; reset() drops it).
_BULK_REASON_CHILDREN: Dict[str, _Bound] = {}


def inc_bulk_segment(reason: str) -> None:
    """Count one bulking-segment flush (called from bulk.Segment.flush)."""
    b = _BULK_REASON_CHILDREN.get(reason)
    if b is None:
        b = _BULK_REASON_CHILDREN[reason] = BULK_SEGMENTS.labels(
            reason=reason)
    b.inc()


# Hot-path cache for the backward-segmentation event counter (the cut
# decision runs once per recorded op append).
_BWD_SEG_CHILDREN: Dict[str, _Bound] = {}


def inc_backward_segment(reason: str) -> None:
    """Count one backward-segmentation event (bulk.try_append's
    param-boundary cut decision)."""
    b = _BWD_SEG_CHILDREN.get(reason)
    if b is None:
        b = _BWD_SEG_CHILDREN[reason] = BULK_BACKWARD_SEGMENTS.labels(
            reason=reason)
    b.inc()


# ---------------------------------------------------------------------------
# jax.monitoring bridge: real XLA backend compiles -> compile-miss counter
# ---------------------------------------------------------------------------

_JAX_HOOK = {"installed": False}


def _install_jax_hooks() -> None:
    if _JAX_HOOK["installed"]:
        return
    _JAX_HOOK["installed"] = True
    try:
        from jax import monitoring as _mon

        def _on_duration(event: str, duration: float, **kw: Any) -> None:
            if event.endswith("backend_compile_duration") or \
                    event.endswith("backend_compile_time_sec"):
                COMPILE_MISSES.inc()
                COMPILE_SECONDS.observe(duration)

        _mon.register_event_duration_secs_listener(_on_duration)
    except Exception:   # noqa: BLE001 - older jax without monitoring
        pass


_install_jax_hooks()


# ---------------------------------------------------------------------------
# Periodic logger thread (MXNET_METRICS_LOG_INTERVAL)
# ---------------------------------------------------------------------------

_LOGGER_STATE: Dict[str, Any] = {"thread": None, "stop": None}


def _nonzero_summary() -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for name, fam in dump_json().items():
        series = []
        for s in fam["series"]:
            if fam["type"] == "histogram":
                if s.get("count"):
                    series.append({"labels": s["labels"],
                                   "sum": round(s["sum"], 6),
                                   "count": s["count"]})
            elif s.get("value"):
                series.append({"labels": s["labels"],
                               "value": s["value"]})
        if series:
            out[name] = series
    return out


def start_logger(interval: Optional[float] = None) -> bool:
    """Start the background metrics logger (idempotent). Returns True if
    a thread is running after the call."""
    if interval is None:
        interval = float(getenv("MXNET_METRICS_LOG_INTERVAL", 0))
    if interval <= 0:
        return False
    if _LOGGER_STATE["thread"] is not None and \
            _LOGGER_STATE["thread"].is_alive():
        return True
    import logging
    log = logging.getLogger("mxnet_tpu.metrics")
    stop = threading.Event()

    def _run() -> None:
        while not stop.wait(interval):
            try:
                log.info("metrics %s", json.dumps(_nonzero_summary()))
            except Exception:   # noqa: BLE001 - never kill the app
                pass

    th = threading.Thread(target=_run, name="mxnet-metrics-logger",
                          daemon=True)
    _LOGGER_STATE["thread"], _LOGGER_STATE["stop"] = th, stop
    th.start()
    return True


def stop_logger() -> None:
    stop = _LOGGER_STATE["stop"]
    if stop is not None:
        stop.set()
    th = _LOGGER_STATE["thread"]
    if th is not None:
        th.join(timeout=2.0)
    _LOGGER_STATE["thread"] = _LOGGER_STATE["stop"] = None


if float(getenv("MXNET_METRICS_LOG_INTERVAL", 0)) > 0:
    start_logger()
