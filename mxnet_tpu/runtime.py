"""``mx.runtime`` — runtime feature introspection.

Reference parity (leezu/mxnet): ``src/libinfo.cc`` / ``python/mxnet/
runtime.py`` — build-time ``USE_*`` flags surfaced as ``Features``.  Here
features are determined at import time from the live environment (which
backend jax sees, whether the native runtime library built, etc.) since
there is no compile-time feature matrix.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Iterator

__all__ = ["Feature", "Features", "feature_list", "list_env"]

from .base import list_env  # noqa: E402  (env-var config surface)


class Feature:
    """One runtime feature flag (reference: ``mx.runtime.Feature``)."""

    def __init__(self, name: str, enabled: bool) -> None:
        self.name = name
        self.enabled = enabled

    def __bool__(self) -> bool:
        return self.enabled

    def __repr__(self) -> str:
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _detect() -> "OrderedDict[str, Feature]":
    feats: "OrderedDict[str, Feature]" = OrderedDict()

    def add(name: str, enabled: bool) -> None:
        feats[name] = Feature(name, bool(enabled))

    try:
        import jax
        platforms = {d.platform for d in jax.devices()}
    except Exception:   # noqa: BLE001 - backend init can fail headless
        platforms = set()
    add("TPU", bool(platforms & {"tpu", "axon"}))
    add("CPU", True)
    add("CUDA", "gpu" in platforms or "cuda" in platforms)

    add("BF16", True)                 # always available on XLA
    add("INT64_TENSOR_SIZE", True)
    try:
        from jax.experimental import pallas  # noqa: F401
        add("PALLAS", True)
    except Exception:   # noqa: BLE001
        add("PALLAS", False)

    try:
        from ._native import LIB
        add("NATIVE_ENGINE", LIB is not None)
    except Exception:   # noqa: BLE001
        add("NATIVE_ENGINE", False)

    add("SPARSE", True)
    add("AMP", True)
    add("RECORDIO", True)
    add("PROFILER", True)
    add("DIST_KVSTORE", True)         # ICI/DCN collectives via jax.sharding
    add("SIGNAL_HANDLER", False)
    add("OPENCV", False)              # PIL-backed decode instead
    try:
        import PIL  # noqa: F401
        add("IMAGE_IO", True)
    except Exception:   # noqa: BLE001
        add("IMAGE_IO", False)
    return feats


class Features:
    """Mapping of feature name -> :class:`Feature`
    (reference: ``mx.runtime.Features``, ``libinfo_features``)."""

    def __init__(self) -> None:
        self._feats = _detect()

    def __getitem__(self, name: str) -> Feature:
        return self._feats[name]

    def __contains__(self, name: str) -> bool:
        return name in self._feats

    def __iter__(self) -> Iterator[str]:
        return iter(self._feats)

    def keys(self):
        return self._feats.keys()

    def values(self):
        return self._feats.values()

    def items(self):
        return self._feats.items()

    def is_enabled(self, name: str) -> bool:
        """True if the named feature is available
        (reference: ``Features.is_enabled``)."""
        return name in self._feats and self._feats[name].enabled

    def __repr__(self) -> str:
        return " ".join(repr(f) for f in self._feats.values())


def feature_list() -> list:
    """List of all runtime features (reference: ``mx.runtime.feature_list``)."""
    return list(Features().values())
