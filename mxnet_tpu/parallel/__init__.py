"""``mx.parallel`` — device-mesh SPMD training.

Reference parity (leezu/mxnet): this package REPLACES the reference's
distributed stack (``src/kvstore/`` + ps-lite + NCCL, SURVEY.md 2.3/3.5)
with the TPU-native model: one ``jax.sharding.Mesh`` under everything,
parameters/activations annotated with PartitionSpecs, XLA inserting the
collectives over ICI/DCN. Strategies the reference never had (TP/SP) are
new capability here, exposed as sharding rules (SURVEY.md 5.7/5.8).
"""
from .mesh import (make_mesh, mesh_axes, replicated, shard_batch,
                   slice_groups)
from .spmd import (PartitionRules, SPMDTrainer, DEFAULT_TRANSFORMER_RULES,
                   DATA_PARALLEL_RULES)
from .ring import ring_attention, local_ring_attention
from .pipeline import (pipeline_apply, pipeline_train_grads, GPTPipe,
                       PIPELINE_RULES)
from .moe import MoEDense, MOE_RULES, MOE_TRANSFORMER_RULES

__all__ = ["make_mesh", "mesh_axes", "replicated", "shard_batch",
           "slice_groups",
           "PartitionRules", "SPMDTrainer", "DEFAULT_TRANSFORMER_RULES",
           "DATA_PARALLEL_RULES", "ring_attention", "local_ring_attention",
           "pipeline_apply", "pipeline_train_grads", "MoEDense",
           "MOE_RULES", "MOE_TRANSFORMER_RULES"]
