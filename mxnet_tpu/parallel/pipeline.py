"""Pipeline parallelism — GPipe-style microbatch schedule over a mesh axis.

NEW capability beyond the reference (SURVEY.md 2.3): leezu/mxnet's closest
analog is manual ``ctx_group`` model parallelism with cross-device copy
nodes; it has no pipeline schedule.  Here stage parameters are stacked on a
leading axis sharded over ``pp``; microbatches flow stage-to-stage via
``ppermute`` inside a ``lax.scan`` (the scaling-book pipelining recipe),
so each hop is one ICI neighbor transfer and XLA overlaps compute with the
collective.

Schedule: ``num_microbatches + num_stages - 1`` ticks (the GPipe bubble);
differentiable end to end — reverse-mode runs the reverse schedule
automatically through the scan/ppermute transpose.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["pipeline_apply", "GPTPipe", "PIPELINE_RULES"]


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        from jax import shard_map
        kw = {"check_vma": False}
    except ImportError:     # jax < 0.8
        from jax.experimental.shard_map import shard_map
        kw = {"check_rep": False}
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     **kw)


def pipeline_apply(stage_fn: Callable, stage_params: Any, x: "jax.Array",
                   mesh: "jax.sharding.Mesh", axis: str = "pp",
                   num_microbatches: Optional[int] = None) -> "jax.Array":
    """Apply ``num_stages`` chained stages to ``x`` with a GPipe schedule.

    stage_fn(params_i, h) -> h' — one stage's computation; the activation
    shape must be the same for every stage (classic pipeline constraint).
    stage_params: pytree whose leaves have leading dim ``num_stages``
    (stage i's slice feeds stage i), sharded over mesh axis ``axis``.
    x: (B, ...) global batch; split into microbatches along dim 0.

    Returns stage_{N-1}(...stage_0(x)) with shape x.shape.
    """
    if axis not in mesh.axis_names:
        # degenerate: run stages sequentially on one device
        n = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
        h = x
        for i in range(n):
            h = stage_fn(jax.tree_util.tree_map(lambda a: a[i],
                                                stage_params), h)
        return h

    n_stages = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} must equal mesh "
                f"axis '{axis}' size {n_stages} (one stage per device)")
    n_micro = num_microbatches or n_stages
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible into {n_micro} "
                         f"microbatches")
    mb = B // n_micro
    x_mb = x.reshape((n_micro, mb) + x.shape[1:])

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def local(params, x_mb):
        # params leaves: (1, ...) own stage slice; x_mb: (n_micro, mb, ...)
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        stage = jax.lax.axis_index(axis)
        state0 = jnp.zeros_like(x_mb[0])
        out_buf0 = jnp.zeros_like(x_mb)

        @jax.checkpoint
        def tick(carry, t):
            state, out_buf = carry
            # stage 0 ingests microbatch t (clamped; masked by `where`)
            inp = x_mb[jnp.clip(t, 0, n_micro - 1)]
            feed = jnp.logical_and(stage == 0, t < n_micro)
            h = jnp.where(feed, inp, state)
            h = stage_fn(params, h)
            # last stage banks finished microbatch t-(n_stages-1)
            done_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            bank = jnp.logical_and(stage == n_stages - 1,
                                   t >= n_stages - 1)
            out_buf = jnp.where(
                bank,
                jax.lax.dynamic_update_index_in_dim(out_buf, h, done_idx, 0),
                out_buf)
            # hand activations to the next stage
            state = jax.lax.ppermute(h, axis, perm)
            return (state, out_buf), None

        (_, out_buf), _ = jax.lax.scan(
            tick, (state0, out_buf0), jnp.arange(n_micro + n_stages - 1))
        return out_buf[None]

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    out = _shard_map(local, mesh,
                     in_specs=(pspec, P()), out_specs=P(axis))(
        stage_params, x_mb)
    # the bank is only populated on the last stage; its slice is the result
    out = out[-1]
    return out.reshape((B,) + x.shape[1:])


# ---------------------------------------------------------------------------
# Real-model pipeline parallelism: GPT blocks as pipeline stages
# ---------------------------------------------------------------------------

from .spmd import PartitionRules  # noqa: E402  (no gluon<->parallel cycle)
from ..gluon.block import HybridBlock  # noqa: E402

PIPELINE_RULES = PartitionRules([
    # stacked per-stage block weights: leading (stage) dim over pp
    (r"stage_", P("pp")),
])


class GPTPipe(HybridBlock):
    """GPT whose transformer blocks run as GPipe pipeline stages.

    Beyond-reference capability (SURVEY.md 2.3: PP absent upstream) on a
    REAL model: the per-block weights live as stacked ``(num_layers, ...)``
    parameters sharded over the mesh's ``pp`` axis (PIPELINE_RULES), and
    forward streams microbatches through ONE template :class:`GPTBlock`
    whose buffers are rebound per stage (``_bind_params``) inside
    :func:`pipeline_apply` — the block math is the model zoo's own, not a
    reimplementation. Works under SPMDTrainer (the stacked params are
    ordinary Parameters).

    Dropout is forced to 0 inside the pipeline (per-tick RNG inside the
    scan is not threaded); embed/head dropout would go outside the stages.
    """

    def __init__(self, mesh, vocab_size: int = 50257, num_layers: int = 4,
                 units: int = 256, hidden_size: int = 1024,
                 num_heads: int = 4, max_length: int = 512,
                 num_microbatches: Optional[int] = None,
                 axis: str = "pp", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        from ..gluon.model_zoo.gpt import GPTBlock
        from ..gluon.nn import Embedding, LayerNorm
        from ..gluon.parameter import Parameter

        self._mesh = mesh
        self._axis = axis
        self._n_micro = num_microbatches
        self._units = units
        self._max_length = max_length
        self._num_layers = num_layers

        self.word_embed = Embedding(vocab_size, units)
        self.position_weight = Parameter(
            "position_weight", shape=(max_length, units), init="normal")
        self.ln_f = LayerNorm(epsilon=1e-5, in_channels=units)

        # template block: supplies the stage math; its own (tiny) buffers
        # are bind targets only, never trained — bypass child registration
        tpl = GPTBlock(units, hidden_size, num_heads, dropout=0.0)
        tpl.initialize()
        object.__setattr__(self, "_template", tpl)
        tpl_params = list(tpl.collect_params().items())
        object.__setattr__(self, "_tpl_params",
                           [p for _, p in tpl_params])
        for name, p in tpl_params:
            sp = Parameter("stage_" + name.replace(".", "_"),
                           shape=(num_layers,) + tuple(p.shape),
                           init=getattr(p, "init", None) or "uniform")
            setattr(self, "stage_" + name.replace(".", "_"), sp)
        object.__setattr__(
            self, "_stacked",
            [getattr(self, "stage_" + name.replace(".", "_"))
             for name, _ in tpl_params])

    def load_block_weights(self, gpt_model) -> None:
        """Copy a :class:`GPTModel`'s per-block weights into the stacked
        stage parameters (for parity tests / converting a trained model)."""
        from ..ndarray.ndarray import NDArray
        blocks = list(gpt_model.blocks._children.values())
        assert len(blocks) == self._num_layers, \
            (len(blocks), self._num_layers)
        per_block = [list(b.collect_params().values()) for b in blocks]
        for k, sp in enumerate(self._stacked):
            stacked = jnp.stack(
                [per_block[i][k].data()._data
                 for i in range(self._num_layers)])
            sp.set_data(NDArray(stacked))

    def _mesh_place(self, nd, spec):
        """Commit an NDArray's buffer to this mesh (writes back), or pass
        tracers through untouched."""
        arr = nd._data
        if isinstance(arr, jax.core.Tracer):
            return arr
        sh = jax.sharding.NamedSharding(self._mesh, spec)
        cur = getattr(arr, "sharding", None)
        if cur is not None and (cur == sh or (
                hasattr(cur, "is_equivalent_to") and
                cur.is_equivalent_to(sh, arr.ndim))):
            return arr
        arr = jax.device_put(arr, sh)
        nd._data = arr
        from .. import engine
        from ..ndarray.register import mark_mesh_resident
        engine.mark_clean(arr)
        if sh.num_devices > 1:
            mark_mesh_resident(nd)   # wrapper outlives per-step buffers
        return arr

    def forward(self, tokens):
        from ..gluon.block import _bind_params
        from ..ndarray.ndarray import from_jax
        from ..ndarray import ops
        from .. import numpy as mxnp
        # eager ops downstream of the pipeline mix mesh-sharded activations
        # with single-device params; the per-op harmonization scan engages
        # via mark_mesh_resident on each placed buffer (and disengages when
        # the last one is collected)
        T = tokens.shape[1]
        if not self.position_weight.is_initialized:
            self.position_weight._finish_deferred_init(
                (self._max_length, self._units))
        x = self.word_embed(tokens)
        pos = ops.slice_axis(self.position_weight.data(), axis=0,
                             begin=0, end=T)
        x = x + pos.expand_dims(0)

        tpl = self._template
        tpl_params = self._tpl_params

        def stage_fn(param_slices, h):
            with _bind_params(tpl_params, param_slices):
                out = tpl.forward(from_jax(h))
            return out._data

        # eager path: stacked weights must live sharded over the pp mesh
        # (write back so the placement is paid once); tracers are already
        # placed by the enclosing pjit (SPMDTrainer rules)
        arrays = []
        for p in self._stacked:
            nd = p.data()
            arrays.append(self._mesh_place(nd, P(self._axis)))
        h = self._mesh_place(x, P())
        out = pipeline_apply(stage_fn, arrays, h, self._mesh,
                             axis=self._axis,
                             num_microbatches=self._n_micro)
        if not isinstance(out, jax.core.Tracer) \
                and getattr(out, "sharding", None) is not None \
                and out.sharding.num_devices > 1:
            from ..ndarray.register import mark_mesh_resident
            mark_mesh_resident(out)
        x = self.ln_f(from_jax(out))
        w = self.word_embed.weight.data()
        return mxnp.matmul(x, w.T)
