"""Pipeline parallelism — GPipe-style microbatch schedule over a mesh axis.

NEW capability beyond the reference (SURVEY.md 2.3): leezu/mxnet's closest
analog is manual ``ctx_group`` model parallelism with cross-device copy
nodes; it has no pipeline schedule.  Here stage parameters are stacked on a
leading axis sharded over ``pp``; microbatches flow stage-to-stage via
``ppermute`` inside a ``lax.scan`` (the scaling-book pipelining recipe),
so each hop is one ICI neighbor transfer and XLA overlaps compute with the
collective.

Schedule: ``num_microbatches + num_stages - 1`` ticks (the GPipe bubble);
differentiable end to end — reverse-mode runs the reverse schedule
automatically through the scan/ppermute transpose.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["pipeline_apply", "pipeline_train_grads", "GPTPipe",
           "PIPELINE_RULES"]


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        from jax import shard_map
        kw = {"check_vma": False}
    except ImportError:     # jax < 0.8
        from jax.experimental.shard_map import shard_map
        kw = {"check_rep": False}
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     **kw)


def pipeline_apply(stage_fn: Callable, stage_params: Any, x: "jax.Array",
                   mesh: "jax.sharding.Mesh", axis: str = "pp",
                   num_microbatches: Optional[int] = None,
                   rng_key: Optional["jax.Array"] = None,
                   batch_axis: Optional[str] = None) -> "jax.Array":
    """Apply ``num_stages`` chained stages to ``x`` with a GPipe schedule.

    stage_fn(params_i, h) -> h' — one stage's computation; the activation
    shape must be the same for every stage (classic pipeline constraint).
    stage_params: pytree whose leaves have leading dim ``num_stages``
    (stage i's slice feeds stage i), sharded over mesh axis ``axis``.
    x: (B, ...) global batch; split into microbatches along dim 0.
    rng_key: when given, ``stage_fn`` is called as
    ``stage_fn(params_i, h, key)`` with a key folded per (tick, stage) —
    the plumbing that makes in-pipeline dropout draw fresh randomness for
    every microbatch at every stage (and regenerate identically in the
    scan's recompute-for-backward).
    batch_axis (r3): a mesh axis to shard each microbatch's batch dim
    over — pp COMPOSES with dp in one program (each dp row pipelines its
    own batch slice; gradient reduction over dp is GSPMD's psum as
    usual). Ignored when absent from the mesh or non-divisible.

    Returns stage_{N-1}(...stage_0(x)) with shape x.shape.
    """
    def call_stage(params, h, m, stage):
        # key folds on (microbatch, stage) — NOT the tick — so the 1F1B
        # backward's recompute (different tick) regenerates the same
        # dropout masks as the forward
        if rng_key is None:
            return stage_fn(params, h)
        key = jax.random.fold_in(jax.random.fold_in(rng_key, m), stage)
        return stage_fn(params, h, key)

    if axis not in mesh.axis_names:
        # degenerate: run stages sequentially on one device
        n = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
        h = x
        for i in range(n):
            h = call_stage(jax.tree_util.tree_map(lambda a: a[i],
                                                  stage_params), h, i, i)
        return h

    n_stages = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} must equal mesh "
                f"axis '{axis}' size {n_stages} (one stage per device)")
    n_micro = num_microbatches or n_stages
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible into {n_micro} "
                         f"microbatches")
    mb = B // n_micro
    x_mb = x.reshape((n_micro, mb) + x.shape[1:])

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def local(params, x_mb):
        # params leaves: (1, ...) own stage slice; x_mb: (n_micro, mb, ...)
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        stage = jax.lax.axis_index(axis)
        state0 = jnp.zeros_like(x_mb[0])
        out_buf0 = jnp.zeros_like(x_mb)

        @jax.checkpoint
        def tick(carry, t):
            state, out_buf = carry
            # stage 0 ingests microbatch t (clamped; masked by `where`)
            inp = x_mb[jnp.clip(t, 0, n_micro - 1)]
            feed = jnp.logical_and(stage == 0, t < n_micro)
            h = jnp.where(feed, inp, state)
            h = call_stage(params, h, jnp.clip(t - stage, 0, n_micro - 1),
                           stage)
            # last stage banks finished microbatch t-(n_stages-1)
            done_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            bank = jnp.logical_and(stage == n_stages - 1,
                                   t >= n_stages - 1)
            out_buf = jnp.where(
                bank,
                jax.lax.dynamic_update_index_in_dim(out_buf, h, done_idx, 0),
                out_buf)
            # hand activations to the next stage
            state = jax.lax.ppermute(h, axis, perm)
            return (state, out_buf), None

        (_, out_buf), _ = jax.lax.scan(
            tick, (state0, out_buf0), jnp.arange(n_micro + n_stages - 1))
        return out_buf[None]

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    bax = (batch_axis if batch_axis and batch_axis in mesh.axis_names
           and batch_axis != axis and mb % mesh.shape[batch_axis] == 0
           else None)
    out = _shard_map(local, mesh,
                     in_specs=(pspec, P(None, bax)),
                     out_specs=P(axis, None, bax))(
        stage_params, x_mb)
    # the bank is only populated on the last stage; its slice is the result
    out = out[-1]
    return out.reshape((B,) + x.shape[1:])


# ---------------------------------------------------------------------------
# 1F1B schedule: hand-scheduled forward+backward in one pass
# ---------------------------------------------------------------------------

def _simulate_1f1b(S: int, M: int):
    """Host-side 1F1B schedule simulation → static per-tick work tables.

    Classic one-forward-one-backward discipline: stage ``s`` may hold at
    most ``S - s`` microbatches in flight (warmup), then strictly
    alternates backward/forward. Each global tick has a forward phase
    and a backward phase; activations/cotangents transfer at tick end
    and are consumable from the next tick (the last stage turns its own
    fresh forward around within the same tick).

    Returns int32 arrays ``(fwd, bwd, arr_f, arr_b)`` of shape (T, S):
    the microbatch each stage forwards / backwards at tick k (-1 idle),
    and the microbatch whose activation / cotangent ARRIVES at stage s
    at tick k (what the previous tick's ppermute carried).
    """
    import numpy as onp
    fwd_done = onp.full((S, M), -1, onp.int64)
    bwd_done = onp.full((S, M), -1, onp.int64)
    next_fwd = [0] * S
    next_bwd = [0] * S
    rows_f, rows_b = [], []
    k = 0
    while any(n < M for n in next_bwd):
        if k > 4 * (M + S) + 8:
            raise AssertionError("1F1B schedule simulation did not "
                                 f"converge (S={S}, M={M})")
        row_f = [-1] * S
        # forward phase: decisions depend only on prior ticks
        for s in range(S):
            m = next_fwd[s]
            if m >= M:
                continue
            if next_fwd[s] - next_bwd[s] >= S - s:   # 1F1B in-flight cap
                continue
            if s > 0 and not (0 <= fwd_done[s - 1][m] < k):
                continue
            row_f[s] = m
            fwd_done[s][m] = k
            next_fwd[s] += 1
        row_b = [-1] * S
        # backward phase: the last stage may consume its same-tick fwd
        for s in range(S):
            m = next_bwd[s]
            if m >= M:
                continue
            if s == S - 1:
                ok = 0 <= fwd_done[s][m] <= k
            else:
                ok = 0 <= bwd_done[s + 1][m] < k
            if ok:
                row_b[s] = m
                bwd_done[s][m] = k
                next_bwd[s] += 1
        rows_f.append(row_f)
        rows_b.append(row_b)
        k += 1
    fwd = onp.asarray(rows_f, onp.int32)
    bwd = onp.asarray(rows_b, onp.int32)
    T = fwd.shape[0]
    arr_f = onp.full((T, S), -1, onp.int32)
    arr_b = onp.full((T, S), -1, onp.int32)
    for kk in range(1, T):
        for s in range(1, S):
            arr_f[kk][s] = fwd[kk - 1][s - 1]
        for s in range(S - 1):
            arr_b[kk][s] = bwd[kk - 1][s + 1]
    # ring-safety: with S saved slots per stage, fwd of m must never
    # overwrite a residual whose backward is still pending
    for s in range(S):
        for m in range(S, M):
            assert bwd_done[s][m - S] < fwd_done[s][m], (s, m)
    return fwd, bwd, arr_f, arr_b


def pipeline_train_grads(stage_fn: Callable, loss_fn: Callable,
                         stage_params: Any, x: "jax.Array", y: "jax.Array",
                         mesh: "jax.sharding.Mesh", axis: str = "pp",
                         num_microbatches: Optional[int] = None,
                         rng_key: Optional["jax.Array"] = None,
                         head_params: Any = None):
    """One pipeline-parallel training pass with the 1F1B schedule:
    returns ``(mean_loss, stage_grads)`` in a single hand-scheduled
    sweep — no ``jax.grad`` over the whole pipeline.

    Versus the GPipe path (``jax.grad`` of :func:`pipeline_apply`):

    * **Memory**: GPipe holds all ``M`` microbatch residuals per stage
      until its reverse sweep; 1F1B holds at most ``S`` (the saved-input
      ring) — backward of microbatch m starts as soon as its forward
      leaves the last stage.
    * **Bubble**: both schedules idle (S-1)/(ticks) at the ramps; the
      tick count here is the simulated 1F1B length (~M + 2(S-1) double
      ticks vs GPipe's (M+S-1) forward + (M+S-1) reversed ticks).
    * Work units are wrapped in ``lax.cond`` so an idle stage SKIPS the
      compute (collectives stay outside the conditionals — every device
      reaches both ppermutes each tick).

    Backward recomputes each stage's forward from the saved input (the
    same remat tradeoff as the GPipe path's per-tick ``jax.checkpoint``).
    ``loss_fn(h_out, y_mb) -> scalar`` is evaluated at the last stage
    (masked elsewhere); grads come back stacked over ``axis`` like
    ``stage_params`` and are already divided by ``num_microbatches``.
    ``rng_key``: as in :func:`pipeline_apply`, folded per
    (microbatch, stage) so backward regenerates the forward's dropout.

    ``head_params`` (full-model 1F1B, r4): an optional pytree of
    last-stage head parameters (final norm, LM projection). When given,
    ``loss_fn(head_params, h_out, y_mb) -> scalar`` runs INSIDE the
    sweep at the last stage (guarded by ``lax.cond`` so interior stages
    skip the vocab matmul), and the return becomes ``(mean_loss,
    stage_grads, head_grads, dx)`` — ``head_grads`` matching
    ``head_params`` and ``dx`` the gradient w.r.t. ``x`` (stage 0's
    incoming cotangents, reassembled over microbatches), so the caller
    can chain embedding/backbone backward outside the pipeline. This is
    what lets a complete model (embed -> stages -> head) train under
    the 1F1B discipline rather than only the stage stack.

    Memory note: ``dx`` accumulates per microbatch, an input-batch-sized
    buffer — the same order as ``x`` itself, which every schedule holds
    for the whole sweep. The 1F1B O(S)-vs-O(M) advantage concerns the
    per-stage HIDDEN-activation residual ring, which stays S-slot here.
    """
    S = mesh.shape[axis]
    n_micro = num_microbatches or S
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible into {n_micro} "
                         f"microbatches")
    mbs = B // n_micro
    # 1F1B COMPOSES with dp (r5): the microbatch batch dim shards over
    # the mesh's dp axis — each dp row pipelines its own batch slice
    # through the same tick tables, and grads/loss psum over dp at the
    # end of the sweep (GSPMD's allreduce analog, but explicit because
    # the whole sweep lives inside one shard_map).
    dp_ax = ("dp" if ("dp" in mesh.axis_names and axis != "dp"
                      and mesh.shape["dp"] > 1) else None)
    dpn = mesh.shape[dp_ax] if dp_ax else 1
    if mbs % dpn:
        raise ValueError(f"microbatch size {mbs} not divisible by "
                         f"dp={dpn}")
    x_mb = x.reshape((n_micro, mbs) + x.shape[1:])
    y_mb = y.reshape((n_micro, mbs) + y.shape[1:])
    ftbl_np, btbl_np, af_np, ab_np = _simulate_1f1b(S, n_micro)
    T = ftbl_np.shape[0]
    perm_f = [(i, (i + 1) % S) for i in range(S)]
    perm_b = [((i + 1) % S, i) for i in range(S)]
    # shapes inside the shard_map are PER-DEVICE: dp splits the batch
    act_shape = (mbs // dpn,) + x.shape[1:]

    def _stage(params, h, m):
        if rng_key is None:
            return stage_fn(params, h)
        stage = jax.lax.axis_index(axis)
        key = jax.random.fold_in(jax.random.fold_in(rng_key, m), stage)
        if dp_ax:
            # distinct dropout draws per dp row (rows hold different
            # examples — replicated masks would correlate them)
            key = jax.random.fold_in(key, jax.lax.axis_index(dp_ax))
        return stage_fn(params, h, key)

    def local(params, x_mb, y_mb, hparams=None):
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        stage = jax.lax.axis_index(axis)
        ftbl = jnp.asarray(ftbl_np)
        btbl = jnp.asarray(btbl_np)
        af = jnp.asarray(af_np)
        ab = jnp.asarray(ab_np)
        dt = x_mb.dtype
        zero_act = jnp.zeros(act_shape, dt)
        ring0 = jnp.zeros((S,) + act_shape, dt)

        def tick(carry, k):
            if head_params is None:
                (wire_f, wire_b, inbox_f, inbox_b, saved,
                 gacc, lacc) = carry
                hacc = dxacc = None
            else:
                (wire_f, wire_b, inbox_f, inbox_b, saved,
                 gacc, hacc, dxacc, lacc) = carry
            fm = ftbl[k][stage]
            bm = btbl[k][stage]
            afk = af[k][stage]
            abk = ab[k][stage]

            # bank last tick's arrivals under their microbatch slot
            inbox_f = jax.lax.cond(
                afk >= 0,
                lambda ib: jax.lax.dynamic_update_index_in_dim(
                    ib, wire_f, afk % S, 0),
                lambda ib: ib, inbox_f)
            inbox_b = jax.lax.cond(
                abk >= 0,
                lambda ib: jax.lax.dynamic_update_index_in_dim(
                    ib, wire_b, abk % S, 0),
                lambda ib: ib, inbox_b)

            # ---- forward phase -------------------------------------
            def fwd_branch(op):
                saved, = op
                h_in = jnp.where(
                    stage == 0, x_mb[jnp.clip(fm, 0, n_micro - 1)],
                    inbox_f[fm % S])
                h_out = _stage(params, h_in, fm)
                saved = jax.lax.dynamic_update_index_in_dim(
                    saved, h_in, fm % S, 0)
                return saved, h_out

            saved, send_f = jax.lax.cond(
                fm >= 0, fwd_branch, lambda op: (op[0], zero_act), (saved,))

            # ---- backward phase ------------------------------------
            def bwd_branch(op):
                if head_params is None:
                    gacc, lacc = op
                else:
                    gacc, hacc, dxacc, lacc = op
                m_clip = jnp.clip(bm, 0, n_micro - 1)
                h_in = saved[bm % S]
                h_out, pull = jax.vjp(
                    lambda p, h: _stage(p, h, bm), params, h_in)
                if head_params is None:
                    loss_m, lpull = jax.vjp(
                        lambda ho: loss_fn(ho, y_mb[m_clip]), h_out)
                    (dh_loss,) = lpull(jnp.ones_like(loss_m))
                    loss_add = jnp.where(stage == S - 1,
                                         loss_m.astype(jnp.float32), 0.0)
                else:
                    # the head (final norm + vocab projection) runs only
                    # where it exists — interior stages skip its FLOPs
                    def at_tail(_):
                        loss_m, lpull = jax.vjp(
                            lambda hp, ho: loss_fn(hp, ho, y_mb[m_clip]),
                            hparams, h_out)
                        dhp, dh = lpull(jnp.ones_like(loss_m))
                        return loss_m.astype(jnp.float32), dhp, dh

                    def not_tail(_):
                        return (jnp.float32(0),
                                jax.tree_util.tree_map(jnp.zeros_like,
                                                       hparams),
                                jnp.zeros_like(h_out))

                    loss_add, dhp, dh_loss = jax.lax.cond(
                        stage == S - 1, at_tail, not_tail, None)
                    hacc = jax.tree_util.tree_map(jnp.add, hacc, dhp)
                g_in = jnp.where(stage == S - 1, dh_loss,
                                 inbox_b[bm % S])
                dp, dh_in = pull(g_in)
                gacc = jax.tree_util.tree_map(jnp.add, gacc, dp)
                lacc = lacc + loss_add
                if head_params is None:
                    return gacc, lacc, dh_in
                # stage 0's incoming cotangent IS d(loss)/d(x_mb[m])
                dxacc = jax.lax.dynamic_update_index_in_dim(
                    dxacc,
                    jnp.where(stage == 0, dh_in, jnp.zeros_like(dh_in)),
                    m_clip, 0)
                return gacc, hacc, dxacc, lacc, dh_in

            if head_params is None:
                gacc, lacc, send_b = jax.lax.cond(
                    bm >= 0, bwd_branch,
                    lambda op: (op[0], op[1], zero_act), (gacc, lacc))
            else:
                gacc, hacc, dxacc, lacc, send_b = jax.lax.cond(
                    bm >= 0, bwd_branch,
                    lambda op: (op[0], op[1], op[2], op[3], zero_act),
                    (gacc, hacc, dxacc, lacc))

            # collectives OUTSIDE the conds: every device participates
            wire_f = jax.lax.ppermute(send_f, axis, perm_f)
            wire_b = jax.lax.ppermute(send_b, axis, perm_b)
            if head_params is None:
                return (wire_f, wire_b, inbox_f, inbox_b, saved,
                        gacc, lacc), None
            return (wire_f, wire_b, inbox_f, inbox_b, saved,
                    gacc, hacc, dxacc, lacc), None

        gacc0 = jax.tree_util.tree_map(jnp.zeros_like, params)

        def _dp_mean(v):
            # mean over dp rows: the global loss is the mean of per-row
            # slice losses, so row grads/losses scale by 1/dpn and sum
            return jax.lax.psum(v, dp_ax) / dpn if dp_ax else v

        if head_params is None:
            carry0 = (zero_act, zero_act, ring0, ring0, ring0,
                      gacc0, jnp.float32(0))
            (*_, gacc, lacc), _ = jax.lax.scan(tick, carry0,
                                               jnp.arange(T))
            loss = _dp_mean(jax.lax.psum(lacc, axis)) / n_micro
            grads = jax.tree_util.tree_map(
                lambda g: (_dp_mean(g) / n_micro)[None], gacc)
            return loss, grads
        hacc0 = jax.tree_util.tree_map(jnp.zeros_like, hparams)
        dx0 = jnp.zeros((n_micro,) + act_shape, dt)
        carry0 = (zero_act, zero_act, ring0, ring0, ring0,
                  gacc0, hacc0, dx0, jnp.float32(0))
        (*_, gacc, hacc, dxacc, lacc), _ = jax.lax.scan(
            tick, carry0, jnp.arange(T))
        loss = _dp_mean(jax.lax.psum(lacc, axis)) / n_micro
        grads = jax.tree_util.tree_map(
            lambda g: (_dp_mean(g) / n_micro)[None], gacc)
        # head grads live only at the tail, dx only at stage 0 — psum
        # replicates both to every stage
        hgrads = jax.tree_util.tree_map(
            lambda g: _dp_mean(jax.lax.psum(g, axis)) / n_micro, hacc)
        # the sweep seeds each microbatch loss with cotangent 1; the
        # returned total is the MEAN over microbatches, so dx needs the
        # same 1/n_micro the stage/head grads get
        # dx stays SHARDED over dp (each row's slice cotangent) but
        # scales by 1/dpn like everything else differentiating the mean
        dx = jax.lax.psum(dxacc, axis) / n_micro / dpn
        return loss, grads, hgrads, dx

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    bspec = P(None, dp_ax)          # (n_micro, batch/dp, ...)
    if head_params is None:
        loss, grads = _shard_map(
            local, mesh, in_specs=(pspec, bspec, bspec),
            out_specs=(P(), pspec))(stage_params, x_mb, y_mb)
        return loss, grads
    hspec = jax.tree_util.tree_map(lambda _: P(), head_params)
    loss, grads, hgrads, dx = _shard_map(
        lambda sp, xm, ym, hp: local(sp, xm, ym, hp),
        mesh, in_specs=(pspec, bspec, bspec, hspec),
        out_specs=(P(), pspec, hspec, bspec))(
            stage_params, x_mb, y_mb, head_params)
    dx = dx.reshape((B,) + x.shape[1:])
    return loss, grads, hgrads, dx


# ---------------------------------------------------------------------------
# Real-model pipeline parallelism: GPT blocks as pipeline stages
# ---------------------------------------------------------------------------

from .spmd import PartitionRules  # noqa: E402  (no gluon<->parallel cycle)
from ..gluon.block import HybridBlock  # noqa: E402

PIPELINE_RULES = PartitionRules([
    # stacked per-stage block weights: leading (stage) dim over pp
    (r"stage_", P("pp")),
])


class GPTPipe(HybridBlock):
    """GPT whose transformer blocks run as GPipe pipeline stages.

    Beyond-reference capability (SURVEY.md 2.3: PP absent upstream) on a
    REAL model: the per-block weights live as stacked ``(num_layers, ...)``
    parameters sharded over the mesh's ``pp`` axis (PIPELINE_RULES), and
    forward streams microbatches through ONE template :class:`GPTBlock`
    whose buffers are rebound per stage (``_bind_params``) inside
    :func:`pipeline_apply` — the block math is the model zoo's own, not a
    reimplementation. Works under SPMDTrainer (the stacked params are
    ordinary Parameters).

    In-pipeline dropout (r3): a per-(microbatch, stage) PRNG key threads
    through the schedule (``pipeline_apply(rng_key=...)``), scoped around
    the template block so its dropout ops draw fresh randomness each
    microbatch at each stage — and regenerate identically in the
    backward recompute.
    """

    def __init__(self, mesh, vocab_size: int = 50257, num_layers: int = 4,
                 units: int = 256, hidden_size: int = 1024,
                 num_heads: int = 4, max_length: int = 512,
                 num_microbatches: Optional[int] = None,
                 axis: str = "pp", dropout: float = 0.0,
                 schedule: str = "gpipe",
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        from ..gluon.model_zoo.gpt import GPTBlock
        from ..gluon.nn import Embedding, LayerNorm
        from ..gluon.parameter import Parameter

        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"schedule must be 'gpipe' or '1f1b', "
                             f"got {schedule!r}")
        if schedule == "1f1b":
            # r5: dp composes (the sweep shards the microbatch batch dim
            # over dp and psums grads/loss — pipeline_train_grads).
            # Other axes (tp/sp) would still be silently replicated: the
            # sweep's stage math carries no in-stage sharding rules.
            extra = [a for a in mesh.axis_names
                     if a not in (axis, "dp") and mesh.shape[a] > 1]
            if extra:
                raise ValueError(
                    f"schedule='1f1b' supports a {axis}(+dp) mesh; "
                    f"axes {extra} would be silently replicated — use "
                    "schedule='gpipe' to compose pp with tp/sp")
        # '1f1b': SPMDTrainer routes gradients through the hand-scheduled
        # sweep (pipeline_loss_and_grads) — S-slot residual memory and
        # tail-ramp backward overlap instead of GPipe's M-microbatch
        # footprint. Inference/forward always uses the GPipe schedule
        # (forward-only has no backward to overlap).
        self.schedule = schedule
        self._mesh = mesh
        self._axis = axis
        self._n_micro = num_microbatches
        self._units = units
        self._max_length = max_length
        self._num_layers = num_layers
        self._dropout = float(dropout)

        self.word_embed = Embedding(vocab_size, units)
        self.position_weight = Parameter(
            "position_weight", shape=(max_length, units), init="normal")
        self.ln_f = LayerNorm(epsilon=1e-5, in_channels=units)

        # template block: supplies the stage math; its own (tiny) buffers
        # are bind targets only, never trained — bypass child registration
        tpl = GPTBlock(units, hidden_size, num_heads, dropout=dropout)
        tpl.initialize()
        object.__setattr__(self, "_template", tpl)
        tpl_params = list(tpl.collect_params().items())
        object.__setattr__(self, "_tpl_params",
                           [p for _, p in tpl_params])
        for name, p in tpl_params:
            sp = Parameter("stage_" + name.replace(".", "_"),
                           shape=(num_layers,) + tuple(p.shape),
                           init=getattr(p, "init", None) or "uniform")
            setattr(self, "stage_" + name.replace(".", "_"), sp)
        object.__setattr__(
            self, "_stacked",
            [getattr(self, "stage_" + name.replace(".", "_"))
             for name, _ in tpl_params])

    def load_block_weights(self, gpt_model) -> None:
        """Copy a :class:`GPTModel`'s per-block weights into the stacked
        stage parameters (for parity tests / converting a trained model)."""
        from ..ndarray.ndarray import NDArray
        blocks = list(gpt_model.blocks._children.values())
        assert len(blocks) == self._num_layers, \
            (len(blocks), self._num_layers)
        per_block = [list(b.collect_params().values()) for b in blocks]
        for k, sp in enumerate(self._stacked):
            stacked = jnp.stack(
                [per_block[i][k].data()._data
                 for i in range(self._num_layers)])
            sp.set_data(NDArray(stacked))

    def _mesh_place(self, nd, spec):
        """Commit an NDArray's buffer to this mesh (writes back), or pass
        tracers through untouched."""
        arr = nd._data
        if isinstance(arr, jax.core.Tracer):
            return arr
        sh = jax.sharding.NamedSharding(self._mesh, spec)
        cur = getattr(arr, "sharding", None)
        if cur is not None and (cur == sh or (
                hasattr(cur, "is_equivalent_to") and
                cur.is_equivalent_to(sh, arr.ndim))):
            return arr
        arr = jax.device_put(arr, sh)
        nd._data = arr
        from .. import engine
        from ..ndarray.register import mark_mesh_resident
        engine.mark_clean(arr)
        if sh.num_devices > 1:
            mark_mesh_resident(nd)   # wrapper outlives per-step buffers
        return arr

    def forward(self, tokens):
        from ..gluon.block import _bind_params
        from ..ndarray.ndarray import from_jax
        from ..ndarray import ops
        from .. import numpy as mxnp
        # eager ops downstream of the pipeline mix mesh-sharded activations
        # with single-device params; the per-op harmonization scan engages
        # via mark_mesh_resident on each placed buffer (and disengages when
        # the last one is collected)
        T = tokens.shape[1]
        if not self.position_weight.is_initialized:
            self.position_weight._finish_deferred_init(
                (self._max_length, self._units))
        x = self.word_embed(tokens)
        pos = ops.slice_axis(self.position_weight.data(), axis=0,
                             begin=0, end=T)
        x = x + pos.expand_dims(0)

        tpl = self._template
        tpl_params = self._tpl_params

        def stage_fn(param_slices, h, key=None):
            from ..ndarray import random as _random
            with _bind_params(tpl_params, param_slices):
                if key is None:
                    out = tpl.forward(from_jax(h))
                else:
                    # scope the per-(microbatch, stage) key so the
                    # block's dropout ops draw from it
                    with _random.trace_key_scope(key):
                        out = tpl.forward(from_jax(h))
            return out._data

        # eager path: stacked weights must live sharded over the pp mesh
        # (write back so the placement is paid once); tracers are already
        # placed by the enclosing pjit (SPMDTrainer rules)
        arrays = []
        for p in self._stacked:
            nd = p.data()
            arrays.append(self._mesh_place(nd, P(self._axis)))
        # pp composes with dp when the mesh has one: activations shard
        # their batch dim over dp, each dp row pipelines its own slice
        bax = "dp" if "dp" in self._mesh.axis_names else None
        h = self._mesh_place(x, P(bax))
        rng = None
        from .._tape import is_training
        if self._dropout > 0.0 and is_training():
            from ..ndarray import random as _random
            rng = _random.split_key()
        out = pipeline_apply(stage_fn, arrays, h, self._mesh,
                             axis=self._axis,
                             num_microbatches=self._n_micro,
                             rng_key=rng, batch_axis=bax)
        if not isinstance(out, jax.core.Tracer) \
                and getattr(out, "sharding", None) is not None \
                and out.sharding.num_devices > 1:
            from ..ndarray.register import mark_mesh_resident
            mark_mesh_resident(out)
        x = self.ln_f(from_jax(out))
        w = self.word_embed.weight.data()
        return mxnp.matmul(x, w.T)

    def pipeline_loss_and_grads(self, params, param_arrays, inputs,
                                labels, loss_fn, rng=None,
                                output_transform=None):
        """SPMDTrainer gradient hook (``schedule='1f1b'``): full-model
        loss and per-parameter grads through the hand-scheduled 1F1B
        sweep — the embedding runs (and backprops) OUTSIDE the pipeline
        via ``jax.vjp`` chained on the sweep's ``dx``, the final norm +
        tied LM projection run INSIDE it as last-stage head params.
        Returns ``(loss, grads, mutated={})`` with grads aligned to
        ``param_arrays``."""
        from ..gluon.block import _bind_params
        from ..ndarray.ndarray import from_jax

        tokens = inputs[0]
        T = int(tokens.shape[1])
        idx = {id(p): i for i, p in enumerate(params)}

        def arr(p):
            return param_arrays[idx[id(p)]]

        ew = arr(self.word_embed.weight)
        pw = arr(self.position_weight)
        ln_plist = list(self.ln_f.collect_params().values())
        ln_arrays = tuple(arr(p) for p in ln_plist)
        stage_arrays = [arr(sp) for sp in self._stacked]

        def embed_fn(ew_, pw_):
            return jnp.take(ew_, tokens, axis=0) + pw_[:T][None]

        x_act, embed_vjp = jax.vjp(embed_fn, ew, pw)

        tpl, tpl_params = self._template, self._tpl_params

        def stage_fn(param_slices, h, key=None):
            from ..ndarray import random as _random
            with _bind_params(tpl_params, param_slices):
                if key is None:
                    out = tpl.forward(from_jax(h))
                else:
                    with _random.trace_key_scope(key):
                        out = tpl.forward(from_jax(h))
            return out._data

        head_params = ln_arrays + (ew,)

        def head_loss(hp, h_out, y_mb):
            with _bind_params(ln_plist, list(hp[:-1])):
                xo = self.ln_f.forward(from_jax(h_out))
            logits = from_jax(jnp.matmul(xo._data, hp[-1].T))
            if output_transform is not None:
                logits = output_transform(logits)
            l = loss_fn(logits, from_jax(y_mb))
            return jnp.mean(l._data)

        from .._tape import is_training
        rng_key = rng if (self._dropout > 0.0 and rng is not None
                          and is_training()) else None
        loss, sgrads, hgrads, dx = pipeline_train_grads(
            stage_fn, head_loss, stage_arrays, x_act, labels, self._mesh,
            axis=self._axis, num_microbatches=self._n_micro,
            rng_key=rng_key, head_params=head_params)
        d_ew_embed, d_pw = embed_vjp(dx)
        grads = [jnp.zeros_like(a) for a in param_arrays]
        # tied embedding: lookup grad + LM-projection grad
        grads[idx[id(self.word_embed.weight)]] = hgrads[-1] + d_ew_embed
        grads[idx[id(self.position_weight)]] = d_pw
        for p, g in zip(ln_plist, hgrads[:-1]):
            grads[idx[id(p)]] = g
        for sp, g in zip(self._stacked, sgrads):
            grads[idx[id(sp)]] = g
        return loss, grads, {}
