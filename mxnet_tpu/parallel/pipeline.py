"""Pipeline parallelism — GPipe-style microbatch schedule over a mesh axis.

NEW capability beyond the reference (SURVEY.md 2.3): leezu/mxnet's closest
analog is manual ``ctx_group`` model parallelism with cross-device copy
nodes; it has no pipeline schedule.  Here stage parameters are stacked on a
leading axis sharded over ``pp``; microbatches flow stage-to-stage via
``ppermute`` inside a ``lax.scan`` (the scaling-book pipelining recipe),
so each hop is one ICI neighbor transfer and XLA overlaps compute with the
collective.

Schedule: ``num_microbatches + num_stages - 1`` ticks (the GPipe bubble);
differentiable end to end — reverse-mode runs the reverse schedule
automatically through the scan/ppermute transpose.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["pipeline_apply"]


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        from jax import shard_map
        kw = {"check_vma": False}
    except ImportError:     # jax < 0.8
        from jax.experimental.shard_map import shard_map
        kw = {"check_rep": False}
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     **kw)


def pipeline_apply(stage_fn: Callable, stage_params: Any, x: "jax.Array",
                   mesh: "jax.sharding.Mesh", axis: str = "pp",
                   num_microbatches: Optional[int] = None) -> "jax.Array":
    """Apply ``num_stages`` chained stages to ``x`` with a GPipe schedule.

    stage_fn(params_i, h) -> h' — one stage's computation; the activation
    shape must be the same for every stage (classic pipeline constraint).
    stage_params: pytree whose leaves have leading dim ``num_stages``
    (stage i's slice feeds stage i), sharded over mesh axis ``axis``.
    x: (B, ...) global batch; split into microbatches along dim 0.

    Returns stage_{N-1}(...stage_0(x)) with shape x.shape.
    """
    if axis not in mesh.axis_names:
        # degenerate: run stages sequentially on one device
        n = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
        h = x
        for i in range(n):
            h = stage_fn(jax.tree_util.tree_map(lambda a: a[i],
                                                stage_params), h)
        return h

    n_stages = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} must equal mesh "
                f"axis '{axis}' size {n_stages} (one stage per device)")
    n_micro = num_microbatches or n_stages
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible into {n_micro} "
                         f"microbatches")
    mb = B // n_micro
    x_mb = x.reshape((n_micro, mb) + x.shape[1:])

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def local(params, x_mb):
        # params leaves: (1, ...) own stage slice; x_mb: (n_micro, mb, ...)
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        stage = jax.lax.axis_index(axis)
        state0 = jnp.zeros_like(x_mb[0])
        out_buf0 = jnp.zeros_like(x_mb)

        @jax.checkpoint
        def tick(carry, t):
            state, out_buf = carry
            # stage 0 ingests microbatch t (clamped; masked by `where`)
            inp = x_mb[jnp.clip(t, 0, n_micro - 1)]
            feed = jnp.logical_and(stage == 0, t < n_micro)
            h = jnp.where(feed, inp, state)
            h = stage_fn(params, h)
            # last stage banks finished microbatch t-(n_stages-1)
            done_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            bank = jnp.logical_and(stage == n_stages - 1,
                                   t >= n_stages - 1)
            out_buf = jnp.where(
                bank,
                jax.lax.dynamic_update_index_in_dim(out_buf, h, done_idx, 0),
                out_buf)
            # hand activations to the next stage
            state = jax.lax.ppermute(h, axis, perm)
            return (state, out_buf), None

        (_, out_buf), _ = jax.lax.scan(
            tick, (state0, out_buf0), jnp.arange(n_micro + n_stages - 1))
        return out_buf[None]

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    out = _shard_map(local, mesh,
                     in_specs=(pspec, P()), out_specs=P(axis))(
        stage_params, x_mb)
    # the bank is only populated on the last stage; its slice is the result
    out = out[-1]
    return out.reshape((B,) + x.shape[1:])
