"""Device-mesh construction helpers.

The single mesh abstraction under all parallelism (SURVEY.md section 7
design stance). Axis names follow convention: ``dp`` (data), ``tp``
(tensor/model), ``sp`` (sequence/context), ``pp`` (pipeline stage),
``ep`` (expert).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as _np
import jax

from ..base import MXNetError

__all__ = ["make_mesh", "mesh_axes", "replicated", "shard_batch"]


def make_mesh(shape: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None) -> "jax.sharding.Mesh":
    """Build a Mesh from an axis-name -> size dict.

    ``make_mesh({"dp": 2, "tp": 4})`` on 8 chips. With ``shape=None`` all
    devices go on one ``dp`` axis. Sizes of ``-1`` are inferred (at most
    one). Axis order follows dict order — put the fastest-varying
    (ICI-neighbor) axis last, e.g. ``tp`` innermost.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = {"dp": n}
    names = list(shape.keys())
    sizes = list(shape.values())
    n_infer = sum(1 for s in sizes if s == -1)
    if n_infer > 1:
        raise MXNetError("at most one mesh axis may be -1")
    if n_infer == 1:
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        if n % known:
            raise MXNetError(f"cannot infer axis: {n} devices not divisible "
                             f"by {known}")
        sizes = [n // known if s == -1 else s for s in sizes]
    total = 1
    for s in sizes:
        total *= s
    if total != n:
        raise MXNetError(f"mesh {dict(zip(names, sizes))} needs {total} "
                         f"devices, have {n}")
    arr = _np.asarray(devices).reshape(sizes)
    return jax.sharding.Mesh(arr, tuple(names))


def mesh_axes(mesh: "jax.sharding.Mesh") -> Tuple[str, ...]:
    return tuple(mesh.axis_names)


def replicated(mesh: "jax.sharding.Mesh") -> "jax.sharding.NamedSharding":
    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())


def shard_batch(batch, mesh: "jax.sharding.Mesh", axis: str = "dp",
                seq_axis: Optional[str] = None):
    """Place a host batch onto the mesh, batch dim sharded over ``axis``
    (and optionally dim1 over ``seq_axis`` for sequence parallelism).

    The TPU-native replacement for ``gluon.utils.split_and_load``.
    """
    from ..ndarray.ndarray import NDArray, from_jax
    P = jax.sharding.PartitionSpec
    spec = P(axis, seq_axis) if seq_axis else P(axis)
    sharding = jax.sharding.NamedSharding(mesh, spec)

    def place(x):
        data = x._data if isinstance(x, NDArray) else x
        return from_jax(jax.device_put(data, sharding))

    if isinstance(batch, (list, tuple)):
        return type(batch)(place(b) for b in batch)
    return place(batch)
