"""Device-mesh construction helpers.

The single mesh abstraction under all parallelism (SURVEY.md section 7
design stance). Axis names follow convention: ``dp`` (data), ``tp``
(tensor/model), ``sp`` (sequence/context), ``pp`` (pipeline stage),
``ep`` (expert).

Multi-slice (DCN) topologies (SURVEY.md section 5.8 north star — the
reference's multi-node ps-lite/DCN tier): ``make_mesh(..., slices=S)``
builds a HYBRID mesh where one axis (``dcn_axis``, default the first —
conventionally ``dp``) spans the slow DCN links between slices
slice-major, and every other axis stays inside a slice so its
collectives ride ICI. The analog of jax's
``mesh_utils.create_hybrid_device_mesh``.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as _np
import jax

from ..base import MXNetError

__all__ = ["make_mesh", "mesh_axes", "replicated", "shard_batch",
           "slice_groups"]


def slice_groups(devices: Sequence) -> List[List]:
    """Group devices by TPU slice: ``slice_index`` when the platform
    reports one (real multi-slice pods), else ``process_index`` (one
    host per slice under ``jax.distributed``), else a single group.
    Groups come back in ascending slice order, each internally ordered
    by device id."""
    keyed: Dict[int, List] = {}
    for d in devices:
        k = getattr(d, "slice_index", None)
        if k is None:
            k = getattr(d, "process_index", 0)
        keyed.setdefault(k, []).append(d)
    return [sorted(keyed[k], key=lambda d: d.id) for k in sorted(keyed)]


def make_mesh(shape: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None,
              slices: Optional[int] = None,
              dcn_axis: Optional[str] = None) -> "jax.sharding.Mesh":
    """Build a Mesh from an axis-name -> size dict.

    ``make_mesh({"dp": 2, "tp": 4})`` on 8 chips. With ``shape=None`` all
    devices go on one ``dp`` axis. Sizes of ``-1`` are inferred (at most
    one). Axis order follows dict order — put the fastest-varying
    (ICI-neighbor) axis last, e.g. ``tp`` innermost.

    ``slices=S`` builds a hybrid DCN x ICI mesh: devices group into S
    slices (``slice_groups``; equal contiguous chunks when the platform
    reports no slice structure, e.g. the virtual CPU mesh), and the
    ``dcn_axis`` (default: the FIRST axis — keep it outermost) is laid
    out slice-major, so positions differing in its high-order part sit
    in different slices (DCN) while its in-slice remainder and every
    other axis stay on ICI. XLA then lowers collectives along that axis
    hierarchically (in-slice reduce + cross-slice exchange).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = {"dp": n}
    names = list(shape.keys())
    sizes = list(shape.values())
    n_infer = sum(1 for s in sizes if s == -1)
    if n_infer > 1:
        raise MXNetError("at most one mesh axis may be -1")
    if n_infer == 1:
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        if n % known:
            raise MXNetError(f"cannot infer axis: {n} devices not divisible "
                             f"by {known}")
        sizes = [n // known if s == -1 else s for s in sizes]
    total = 1
    for s in sizes:
        total *= s
    if total != n:
        raise MXNetError(f"mesh {dict(zip(names, sizes))} needs {total} "
                         f"devices, have {n}")
    if slices is not None and slices > 1:
        arr = _hybrid_device_array(devices, names, sizes, slices, dcn_axis)
    else:
        arr = _np.asarray(devices).reshape(sizes)
    return jax.sharding.Mesh(arr, tuple(names))


def _hybrid_device_array(devices: List, names: List[str],
                         sizes: List[int], slices: int,
                         dcn_axis: Optional[str]) -> "_np.ndarray":
    """Device array for a multi-slice mesh: ``dcn_axis`` slice-major,
    everything else within-slice."""
    n = len(devices)
    axis = dcn_axis if dcn_axis is not None else names[0]
    if axis not in names:
        raise MXNetError(f"dcn_axis {axis!r} is not a mesh axis "
                         f"({names})")
    ai = names.index(axis)
    if sizes[ai] % slices:
        raise MXNetError(
            f"dcn axis {axis!r} (size {sizes[ai]}) must divide into "
            f"{slices} slices — its high-order factor IS the slice "
            "dimension")
    groups = slice_groups(devices)
    if len(groups) != slices:
        if len(groups) == 1 and n % slices == 0:
            # no slice structure reported (virtual CPU mesh, single
            # host): equal contiguous chunks stand in for slices
            flat = groups[0]
            per = n // slices
            groups = [flat[i * per:(i + 1) * per] for i in range(slices)]
        else:
            raise MXNetError(
                f"{len(groups)} device slice(s) found, asked for "
                f"{slices} — pass the full multi-slice device set or "
                "a slice count matching the platform")
    per = n // slices
    if any(len(g) != per for g in groups):
        raise MXNetError(
            f"uneven slices {[len(g) for g in groups]} — a hybrid mesh "
            "needs equal devices per slice")
    ici_sizes = list(sizes)
    ici_sizes[ai] = sizes[ai] // slices
    arr = _np.stack([_np.asarray(g, dtype=object).reshape(ici_sizes)
                     for g in groups])           # (S, ..., a/S, ...)
    arr = _np.moveaxis(arr, 0, ai)               # (..., S, a/S, ...)
    return arr.reshape(sizes)                    # merge: a slice-major


def mesh_axes(mesh: "jax.sharding.Mesh") -> Tuple[str, ...]:
    return tuple(mesh.axis_names)


def replicated(mesh: "jax.sharding.Mesh") -> "jax.sharding.NamedSharding":
    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())


def shard_batch(batch, mesh: "jax.sharding.Mesh", axis: str = "dp",
                seq_axis: Optional[str] = None):
    """Place a host batch onto the mesh, batch dim sharded over ``axis``
    (and optionally dim1 over ``seq_axis`` for sequence parallelism).

    The TPU-native replacement for ``gluon.utils.split_and_load``.
    """
    from ..ndarray.ndarray import NDArray, from_jax
    P = jax.sharding.PartitionSpec
    spec = P(axis, seq_axis) if seq_axis else P(axis)
    sharding = jax.sharding.NamedSharding(mesh, spec)

    def place(x):
        data = x._data if isinstance(x, NDArray) else x
        return from_jax(jax.device_put(data, sharding))

    if isinstance(batch, (list, tuple)):
        return type(batch)(place(b) for b in batch)
    return place(batch)
