"""SPMD trainer — one compiled, sharded train step over a device mesh.

This is the TPU-native replacement for the reference's whole distributed
training path (gluon Trainer + KVStore push/pull + ps-lite servers,
SURVEY.md 3.5): parameters carry NamedShardings chosen by regex rules
(tensor parallelism), the batch is sharded over ``dp`` (and optionally the
sequence over ``sp``), and ONE jit-compiled step does forward, backward,
and the fused optimizer update with XLA inserting every collective
(gradient psum over dp rides ICI — no servers, no key slicing).

Pipeline ('pp') and expert ('ep') axes are accepted in the mesh. Real
microbatch pipeline scheduling lives in ``parallel.pipeline`` —
``GPTPipe`` stacks a model's blocks as stages and runs the GPipe
schedule (``pipeline_apply``: microbatches hop stages via ppermute
inside a scan, remat bounds live activations) under this trainer via
``PIPELINE_RULES``. A 1F1B schedule would only re-order the bubble;
with ``jax.checkpoint`` on each tick the activation footprint is
already O(stages), so GPipe is the deliberate choice here.
"""
from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..base import MXNetError, getenv, register_env
from ..ndarray.ndarray import NDArray, from_jax
from ..ndarray import random as _random
from .. import tracing as _tracing
from .. import optimizer as opt_mod
from ..gluon.block import _bind_params
from ..gluon.parameter import Parameter
from .mesh import make_mesh

P = jax.sharding.PartitionSpec

register_env(
    "MXNET_SPMD_REBIND_INPUTS", 0,
    "Multi-process SPMDTrainer jobs: rebind caller NDArrays in place to "
    "their mesh-resident (non-fully-addressable) buffers, saving the "
    "per-step host->device transfer for re-used batches at the cost of "
    "later host reads on the same NDArray raising. Single-process jobs "
    "always rebind. Read per step.")


def _global_put(a, sh):
    """Place a REPLICATED-CONSISTENT host value (params, optimizer
    state, schedule arrays — every process holds the same full value)
    onto a possibly multi-process mesh sharding.

    ``jax.device_put`` cannot target non-addressable devices; each
    process contributes its addressable shards of the common value via
    ``make_array_from_callback`` — the standard multihost placement
    pattern. NOT for per-process batch data (see ``SPMDTrainer._place``:
    local batches are shards of the global batch, not copies of it)."""
    if jax.process_count() == 1:
        return jax.device_put(a, sh)
    if isinstance(a, jax.Array) and not a.is_fully_addressable:
        # already a global array: reshard through the compiled path
        return jax.device_put(a, sh)
    arr = jnp.asarray(a)
    return jax.make_array_from_callback(
        arr.shape, sh, lambda idx: arr[idx])

__all__ = ["PartitionRules", "SPMDTrainer", "DEFAULT_TRANSFORMER_RULES",
           "DATA_PARALLEL_RULES"]


class PartitionRules:
    """Ordered (regex -> PartitionSpec) rules over parameter names.

    First match wins; no match = fully replicated. Specs name mesh axes
    ('tp', 'pp', ...); axes absent from the mesh are dropped.
    """

    def __init__(self, rules: Sequence[Tuple[str, "P"]]) -> None:
        self._rules = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(self, name: str, shape: Tuple[int, ...],
                 mesh: "jax.sharding.Mesh") -> "P":
        for pat, spec in self._rules:
            if pat.search(name):
                return _filter_spec(spec, shape, mesh)
        return P()

    def __add__(self, other: "PartitionRules") -> "PartitionRules":
        out = PartitionRules([])
        out._rules = self._rules + other._rules
        return out


def _filter_spec(spec: "P", shape: Tuple[int, ...],
                 mesh: "jax.sharding.Mesh",
                 axis_sizes: Optional[Dict[str, int]] = None) -> "P":
    """Drop axes not in the mesh or not dividing the dim evenly.
    ``axis_sizes`` overrides the divisibility extents (the host-local
    batch path validates a PER-PROCESS shape against the per-process
    mesh extent, not the global axis size)."""
    sizes = axis_sizes if axis_sizes is not None else \
        dict(zip(mesh.axis_names, mesh.devices.shape))
    parts = []
    for i, entry in enumerate(spec):
        if entry is None or i >= len(shape):
            parts.append(None)
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        keep = tuple(n for n in names
                     if n in sizes and shape[i] % sizes[n] == 0)
        parts.append(keep if len(keep) > 1 else (keep[0] if keep else None))
    parts = parts[:len(shape)]
    return P(*parts)


# Megatron-style rules for the transformer blocks in this repo (BERT and
# friends): column-parallel QKV/FFN-in, row-parallel out/FFN-out,
# vocab-parallel embeddings. Dense weights are (out, in).
DEFAULT_TRANSFORMER_RULES = PartitionRules([
    (r"attn_qkv\.weight$", P("tp", None)),
    (r"attn_out\.weight$", P(None, "tp")),
    (r"ffn1\.weight$", P("tp", None)),
    (r"ffn2\.weight$", P(None, "tp")),
    (r"attn_qkv\.bias$", P("tp")),
    (r"ffn1\.bias$", P("tp")),
    # seq2seq decoder cross-attention (model_zoo.transformer): q and kv
    # projections column-parallel, output row-parallel — same Megatron
    # split as self-attention
    (r"cross_q\.weight$", P("tp", None)),
    (r"cross_q\.bias$", P("tp")),
    (r"cross_kv\.weight$", P("tp", None)),
    (r"cross_kv\.bias$", P("tp")),
    (r"cross_out\.weight$", P(None, "tp")),
    (r"(src|tgt)_embed\.weight$", P("tp", None)),
    (r"word_embed\.weight$", P("tp", None)),
    (r"mlm_bias$", P("tp")),
])

DATA_PARALLEL_RULES = PartitionRules([])  # replicate everything


class SPMDTrainer:
    """Compiled sharded training: forward+backward+update in one program.

    Parameters
    ----------
    block : HybridBlock
        Initialized model; its parameters are re-placed onto the mesh
        according to ``rules`` (in place — the block keeps working for
        eval too).
    loss_fn : callable(outputs, labels) -> per-sample loss NDArray
    optimizer : str or Optimizer
    mesh : jax.sharding.Mesh or dict (passed to make_mesh)
    rules : PartitionRules for tensor/pipeline parallel parameter layout.
    data_spec / label_spec : PartitionSpecs for the batch arguments.
    """

    def __init__(self, block: Any, loss_fn: Callable,
                 optimizer: Any = "sgd",
                 optimizer_params: Optional[Dict[str, Any]] = None,
                 mesh: Any = None,
                 rules: PartitionRules = DATA_PARALLEL_RULES,
                 data_spec: "P" = P("dp"),
                 label_spec: "P" = P("dp"),
                 donate: bool = True,
                 output_transform: Optional[Callable] = None) -> None:
        self.block = block
        self.loss_fn = loss_fn
        # which forward output feeds the loss (default: first of a tuple)
        self._output_transform = output_transform or (
            lambda out: out[0] if isinstance(out, tuple) else out)
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        elif optimizer_params:
            raise MXNetError("optimizer_params requires a string optimizer")
        self.optimizer = optimizer
        if mesh is None or isinstance(mesh, dict):
            mesh = make_mesh(mesh)
        self.mesh = mesh
        self.rules = rules
        self._data_spec = data_spec
        self._label_spec = label_spec

        # SHARED parameters (tied embeddings registered under two names)
        # enter once, under their first name — a duplicate would bind the
        # same buffer twice in the traced step and double-count its grad
        from ..gluon.parameter import dedupe_shared
        self._names, self._params = dedupe_shared(
            (k, p) for k, p in block.collect_params().items()
            if p.is_initialized)
        # launder eager-produced parameter buffers first (axon: lazy
        # handles cost a tunnel round-trip PER PARAM per step — see
        # engine.launder), then place onto the mesh per rules
        from .. import engine as _engine
        clean = _engine.launder([p.data()._data for p in self._params])
        self._param_shardings = []
        for name, p, arr in zip(self._names, self._params, clean):
            spec = rules.spec_for(name, tuple(p.shape), mesh)
            sh = jax.sharding.NamedSharding(mesh, spec)
            p._data._data = _global_put(arr, sh)
            self._param_shardings.append(sh)
        if mesh.size > 1:
            # eager ops may now mix mesh-placed params with fresh
            # single-device arrays; enable the dispatch-path fixup for
            # as long as the placed parameter buffers live
            from ..ndarray import register as _register
            for p in self._params:
                # the NDArray wrapper persists across per-step buffer
                # swaps; its lifetime = the placed parameter's lifetime
                _register.mark_mesh_resident(p._data)

        # optimizer states co-sharded with their parameter (laundered:
        # they come from eager state-creation ops)
        states = [self.optimizer.create_state_multi_precision(i, p.data())
                  for i, p in enumerate(self._params)]
        leaves, treedef = jax.tree_util.tree_flatten(states)
        leaves = _engine.launder(leaves) if leaves else leaves
        states = jax.tree_util.tree_unflatten(treedef, leaves)
        self._opt_states = [
            jax.tree_util.tree_map(
                lambda a, s=self._param_shardings[i]: _global_put(a, s),
                st)
            for i, st in enumerate(states)]

        self._step_fn = None
        self._multi_fn = None
        self._step_count = 0
        self._donate = donate
        # prefetched fit() loops may donate batch buffers too (every
        # step gets a fresh batch); toggled by _set_input_donation
        self._donate_inputs = False
        # (spec, shape, leading, host_local) -> NamedSharding: _place
        # runs per input per step — the filtered-spec + sharding build
        # is pure and repeats endlessly for steady-shape training
        self._spec_cache: Dict[Any, Any] = {}
        # (n_inputs, donate_inputs, health_gate) -> jitted step: flag
        # toggles (fit entering/leaving prefetch donation or the health
        # gate) swap back to the SAME jit wrapper instead of re-jitting
        # — a fresh jax.jit wrapper retraces and recompiles even for an
        # identical program
        self._built_steps: Dict[Any, Any] = {}
        # health-sentry gate: when on, the compiled step computes a
        # fused finite-check over loss+grads, gates the whole update on
        # it (a bad step leaves params/state untouched ON DEVICE), and
        # returns a [any_bad, first_bad_index, loss] vector — the
        # guard's single per-step readback (mxnet_tpu.health)
        self._health_gate = False
        self._last_health = None
        # device-resident step counter + value-keyed scalar cache: a host
        # scalar whose VALUE changes every call (e.g. jnp.float32(t))
        # misses jax's constant cache and, on the axon remote backend,
        # makes every consuming compiled call pay a slow uncommitted-
        # argument path (measured 8.4s/step vs 73ms with committed
        # scalars). t lives on device and advances by a tiny jitted
        # increment; lr/wd are laundered once per distinct value.
        self._t_dev = None
        # LRU, not clear-at-cap: a cyclic lr schedule (warm restarts)
        # revisits values — a wholesale clear at overflow would re-pay
        # the committed-transfer for EVERY schedule scalar each cycle,
        # while LRU eviction only drops the coldest value
        from collections import OrderedDict as _OD
        self._scalar_cache: "_OD[float, Any]" = _OD()

    _SCALAR_CACHE_CAP = 512

    def _committed_scalar(self, v: float) -> Any:
        key = float(v)
        a = self._scalar_cache.get(key)
        if a is None:
            from .. import engine as _engine
            a = _engine.launder([jnp.float32(key)])[0]
            self._scalar_cache[key] = a
            if len(self._scalar_cache) > self._SCALAR_CACHE_CAP:
                self._scalar_cache.popitem(last=False)
        else:
            self._scalar_cache.move_to_end(key)
        return a

    def set_health_gate(self, on: bool) -> None:
        """Toggle the in-program health sentry (``fit(health_guard=)``
        flips it).  Changing the flag changes the traced program, so the
        compiled step is invalidated."""
        on = bool(on)
        if self._health_gate == on:
            return
        self._health_gate = on
        self._last_health = None
        self._step_fn = None
        self._multi_fn = None
        if hasattr(self, "_raw_step_fn"):
            del self._raw_step_fn

    def _set_input_donation(self, on: bool) -> None:
        """Donate batch buffers into the compiled step.  Only valid for
        loops that feed every step a FRESH batch (the prefetched fit
        path): donation deletes the input buffer after the call, so a
        re-used batch would read dead memory.  Changing the flag
        changes the jit donation signature, invalidating the step."""
        on = bool(on)
        if self._donate_inputs == on:
            return
        self._donate_inputs = on
        self._step_fn = None

    # ------------------------------------------------------------------
    def _build_step(self, n_inputs: int) -> Callable:
        body = self._build_step_body(n_inputs,
                                     health_gate=self._health_gate)

        def step(param_arrays, opt_states, rng, lr, wd, t, *batch):
            # the device-side step counter advances INSIDE the program
            # (trailing t+1 output fed back as next step's t): the loop
            # used to dispatch a separate tiny increment program per
            # step — a fixed host round-trip on remote backends
            return body(param_arrays, opt_states, rng, lr, wd, t,
                        *batch) + (t + 1.0,)

        from .. import compile_cache as _cc
        donate = (0, 1) if self._donate else ()
        if not self._donate_inputs:
            return _cc.persistently_cached(
                jax.jit(step, donate_argnums=donate),
                surface="spmd.step")
        # batch args start at position 6; n_inputs data arrays plus
        # the label array.  Batch buffers rarely alias an output shape
        # (params/states/loss) — the donation win is the EARLY release
        # of the consumed batch's device memory, so XLA's "donated
        # buffers were not usable" aliasing warning is expected noise:
        # filter it ONCE, message-scoped, at build time (a per-call
        # warnings.catch_warnings() mutates process-global state and is
        # documented thread-unsafe against the prefetch thread)
        import warnings as _warnings
        _warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        donate = donate + tuple(range(6, 6 + n_inputs + 1))
        return _cc.persistently_cached(
            jax.jit(step, donate_argnums=donate), surface="spmd.step")

    def _build_step_body(self, n_inputs: int,
                         health_gate: bool = False) -> Callable:
        block, loss_fn = self.block, self.loss_fn
        mesh = self.mesh
        params = self._params
        optimizer = self.optimizer
        hp = [optimizer._hyper(i) for i in range(len(params))]
        opt_cls = type(optimizer)

        def step(param_arrays, opt_states, rng, lr, wd, t, *batch):
            inputs, labels = list(batch[:-1]), batch[-1]

            def forward(pa):
                from .ring import sequence_parallel
                from .moe import collect_aux_losses
                import contextlib
                sp_ctx = (sequence_parallel(mesh, "sp")
                          if "sp" in mesh.axis_names
                          else contextlib.nullcontext())
                with _bind_params(params, pa), _random.trace_key_scope(rng), \
                        sp_ctx, collect_aux_losses() as aux_losses:
                    from .._tape import set_training
                    prev = set_training(True)
                    try:
                        out = block.forward(
                            *[from_jax(b) for b in inputs])
                    finally:
                        set_training(prev)
                    out = self._output_transform(out)
                    loss = loss_fn(out, from_jax(labels))
                    # loss is already MEAN-reduced here, so grads need no
                    # 1/batch rescale (unlike the Trainer path, which
                    # rescales summed per-sample grads)
                    total = loss.mean()._data
                    # MoE load-balancing terms raised during forward
                    for a in aux_losses:
                        total = total + a._data
                    # in-trace writes to non-differentiable state (BN
                    # running stats), read BEFORE _bind_params restores
                    from ..gluon.block import _collect_mutated
                    mut = dict(_collect_mutated(params, pa))
                    return total, mut

            if getattr(block, "schedule", None) == "1f1b" and \
                    callable(getattr(block, "pipeline_loss_and_grads",
                                     None)):
                # pipeline blocks with a hand-scheduled 1F1B sweep own
                # their gradient computation — interleaved fwd/bwd with
                # an S-slot residual ring instead of jax.grad over the
                # whole GPipe schedule. Training mode and the trainer's
                # output transform apply exactly as on the autodiff path.
                from .._tape import set_training
                prev = set_training(True)
                try:
                    loss, grads, mut = block.pipeline_loss_and_grads(
                        params, list(param_arrays), inputs, labels,
                        loss_fn, rng,
                        output_transform=self._output_transform)
                finally:
                    set_training(prev)
            else:
                (loss, mut), grads = jax.value_and_grad(
                    forward, has_aux=True)(list(param_arrays))
            for i in mut:
                if params[i].grad_req != "null":
                    raise MXNetError(
                        f"parameter {self._names[i]!r} (grad_req="
                        f"{params[i].grad_req!r}) was reassigned during "
                        "forward; only non-differentiable state may be "
                        "mutated in-trace — its optimizer update would "
                        "be silently discarded")
            ok = None
            health = None
            if health_gate:
                # fused finite/overflow reduction over the loss and
                # every live gradient — ONE traced reduction, no
                # per-tensor host syncs; index 0 is the loss, i+1 is
                # parameter i (for the guard's culprit naming)
                flags = [jnp.logical_not(jnp.all(jnp.isfinite(loss)))]
                for i, g in enumerate(grads):
                    if params[i].grad_req != "null" and i not in mut:
                        flags.append(jnp.logical_not(
                            jnp.all(jnp.isfinite(g))))
                    else:
                        flags.append(jnp.zeros((), jnp.bool_))
                badv = jnp.stack(flags)
                any_bad = badv.any()
                ok = jnp.logical_not(any_bad)
                health = jnp.stack([any_bad.astype(jnp.float32),
                                    jnp.argmax(badv).astype(jnp.float32),
                                    loss.astype(jnp.float32)])
            def apply_updates(args):
                pa, sts, gs, mt = args
                new_params, new_states = [], []
                for i, (w, g, st) in enumerate(zip(pa, gs, sts)):
                    if i in mt:
                        # forward-mutated state advances by its traced
                        # update; it must NOT get an optimizer step (wd
                        # would decay BN running stats — zero grad does
                        # not mean no-op)
                        new_params.append(mt[i])
                        new_states.append(st)
                    elif params[i].grad_req == "null":
                        new_params.append(w)
                        new_states.append(st)
                    else:
                        nw, ns = opt_cls._step(w, g, st, lr, wd, t,
                                               hp[i])
                        new_params.append(nw)
                        new_states.append(ns)
                return new_params, new_states

            operands = (list(param_arrays), list(opt_states),
                        list(grads), mut)
            if ok is None:
                new_params, new_states = apply_updates(operands)
                return new_params, new_states, loss
            # gate the whole update on the sentry verdict with ONE
            # lax.cond: a bad step takes the identity branch (params,
            # optimizer state, and BN running stats all untouched —
            # buffer-forwarded, no per-tensor where doubling the
            # update's memory traffic on the common clean path)
            new_params, new_states = jax.lax.cond(
                ok, apply_updates,
                lambda args: (list(args[0]), list(args[1])), operands)
            return new_params, new_states, loss, health

        return step

    def _build_multi_step(self, n_inputs: int) -> Callable:
        """K steps fused into one program via lax.scan — the TPU analog
        of the reference's engine op bulking (MXNET_EXEC_BULK_EXEC_TRAIN):
        one dispatch, one set of output buffers, no per-step host
        round-trips."""
        raw_step = self._raw_step(n_inputs)

        def multi(param_arrays, opt_states, keys, lrs, wds, t0, *batches):
            xs, ys = list(batches[:-1]), batches[-1]

            def body(carry, inp):
                params, states, t = carry
                key, lr, wd = inp[0], inp[1], inp[2]
                step_inputs = inp[3:]
                new_p, new_s, loss = raw_step(
                    params, states, key, lr, wd, t, *step_inputs)
                return (new_p, new_s, t + 1.0), loss

            (params, states, _), losses = jax.lax.scan(
                body, (list(param_arrays), list(opt_states), t0),
                (keys, lrs, wds) + tuple(xs) + (ys,))
            return params, states, losses

        from .. import compile_cache as _cc
        donate = (0, 1) if self._donate else ()
        return _cc.persistently_cached(
            jax.jit(multi, donate_argnums=donate), surface="spmd.multi")

    def _raw_step(self, n_inputs: int) -> Callable:
        """The unjitted single-step body (shared by step and multi-step)."""
        if not hasattr(self, "_raw_step_fn") or \
                self._raw_step_n != n_inputs:
            self._raw_step_fn = self._build_step_body(n_inputs)
            self._raw_step_n = n_inputs
        return self._raw_step_fn

    def _check_graph_epoch(self) -> None:
        """Invalidate the compiled step when host-side layer state changed
        the traced program (BatchNorm cold-start bootstrap runs exactly
        once: the step after it must re-trace to the blend graph)."""
        from ..gluon.block import graph_epoch, _remat_enabled
        # env knobs that change the traced program invalidate
        # UNCONDITIONALLY — the _epoch_sensitive filter below only
        # covers layer-state epochs (BatchNorm), not trace-time flags
        remat = _remat_enabled()
        if getattr(self, "_remat_flag", None) != remat:
            self._remat_flag = remat
            self._step_fn = None
            self._multi_fn = None
            self._built_steps.clear()
            if hasattr(self, "_raw_step_fn"):
                del self._raw_step_fn
        epoch = graph_epoch()
        if getattr(self, "_graph_epoch", None) != epoch:
            self._graph_epoch = epoch
            if not getattr(self.block, "_epoch_sensitive", lambda: True)():
                return      # traced program cannot have changed
            self._step_fn = None
            self._multi_fn = None
            self._built_steps.clear()
            if hasattr(self, "_raw_step_fn"):
                del self._raw_step_fn

    def _place(self, x: Any, spec: "P",
               leading_step_dim: bool = False) -> Any:
        """Put a batch input onto the mesh per ``spec`` (with an unsharded
        leading K dimension for the fused multi-step path) and write the
        mesh-resident buffer back into the NDArray: eager arrays live on
        the eager backend (CPU under the axon tunnel), and without the
        write-back a re-used batch re-pays the full host->device transfer
        on EVERY step (measured ~1s/step for a 128x3x224x224 batch vs
        70ms once resident)."""
        a = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        multi = jax.process_count() > 1
        host_local = multi and not (
            isinstance(a, jax.Array) and not a.is_fully_addressable)
        orig = spec
        cache_key = (orig, tuple(a.shape), leading_step_dim, host_local)
        cached = self._spec_cache.get(cache_key)
        if cached is not None:
            spec, sh = cached
        else:
            # a host-local batch is a PER-PROCESS shard: its dims must
            # divide the per-process mesh extent, not the global axis
            # size (a local batch of 2 on a dp=4 mesh over 2 processes
            # is valid — 2 local devices each)
            sizes = (dict(zip(self.mesh.axis_names,
                              self.mesh.local_mesh.devices.shape))
                     if host_local else None)
            shape = tuple(a.shape[1:] if leading_step_dim else a.shape)
            spec = _filter_spec(orig, shape, self.mesh, axis_sizes=sizes)
            if host_local:
                # for host-local data a dropped-for-divisibility axis
                # CHANGES MEANING (shard of the global batch -> claimed
                # copy of it), so it must error, not silently replicate
                # inconsistent data
                membership = _filter_spec(
                    orig, shape, self.mesh,
                    axis_sizes={n: 1 for n in self.mesh.axis_names})
                if tuple(spec) != tuple(membership):
                    raise MXNetError(
                        f"per-process batch shape {shape} does not "
                        f"divide the local mesh extent "
                        f"{dict((k, v) for k, v in sizes.items())} for "
                        f"spec {orig}; each process's local batch must "
                        "split evenly over its own devices")
            if leading_step_dim:
                spec = P(*((None,) + tuple(spec)))
            sh = jax.sharding.NamedSharding(self.mesh, spec)
            if len(self._spec_cache) > 64:     # few live shapes; bound it
                self._spec_cache.clear()
            self._spec_cache[cache_key] = (spec, sh)
        cur = getattr(a, "sharding", None)
        if cur is not None and (cur == sh or (
                hasattr(cur, "is_equivalent_to") and
                cur.is_equivalent_to(sh, a.ndim))):
            return a
        if host_local:
            # this process's shard of the global batch (reference
            # dist_sync semantics: every worker feeds its own local data)
            from jax.experimental import multihost_utils
            a = multihost_utils.host_local_array_to_global_array(
                jnp.asarray(a), self.mesh, spec)
        elif multi:
            a = jax.device_put(a, sh)           # global array: reshard
        else:
            a = _global_put(a, sh)
        if isinstance(x, NDArray) and (
                not multi or bool(getenv("MXNET_SPMD_REBIND_INPUTS", 0))):
            # write the mesh-resident buffer back into the caller's NDArray
            # so re-used batches skip the host->device transfer on every
            # step. Multi-process jobs skip the rebind by default — there
            # the buffer is non-fully-addressable and a later asnumpy()/
            # metric read on the caller's array would raise (opt back in
            # with MXNET_SPMD_REBIND_INPUTS=1 when inputs are step-only).
            x._data = a
            if getattr(a, "sharding", None) is not None \
                    and a.sharding.num_devices > 1:
                # the caller's wrapper may outlive the trainer: keep the
                # harmonization scan alive while it does
                from ..ndarray.register import mark_mesh_resident
                mark_mesh_resident(x)
        from .. import engine as _engine
        _engine.mark_clean(a)
        return a

    def run_steps(self, data: Any, labels: Any) -> NDArray:
        """Run K fused steps: ``data``/``labels`` carry a leading step
        dimension (K, batch, ...). Returns the (K,) per-step losses.
        Parameters/optimizer state advance K times on device.

        Like :meth:`step`, input NDArrays are rebound in place to their
        mesh-resident shardings (see the step() docstring for the
        multi-process caveat).
        """
        import time
        from .. import metrics as _metrics
        inputs = data if isinstance(data, (list, tuple)) else [data]

        t0 = time.perf_counter()
        arrays = [self._place(x, self._data_spec, leading_step_dim=True)
                  for x in inputs]
        label_arr = self._place(labels, self._label_spec,
                                leading_step_dim=True)
        t_data = time.perf_counter() - t0
        K = arrays[0].shape[0]
        self._check_graph_epoch()
        if self._multi_fn is None:
            self._multi_fn = self._build_multi_step(len(arrays))
        rng = _random.split_key()
        keys = jax.random.split(rng, K)
        # per-step lr/wd so schedules advance exactly as K single steps
        base = self._step_count
        lrs, wds = [], []
        for i in range(1, K + 1):
            self.optimizer.num_update = base + i
            lrs.append(self.optimizer.learning_rate)
            wds.append(self.optimizer.wd)
        param_arrays = [p.data()._data for p in self._params]
        # launder the freshly-built schedule arrays + t0: varying-value
        # host arrays would hit the slow uncommitted-argument path on
        # every call (see _committed_scalar)
        from .. import engine as _engine
        lrs_a, wds_a, t0_a = _engine.launder(
            [jnp.asarray(lrs, jnp.float32), jnp.asarray(wds, jnp.float32),
             jnp.float32(base + 1)])
        # donated param/state buffers: pending bulked segments holding
        # them BY VALUE must materialize first (targeted — the prefetch
        # thread's in-build segment never captured them and keeps going)
        from .. import bulk as _bulk
        _bulk.flush_holding(
            param_arrays + jax.tree_util.tree_leaves(self._opt_states),
            "mutation")
        new_params, new_states, losses = self._multi_fn(
            param_arrays, self._opt_states, keys,
            lrs_a, wds_a, t0_a, *arrays, label_arr)
        self._step_count += K
        self.optimizer.num_update = self._step_count
        self._t_dev = None  # re-sync the device counter on next step()
        from .. import engine as _engine
        _engine.mark_clean(new_params)
        for p, a in zip(self._params, new_params):
            p.data()._data = a
        self._opt_states = new_states
        total = time.perf_counter() - t0
        _metrics.record_step(total, data=t_data,
                             dispatch=total - t_data, count=K)
        _metrics.record_device_highwater()
        return from_jax(losses)

    def step(self, data: Any, labels: Any, batch_size: Optional[int] = None
             ) -> NDArray:
        """One training step; returns the (replicated) scalar loss.

        Input NDArrays are rebound in place to their mesh-resident
        shardings so a re-used batch pays its host->device transfer only
        once. In multi-process jobs the rebound buffer is a global
        (non-host-addressable) array: per-process host-side reads of the
        same NDArray (``asnumpy``, eager ops, metrics) must use a separate
        copy of the data.
        """
        import time
        from .. import metrics as _metrics
        inputs = data if isinstance(data, (list, tuple)) else [data]

        t0 = time.perf_counter()
        with _tracing.child_span("step.place"):
            arrays = [self._place(x, self._data_spec) for x in inputs]
            label_arr = self._place(labels, self._label_spec)
        t_data = time.perf_counter() - t0
        from .. import faults as _faults
        if _faults._ARMED:
            # tensor-corrupting chaos site: kind=nan poisons the first
            # float tensor among data + labels, making the compiled
            # step's gradients non-finite — the deterministic trigger
            # the health sentry trains against
            corr = _faults.maybe_corrupt(
                "trainer.step", list(arrays) + [label_arr],
                step=self._step_count)
            arrays, label_arr = corr[:-1], corr[-1]
        self._check_graph_epoch()
        if self._step_fn is None:
            key = (len(arrays), self._donate_inputs, self._health_gate)
            fn = self._built_steps.get(key)
            if fn is None:
                fn = self._built_steps[key] = \
                    self._build_step(len(arrays))
            self._step_fn = fn
        self._step_count += 1
        self.optimizer.num_update = self._step_count
        lr = self.optimizer.learning_rate
        wd = self.optimizer.wd
        rng = _random.split_key()
        param_arrays = [p.data()._data for p in self._params]
        if self._t_dev is None:
            # (re-)sync the device-resident step counter; afterwards it
            # advances inside the compiled step (trailing t+1 output)
            self._t_dev = self._committed_scalar(float(self._step_count))
        # the compiled step donates param/state buffers (and, on the
        # prefetched fit path, the batch buffers): any pending bulked
        # segment still holding one BY VALUE must materialize first.
        # Targeted — NOT flush_all: a global flush here cut the prefetch
        # thread's in-build preprocessing segment once per step,
        # re-serializing exactly the work the input pipeline overlaps
        from .. import bulk as _bulk
        donated = param_arrays + jax.tree_util.tree_leaves(
            self._opt_states)
        if self._donate_inputs:
            donated = donated + list(arrays) + [label_arr]
        _bulk.flush_holding(donated, "mutation")
        with _tracing.child_span("step.dispatch"):
            out = self._step_fn(
                param_arrays, self._opt_states, rng,
                self._committed_scalar(lr), self._committed_scalar(wd),
                self._t_dev,
                *arrays, label_arr)
        if self._health_gate:
            new_params, new_states, loss, self._last_health, \
                self._t_dev = out
        else:
            new_params, new_states, loss, self._t_dev = out
        from .. import engine as _engine
        _engine.mark_clean(new_params)
        for p, a in zip(self._params, new_params):
            p.data()._data = a
        self._opt_states = new_states
        total = time.perf_counter() - t0
        # dispatch-side accounting: the program is still running on
        # device when step() returns — the caller's loss sync is the
        # mxnet_step_sync_seconds component (estimator/bench observe it)
        _metrics.record_step(total, data=t_data,
                             dispatch=total - t_data)
        _metrics.record_device_highwater()
        return from_jax(loss)

    @property
    def learning_rate(self) -> float:
        return self.optimizer.learning_rate

    def input_placement(self) -> Callable[[Any], Any]:
        """A ``(data, labels) -> (data, labels)`` callable committing a
        batch onto this trainer's mesh shardings.

        ``DevicePrefetcher.attach(trainer)`` installs it as the
        prefetcher's placement: the background thread then pays the
        host->device transfer of batch N+1 while step N executes, and
        ``step()``'s own ``_place`` short-circuits on the already-
        matching sharding (no second copy).

        Multi-process jobs keep placement at step time (identity here):
        ``_place`` there runs ``host_local_array_to_global_array`` — a
        cross-process collective that must interleave identically on
        every process, which a background thread cannot guarantee
        against the step's own collectives — and skips the in-place
        rebind, so prefetch-thread placement work would be discarded
        anyway.  The prefetcher still overlaps the host fetch +
        preprocessing."""
        if jax.process_count() > 1:
            return lambda batch: batch

        def one(x: Any, spec: "P") -> Any:
            if not isinstance(x, NDArray):
                x = from_jax(jnp.asarray(x))
            self._place(x, spec)       # rebinds x._data mesh-resident
            return x

        def place(batch: Any) -> Any:
            data, labels = batch
            if isinstance(data, (list, tuple)):
                data = type(data)(one(x, self._data_spec) for x in data)
            else:
                data = one(data, self._data_spec)
            labels = one(labels, self._label_spec)
            return data, labels

        return place

    # -- preemption-safe training loop ---------------------------------
    def fit(self, batch_fn: Any, num_steps: int,
            checkpoint_manager: Any = None,
            checkpoint_every: int = 10,
            health_guard: Any = None) -> Optional[NDArray]:
        """Run up to ``num_steps`` steps with auto-resume and graceful
        preemption — the kill-and-restart-safe loop.

        ``batch_fn``: a callable ``step -> (data, labels)`` (preferred —
        resume re-derives the exact batch for any step), an iterable
        of ``(data, labels)`` (on resume, the first ``restored_step``
        batches are consumed and discarded to stay on-schedule), or a
        :class:`~mxnet_tpu.io.DevicePrefetcher` wrapping either.  A
        callable-mode prefetcher is driven directly: host fetch +
        sharded device placement of batch N+1 overlap step N on the
        prefetch thread, batch buffers are donated to the compiled step
        (``MXNET_PREFETCH_DONATE``), and checkpoint resume / HealthGuard
        rewind invalidate queued batches transparently.

        With ``checkpoint_manager``: restores the newest verified
        checkpoint before the first step (making the call idempotent
        under kill-and-restart — a rerun continues where the kill
        landed, and a completed run is a no-op), saves every
        ``checkpoint_every`` steps, and saves a final checkpoint at
        ``num_steps``.  A
        :class:`~mxnet_tpu.checkpoint.CoordinatedCheckpointManager`
        slots in unchanged: every rank then agrees on the checkpoint
        step through the two-phase cluster rendezvous before any rank
        commits, and the restore resumes the whole cluster from one
        consistent step; the rendezvous is hang-watchdog-armed
        (``checkpoint.save`` site) and a dead rank is named in a
        structured error instead of stalling the save.  A SIGTERM/SIGINT during the loop finishes the
        in-flight step, writes a checkpoint, and returns cleanly
        (:class:`~mxnet_tpu.preemption.PreemptionGuard`); the next
        incarnation resumes from it.

        With ``health_guard`` (:class:`mxnet_tpu.health.HealthGuard`):
        the compiled step gains an in-program numerics sentry that
        gates the whole update on-device (a NaN/Inf step never touches
        parameters or optimizer state), the guard reads one small
        health vector per step and applies its skip/rewind/abort
        policy, and the hang watchdog arms around every step.  Rewind
        needs BOTH a ``checkpoint_manager`` and a callable ``batch_fn``
        (an iterable cannot replay); ``batch_fn(step, salt=...)`` is
        used when the callable accepts a ``salt`` keyword, so replays
        after a rewind perturb the data order.

        Returns the loss of the last executed step (``None`` if there
        was nothing left to run).  Only that one loss is fetched — the
        loop itself never syncs on the device (a ``health_guard`` adds
        its single per-step readback).
        """
        from ..preemption import PreemptionGuard
        from ..io.prefetch import DevicePrefetcher
        if checkpoint_manager is not None:
            checkpoint_manager.restore(self)
        start = self._step_count
        prefetcher: Optional[DevicePrefetcher] = None
        if isinstance(batch_fn, DevicePrefetcher) and batch_fn.is_callable:
            # the prefetched loop: batch N+1 is fetched, preprocessed,
            # and committed to this trainer's mesh shardings on the
            # prefetcher's background thread WHILE step N executes —
            # get() below is a queue pop of a device-resident batch.
            # A resume (non-consecutive step) or a HealthGuard rewind
            # (changed salt) invalidates queued batches automatically.
            prefetcher = batch_fn.attach(self)
            if prefetcher.takes_salt and health_guard is not None:
                def get_batch(step):
                    return prefetcher.get(
                        step, salt=health_guard.replay_salt)
            else:
                def get_batch(step):
                    return prefetcher.get(step)
            if prefetcher.donate:
                # every step gets a FRESH device-resident batch, so its
                # buffers can be donated into the compiled step (XLA
                # reuses the input memory for outputs)
                self._set_input_donation(True)
        elif callable(batch_fn):
            from ..io.prefetch import takes_salt as _takes_salt
            if _takes_salt(batch_fn) and health_guard is not None:
                def get_batch(step):
                    return batch_fn(step, salt=health_guard.replay_salt)
            else:
                get_batch = batch_fn
        else:
            it = iter(batch_fn)

            def get_batch(step, _it=it):
                try:
                    return next(_it)
                except StopIteration:
                    raise MXNetError(
                        f"batch iterable exhausted at step {step} "
                        f"(num_steps={num_steps}); pass a callable "
                        "batch_fn (step -> batch) or a long-enough "
                        "iterable") from None

            for s in range(start):      # skip batches already trained on
                get_batch(s)
        import contextlib
        if health_guard is not None:
            self.set_health_gate(True)
            if checkpoint_manager is not None and (
                    prefetcher is not None or callable(batch_fn)):
                # a callable-mode prefetcher replays like a bare
                # batch_fn: the rewind's non-consecutive step (and
                # perturbed salt) invalidates its queue and reseeks
                health_guard.set_rewind(
                    lambda: checkpoint_manager.restore(self))
        loss: Optional[NDArray] = None
        try:
            with PreemptionGuard() as guard:
                # the sentry verdict for step N is read while step N+1
                # is already in flight (`prev` holds the un-verified
                # step's health vector + loss): the readback then
                # overlaps device compute instead of stalling the
                # pipeline every step.  Verifying one step late is
                # sound BECAUSE the update is gated on-device — a bad
                # step never touched parameters, so any checkpoint
                # written in the detection gap is still clean.
                prev = None
                while True:
                    cur = None
                    ran = self._step_count < num_steps
                    if ran:
                        step = self._step_count
                        # per-step root span: batch get (prefetch pop
                        # or host fetch) and the step dispatch are its
                        # children — a slow step tail-upgrades the
                        # whole tree into the trace ring
                        with _tracing.span("train.step", step=step):
                            data, labels = get_batch(step)
                            with (health_guard.watch("trainer.step",
                                                     step=step)
                                  if health_guard is not None
                                  else contextlib.nullcontext()):
                                step_loss = self.step(data, labels)
                        if health_guard is None:
                            loss = step_loss
                        else:
                            cur = (self._last_health, step_loss)
                            try:     # start the readback without blocking
                                cur[0].copy_to_host_async()
                            except Exception:   # noqa: BLE001 - backend-
                                pass            # dependent surface
                    if health_guard is not None and prev is not None:
                        verdict = health_guard.check_device(
                            prev[0], names=self._names)
                        if verdict.action == "rewind":
                            if health_guard.do_rewind() is not None:
                                # restored the newest verified
                                # checkpoint (replay gets a perturbed
                                # salt); the in-flight step built on
                                # abandoned state — discard it, restore
                                # overwrites everything
                                prev = None
                                continue
                            # nothing to restore to (no checkpoint yet;
                            # accounted as a skip): the gated bad step
                            # never landed, the in-flight step is still
                            # valid — keep pipelining
                        elif verdict.ok:
                            # a skipped step's loss is the garbage that
                            # triggered the skip — the returned "last
                            # loss" tracks accepted steps only
                            loss = prev[1]
                    prev = cur
                    done = self._step_count
                    preempted = guard.requested
                    need_ckpt = ran and checkpoint_manager is not None \
                        and (preempted or done == num_steps
                             or (checkpoint_every > 0
                                 and done % checkpoint_every == 0))
                    if need_ckpt and health_guard is not None \
                            and prev is not None:
                        # a checkpoint must never capture an UNVERIFIED
                        # step: it would become the newest "verified"
                        # rewind target, and a rewind to it would
                        # silently never replay the bad step.  Drain
                        # this step's verdict synchronously (only
                        # checkpoint-boundary steps pay the stall).
                        hv, pl = prev
                        prev = None
                        verdict = health_guard.check_device(
                            hv, names=self._names)
                        if verdict.action == "rewind":
                            if health_guard.do_rewind() is not None:
                                continue      # restored: skip the save
                            # no-op rewind (no checkpoint yet, counted
                            # as a skip): state is clean — save anyway
                        elif verdict.ok:
                            loss = pl
                    if need_ckpt:
                        # watchdog-armed: a coordinated save blocks in
                        # the cluster rendezvous — a hang here (wedged
                        # peer) dumps stacks instead of stalling silent
                        from .. import health as _health
                        with _tracing.span("checkpoint.save",
                                           step=done), \
                                _health.watch_section("checkpoint.save",
                                                      step=done):
                            checkpoint_manager.save(self, step=done)
                    if preempted:
                        # drain the pending verdict so accounting and
                        # the returned loss cover the final step.  Only
                        # the manager-less path can still hold one here,
                        # and without a rewind action the policy already
                        # degrades to skip — no rewind can be decided
                        # during shutdown.
                        if health_guard is not None and prev is not None:
                            verdict = health_guard.check_device(
                                prev[0], names=self._names)
                            if verdict.ok:
                                loss = prev[1]
                        break
                    if self._step_count >= num_steps and prev is None:
                        break
        finally:
            if health_guard is not None:
                self.set_health_gate(False)
            if prefetcher is not None and prefetcher.donate:
                # manual step() calls after fit must not have their
                # batch buffers deleted under them
                self._set_input_donation(False)
        return loss

    # -- checkpoint / resume (reference SURVEY.md 5.4: .params format +
    # sharded device-resident trainer state keyed by param names) --------
    def save_checkpoint(self, prefix: str) -> None:
        """Write ``prefix.params`` (reference-format, interop-safe) and
        ``prefix.states`` (optimizer state + step count).  Sharded arrays
        are gathered to host; shardings are re-applied on load."""
        import pickle
        import numpy as onp
        from .. import ndarray_io
        ndarray_io.save_params(
            prefix + ".params",
            {n: from_jax(p.data()._data)
             for n, p in zip(self._names, self._params)})
        payload = {
            "step_count": self._step_count,
            "opt_states": [jax.tree_util.tree_map(onp.asarray, s)
                           for s in self._opt_states],
            "names": self._names,
        }
        with open(prefix + ".states", "wb") as f:
            pickle.dump(payload, f)

    def load_checkpoint(self, prefix: str) -> None:
        """Restore a :meth:`save_checkpoint`; parameters and optimizer
        state land back on the mesh with their recorded shardings."""
        import pickle
        from .. import ndarray_io
        # validate EVERYTHING before touching live state: a mismatched
        # checkpoint must not leave the trainer half-loaded
        loaded = ndarray_io.load_params(prefix + ".params")
        missing = [n for n in self._names if n not in loaded]
        if missing:
            raise MXNetError(f"checkpoint {prefix}.params missing "
                             f"parameters {missing}")
        with open(prefix + ".states", "rb") as f:
            payload = pickle.load(f)
        if payload["names"] != self._names:
            raise MXNetError("checkpoint parameter names do not match "
                             "this trainer's model")
        for name, p, sh in zip(self._names, self._params,
                               self._param_shardings):
            p._data._data = _global_put(loaded[name]._data, sh)
        self._step_count = payload["step_count"]
        self.optimizer.num_update = self._step_count
        self._t_dev = None  # re-sync the device counter on next step()
        self._opt_states = [
            jax.tree_util.tree_map(
                lambda a, s=sh: _global_put(jnp.asarray(a), s), st)
            for st, sh in zip(payload["opt_states"],
                              self._param_shardings)]
