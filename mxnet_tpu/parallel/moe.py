"""Mixture-of-experts with expert parallelism over the ``ep`` mesh axis.

NEW capability beyond the reference (SURVEY.md 2.3 lists EP/MoE as
ABSENT).  Design (tpu-first): experts are ONE set of stacked parameters
(leading dim = num_experts) so a PartitionSpec ``P('ep', ...)`` shards
them; token dispatch/combine are dense einsums against a capacity-bucketed
one-hot mask (Shazeer/GShard style), which GSPMD turns into all-to-all
over ICI when the expert dim is sharded — no manual collective calls.
"""
from __future__ import annotations

import contextlib
import math
from typing import Any, Optional

import jax

from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon.parameter import Parameter
from ..ndarray import ops as ndops
from ..ndarray.ndarray import NDArray
from .spmd import DEFAULT_TRANSFORMER_RULES, PartitionRules

__all__ = ["MoEDense", "MOE_RULES", "MOE_TRANSFORMER_RULES",
           "collect_aux_losses"]


# Active aux-loss collector (trace-safe channel from MoE layers to the
# trainer's objective; ``self.aux_loss`` would leak tracers under jit).
_collector: Optional[list] = None


@contextlib.contextmanager
def collect_aux_losses():
    """Collect MoE load-balancing losses raised during ``forward``.

    SPMDTrainer wraps its traced loss computation in this context and adds
    the collected terms to the objective inside the same trace. Yields the
    list that forward() appends NDArray aux-loss terms to."""
    global _collector
    prev = _collector
    _collector = []
    try:
        yield _collector
    finally:
        _collector = prev


# Shard stacked expert weights over ep; everything else replicated.
MOE_RULES = PartitionRules([
    (r"expert_w1$", P("ep", None, None)),
    (r"expert_b1$", P("ep", None)),
    (r"expert_w2$", P("ep", None, None)),
    (r"expert_b2$", P("ep", None)),
])

# MoE transformer on a combined mesh (e.g. {"dp": 2, "ep": 4}): expert
# weights over ep, attention/FFN/embedding over tp when present, batch
# over dp via the trainer's data spec.
MOE_TRANSFORMER_RULES = MOE_RULES + DEFAULT_TRANSFORMER_RULES


class MoEDense(HybridBlock):
    """Routed mixture of expert FFNs (GShard-style, top-1 or top-2).

    Input (B, T, d) or (N, d); each token goes to its argmax expert
    (``top_k=2`` adds the runner-up with renormalized combine weights
    and a queue appended after all first choices),
    bucketed to ``capacity_factor * N / num_experts`` slots per expert.
    Overflow tokens produce ZERO output — wrap the layer in an external
    residual connection (as Switch Transformer does) so they pass through.
    The load-balancing auxiliary loss (fraction·probability dot product,
    Switch-Transformer eq. 4) is stored on ``self.aux_loss`` after eager
    forwards; under a traced step (SPMDTrainer) it is instead delivered
    through ``collect_aux_losses`` and added to the objective.
    """

    def __init__(self, num_experts: int, hidden_size: int,
                 units: Optional[int] = None, activation: str = "gelu",
                 capacity_factor: float = 1.25, dtype: Any = "float32",
                 top_k: int = 1, router_z_loss: float = 0.0,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if num_experts < 1:
            raise MXNetError("num_experts must be >= 1")
        if top_k not in (1, 2):
            raise MXNetError("top_k must be 1 or 2")
        if top_k > num_experts:
            raise MXNetError(
                f"top_k={top_k} needs at least that many experts "
                f"(got num_experts={num_experts})")
        self._top_k = top_k
        self._z_coef = float(router_z_loss)
        self._E = num_experts
        self._H = hidden_size
        self._units = units          # defaults to input dim (residual FFN)
        self._act = activation
        self._cf = capacity_factor
        self.gate = Parameter("gate", shape=(num_experts, 0), dtype=dtype)
        self.expert_w1 = Parameter("expert_w1",
                                   shape=(num_experts, 0, hidden_size),
                                   dtype=dtype)
        self.expert_b1 = Parameter("expert_b1",
                                   shape=(num_experts, hidden_size),
                                   dtype=dtype, init="zeros")
        self.expert_w2 = Parameter("expert_w2",
                                   shape=(num_experts, hidden_size, 0),
                                   dtype=dtype)
        self.expert_b2 = Parameter("expert_b2", shape=(num_experts, 0),
                                   dtype=dtype, init="zeros")
        self.aux_loss: Optional[NDArray] = None

    def _finish_init(self, d: int) -> None:
        units = self._units or d
        if not self.gate.is_initialized:
            self.gate._finish_deferred_init((self._E, d))
            self.expert_w1._finish_deferred_init((self._E, d, self._H))
            self.expert_b1._finish_deferred_init((self._E, self._H))
            self.expert_w2._finish_deferred_init((self._E, self._H, units))
            self.expert_b2._finish_deferred_init((self._E, units))

    def forward(self, x: NDArray) -> NDArray:
        shape = x.shape
        d = shape[-1]
        self._finish_init(d)
        flat = x.reshape((-1, d))                       # (N, d)
        N = flat.shape[0]
        E = self._E
        C = max(1, int(math.ceil(self._cf * N / E)))

        logits = ndops.dot(flat, self.gate.data().T)    # (N, E)
        from ..ops import nn as npx
        probs = npx.softmax(logits, axis=-1)
        top_e = ndops.argmax(logits, axis=-1)           # (N,)
        e_hot = ndops.one_hot(top_e, E, dtype=x.dtype)  # (N, E)
        p1 = (probs * e_hot).sum(axis=-1)               # (N,)

        # first-choice capacity queues (position of each token within its
        # expert's queue; tokens past capacity produce zero output — the
        # external residual carries them, Switch-Transformer style)
        pos1 = ndops.cumsum(e_hot, axis=0) * e_hot - e_hot   # (N, E)
        keep1 = (pos1 < float(C)).astype(x.dtype) * e_hot
        pos_idx1 = (pos1 * keep1).sum(axis=-1)               # (N,)
        c_hot1 = ndops.one_hot(pos_idx1, C, dtype=x.dtype)   # (N, C)
        d1 = ndops.einsum("ne,nc->nec", keep1, c_hot1)       # (N, E, C)

        if self._top_k == 2:
            # second choice: argmax with the first expert masked out;
            # its queue appends AFTER every first-choice token (GShard
            # top-2 priority), combine weights renormalized over the pair
            probs2 = probs * (1.0 - e_hot)
            e2_hot = ndops.one_hot(ndops.argmax(probs2, axis=-1), E,
                                   dtype=x.dtype)            # (N, E)
            p2 = (probs2 * e2_hot).sum(axis=-1)
            cnt1 = e_hot.sum(axis=0)                         # (E,)
            pos2 = (ndops.cumsum(e2_hot, axis=0) * e2_hot - e2_hot
                    + e2_hot * cnt1.reshape((1, E)))
            keep2 = (pos2 < float(C)).astype(x.dtype) * e2_hot
            pos_idx2 = (pos2 * keep2).sum(axis=-1)
            c_hot2 = ndops.one_hot(pos_idx2, C, dtype=x.dtype)
            d2 = ndops.einsum("ne,nc->nec", keep2, c_hot2)
            denom = p1 + p2 + 1e-9
            w1, w2 = p1 / denom, p2 / denom
            dispatch = d1 + d2
            combine = d1 * w1.reshape((N, 1, 1)) \
                + d2 * w2.reshape((N, 1, 1))
        else:
            dispatch = d1
            combine = d1 * p1.reshape((N, 1, 1))

        # aux load-balance loss: E * sum_e fraction_e * mean-prob_e
        # (first-choice fractions, Switch-Transformer eq. 4), plus the
        # router z-loss mean(logsumexp(logits)^2) that keeps gate logits
        # from drifting large (ST-MoE)
        frac = e_hot.mean(axis=0)                            # (E,)
        mean_p = probs.mean(axis=0)
        aux = (frac * mean_p).sum() * float(E)
        if self._z_coef:
            zmax = logits.max(axis=-1, keepdims=True)
            z = ((logits - zmax).exp().sum(axis=-1)).log() \
                + zmax.squeeze(-1)
            aux = aux + float(self._z_coef) * (z * z).mean()
        if _collector is not None:
            _collector.append(aux)
        if not isinstance(aux._data, jax.core.Tracer):
            self.aux_loss = aux

        # dispatch -> expert FFN (stacked weights) -> combine
        xe = ndops.einsum("nec,nd->ecd", dispatch, flat)     # (E, C, d)
        h = ndops.einsum("ecd,edh->ech", xe, self.expert_w1.data())
        h = h + self.expert_b1.data().reshape((E, 1, self._H))
        h = npx.gelu(h) if self._act == "gelu" else npx.relu(h)
        ye = ndops.einsum("ech,ehu->ecu", h, self.expert_w2.data())
        ye = ye + self.expert_b2.data().reshape((E, 1, -1))
        out = ndops.einsum("nec,ecu->nu", combine, ye)       # (N, units)

        units = out.shape[-1]
        return out.reshape(tuple(shape[:-1]) + (units,))
