"""Ring attention — sequence-parallel attention over a mesh axis.

NEW capability beyond the reference (SURVEY.md 5.7): leezu/mxnet's long-
sequence story is bucketing + truncated BPTT; it has no sequence
parallelism at all.  This module shards the sequence dimension across the
``sp`` mesh axis and computes exact attention by rotating K/V blocks
around the ring with ``jax.lax.ppermute`` (one neighbor hop per step —
the collective rides ICI), combining partial results with the online-
softmax rule so nothing O(T²) ever materializes per device.

Math: per ring step each device holds one K/V block; scores for the local
Q block are combined via the running (max, denominator, accumulator)
triple — the same rule the Pallas flash kernel uses within a chip
(ops/pallas/attention.py), applied here across chips.  Backward is plain
reverse-mode through the ``lax.scan`` (ppermute transposes to the reverse
rotation automatically); ``jax.checkpoint`` on the per-step body keeps
residual memory at one K/V block per step.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..base import getenv, register_env, register_graph_knob

__all__ = ["ring_attention", "local_ring_attention", "sequence_parallel",
           "current_sequence_parallel"]

_NEG_INF = -1e30

register_env("MXNET_RING_FLASH", 1,
             "Route eligible ring-attention blocks through the Pallas "
             "flash kernels (0 disables; falls back to the dense online-"
             "softmax block update).")

_RING_FLASH_LAST = [None]


def _ring_flash_enabled() -> bool:
    """Resolve MXNET_RING_FLASH OUTSIDE traced closures.  Toggling after
    a program compiled must re-trace, not silently replay the stale
    executable, so a change bumps the gluon graph epoch (the same
    invariant the remat/flash knobs keep)."""
    cur = bool(int(getenv("MXNET_RING_FLASH", 1)))
    if _RING_FLASH_LAST[0] is None:
        _RING_FLASH_LAST[0] = cur
    elif _RING_FLASH_LAST[0] != cur:
        _RING_FLASH_LAST[0] = cur
        from ..gluon.block import invalidate_cached_graphs
        invalidate_cached_graphs()
    return cur


register_graph_knob(_ring_flash_enabled)

# Active sequence-parallel context: attention ops consult this to route
# through ring attention (set by SPMDTrainer or the user context manager).
_sp_state = {"mesh": None, "axis": None}


class sequence_parallel:
    """Context manager: route attention ops through ring attention over
    ``axis`` of ``mesh`` while active.  SPMDTrainer enters this
    automatically when its mesh has an ``sp`` axis."""

    def __init__(self, mesh: "jax.sharding.Mesh", axis: str = "sp") -> None:
        self.mesh, self.axis = mesh, axis
        self._prev = None

    def __enter__(self) -> "sequence_parallel":
        self._prev = dict(_sp_state)
        _sp_state["mesh"], _sp_state["axis"] = self.mesh, self.axis
        return self

    def __exit__(self, *exc) -> None:
        _sp_state.update(self._prev)


def current_sequence_parallel():
    """(mesh, axis) if a sequence-parallel context is active, else None."""
    if _sp_state["mesh"] is None:
        return None
    return _sp_state["mesh"], _sp_state["axis"]


def _block_update(q, k, v, m, l, acc, scale, row0, col0, causal, kv_len,
                  bias_blk=None, keep=None, rate: float = 0.0):
    """Online-softmax update of (m, l, acc) with one K/V block.

    q: (B, Tq, H, D); k/v: (B, Tk, H, D); m/l: (B, Tq, H, 1);
    acc: (B, Tq, H, D). row0/col0 are the global offsets of the local Q
    block and the current K/V block; kv_len masks ragged padding.
    bias_blk: additive score bias for this block's columns, broadcastable
    to (B, Tq, H, Tk). keep/rate: probability-dropout mask for the block
    (the denominator uses the UNdropped probabilities, matching the
    Pallas flash kernel and inverted-dropout convention).
    """
    s = jnp.einsum("bqhd,bkhd->bqhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias_blk is not None:
        s = s + bias_blk.astype(jnp.float32)
    col = col0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
    mask = col < kv_len
    if causal:
        row = row0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = jnp.logical_and(mask, col <= row)
    s = jnp.where(mask, s, _NEG_INF)

    m_cur = jnp.max(s, axis=3, keepdims=True)          # (B, Tq, H, 1)
    m_new = jnp.maximum(m, m_cur)
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_new = alpha * l + jnp.sum(p, axis=3, keepdims=True)
    if rate > 0.0:
        p = jnp.where(keep, p / (1.0 - rate), 0.0)
    acc_new = acc * alpha + jnp.einsum(
        "bqhk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


# ---------------------------------------------------------------------------
# Flash-kernel ring (r4, VERDICT r3 weak 4): each ring step computes its
# block with the Pallas flash kernel instead of materialized O(Tl*Tk)
# scores. Per-block (out, lse) pairs combine with the log-sum-exp rule;
# a custom VJP re-runs the per-block flash BACKWARD kernels with the
# GLOBAL row stats (the same trick the kernel itself uses across its
# k-blocks), with dk/dv accumulators ppermuting home alongside their
# K/V blocks.
# ---------------------------------------------------------------------------

def _ring_flash_block(q_h, k_h, v_h, bias_h, scale, case, blk_cfg):
    """One ring block's flash forward: returns (o f32 (B,H,Tl,D),
    lse (B,H,Tl,1)). case: 0 = fully visible, 1 = aligned causal
    diagonal, 2 = fully masked (skip compute)."""
    from ..ops.pallas.attention import _flash_forward
    bq, bk, interpret = blk_cfg
    B, H, Tl, D = q_h.shape

    def before(_):
        o, lse = _flash_forward(q_h, k_h, v_h, bias_h, None, scale,
                                False, bq, bk, 0.0, interpret)
        return o.astype(jnp.float32), lse

    def diag(_):
        o, lse = _flash_forward(q_h, k_h, v_h, bias_h, None, scale,
                                True, bq, bk, 0.0, interpret)
        return o.astype(jnp.float32), lse

    def after(_):
        return (jnp.zeros((B, H, Tl, D), jnp.float32),
                jnp.full((B, H, Tl, 1), _NEG_INF, jnp.float32))

    return jax.lax.switch(case, [before, diag, after], None)


def _ring_flash_bwd_block(q_h, k_h, v_h, bias_h, o_h, lse_h, g_h, scale,
                          case, blk_cfg, want_dbias):
    """One ring block's flash backward with GLOBAL (o, lse): returns
    (dq, dk, dv, dbias) partial grads for this block."""
    from ..ops.pallas.attention import _flash_backward
    bq, bk, interpret = blk_cfg

    def run(causal_flag):
        def f(_):
            dq, dk, dv, db = _flash_backward(
                q_h, k_h, v_h, bias_h, None, o_h, lse_h, g_h, scale,
                causal_flag, bq, bk, 0.0, interpret,
                bias_grad=want_dbias)
            if db is None:
                db = jnp.zeros((1, 1, 1, 1), jnp.float32)
            return (dq.astype(jnp.float32), dk.astype(jnp.float32),
                    dv.astype(jnp.float32), db.astype(jnp.float32))
        return f

    def after(_):
        db_shape = bias_h.shape if (bias_h is not None and want_dbias) \
            else (1, 1, 1, 1)
        return (jnp.zeros(q_h.shape, jnp.float32),
                jnp.zeros(k_h.shape, jnp.float32),
                jnp.zeros(v_h.shape, jnp.float32),
                jnp.zeros(db_shape, jnp.float32))

    return jax.lax.switch(case, [run(False), run(True), after], None)


def _case_of(src, my, causal: bool):
    if not causal:
        return jnp.int32(0)
    return jnp.where(src < my, jnp.int32(0),
                     jnp.where(src == my, jnp.int32(1), jnp.int32(2)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_ring(q, k, v, bias, axis_name, n_shards, scale, causal):
    out, _ = _flash_ring_fwd(q, k, v, bias, axis_name, n_shards, scale,
                             causal)
    return out


def _flash_ring_fwd(q, k, v, bias, axis_name, n_shards, scale, causal):
    """q/k/v: (B, Tl, H, D) local shards; bias: (B|1, Tl|1, H|1, Tk_g)
    row stripe or None. Returns (out, residuals)."""
    from ..ops.pallas.attention import (_interpret_for, DEFAULT_BLOCK_Q,
                                        DEFAULT_BLOCK_K)
    B, Tl, H, D = q.shape
    Tk = k.shape[1]
    my = jax.lax.axis_index(axis_name)
    interpret = _interpret_for(q)
    # final block legalization happens inside the kernels; this is just
    # the requested upper bound
    blk_cfg = (min(DEFAULT_BLOCK_Q, Tl), min(DEFAULT_BLOCK_K, Tk),
               interpret)
    q_h = jnp.swapaxes(q, 1, 2)                       # (B,H,Tl,D)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    def body(carry, step):
        k_blk, v_blk, acc, lse = carry
        src = (my - step) % n_shards
        case = _case_of(src, my, causal)
        bias_h = None
        if bias is not None:
            stripe = jax.lax.dynamic_slice_in_dim(
                bias, src * Tk, Tk, axis=3)           # (B|1,Tl|1,H|1,Tk)
            bias_h = jnp.swapaxes(stripe, 1, 2)       # (B|1,H|1,Tl|1,Tk)
        o_blk, lse_blk = _ring_flash_block(
            q_h, jnp.swapaxes(k_blk, 1, 2), jnp.swapaxes(v_blk, 1, 2),
            bias_h, scale, case, blk_cfg)
        lse_new = jnp.logaddexp(lse, lse_blk)
        # avoid exp(-inf - -inf) NaNs before any block contributed
        w_old = jnp.where(jnp.isfinite(lse_new), jnp.exp(lse - lse_new),
                          0.0)
        w_new = jnp.where(jnp.isfinite(lse_new),
                          jnp.exp(lse_blk - lse_new), 0.0)
        acc = acc * w_old + o_blk * w_new
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, acc, lse_new), None

    acc0 = jnp.zeros((B, H, Tl, D), jnp.float32)
    lse0 = jnp.full((B, H, Tl, 1), _NEG_INF, jnp.float32)
    (_, _, acc, lse), _ = jax.lax.scan(
        body, (k, v, acc0, lse0), jnp.arange(n_shards))
    out = jnp.swapaxes(acc, 1, 2).astype(q.dtype)     # (B,Tl,H,D)
    return out, (q, k, v, bias, out, lse)


def _flash_ring_bwd(axis_name, n_shards, scale, causal, res, g):
    from ..ops.pallas.attention import (_interpret_for, DEFAULT_BLOCK_Q,
                                        DEFAULT_BLOCK_K)
    q, k, v, bias, out, lse = res
    B, Tl, H, D = q.shape
    Tk = k.shape[1]
    my = jax.lax.axis_index(axis_name)
    interpret = _interpret_for(q)
    blk_cfg = (min(DEFAULT_BLOCK_Q, Tl), min(DEFAULT_BLOCK_K, Tk),
               interpret)
    q_h = jnp.swapaxes(q, 1, 2)
    o_h = jnp.swapaxes(out, 1, 2)
    g_h = jnp.swapaxes(g, 1, 2)
    want_dbias = bias is not None
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    def body(carry, step):
        k_blk, v_blk, dk_blk, dv_blk, dq_acc, db_acc = carry
        src = (my - step) % n_shards
        case = _case_of(src, my, causal)
        bias_h = None
        if bias is not None:
            stripe = jax.lax.dynamic_slice_in_dim(bias, src * Tk, Tk,
                                                  axis=3)
            bias_h = jnp.swapaxes(stripe, 1, 2)
        dq_i, dk_i, dv_i, db_i = _ring_flash_bwd_block(
            q_h, jnp.swapaxes(k_blk, 1, 2), jnp.swapaxes(v_blk, 1, 2),
            bias_h, o_h, lse, g_h, scale, case, blk_cfg, want_dbias)
        dq_acc = dq_acc + dq_i
        # (B,H,Tk,D) -> the ring layout, accumulated onto THIS block's
        # rotating gradient slot — it ppermutes home with the block
        dk_blk = dk_blk + jnp.swapaxes(dk_i, 1, 2)
        dv_blk = dv_blk + jnp.swapaxes(dv_i, 1, 2)
        if want_dbias:
            db_stripe = jnp.swapaxes(db_i, 1, 2)      # (B|1,Tl|1,H|1,Tk)
            db_acc = jax.lax.dynamic_update_slice_in_dim(
                db_acc, db_stripe, src * Tk, axis=3)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        dk_blk = jax.lax.ppermute(dk_blk, axis_name, perm)
        dv_blk = jax.lax.ppermute(dv_blk, axis_name, perm)
        return (k_blk, v_blk, dk_blk, dv_blk, dq_acc, db_acc), None

    dk0 = jnp.zeros_like(k, jnp.float32)
    dv0 = jnp.zeros_like(v, jnp.float32)
    dq0 = jnp.zeros((B, H, Tl, D), jnp.float32)
    db0 = (jnp.zeros(bias.shape, jnp.float32) if want_dbias
           else jnp.zeros((1,), jnp.float32))
    (_, _, dk_f, dv_f, dq_f, db_f), _ = jax.lax.scan(
        body, (k, v, dk0, dv0, dq0, db0), jnp.arange(n_shards))
    dq = jnp.swapaxes(dq_f, 1, 2).astype(q.dtype)
    d_bias = db_f.astype(bias.dtype) if want_dbias else None
    return dq, dk_f.astype(k.dtype), dv_f.astype(v.dtype), d_bias


_flash_ring.defvjp(_flash_ring_fwd, _flash_ring_bwd)


def local_ring_attention(q, k, v, axis_name: str, n_shards: int,
                         scale: Optional[float] = None,
                         causal: bool = False, kv_len: Optional[int] = None,
                         bias=None, dropout: float = 0.0,
                         dropout_key=None, use_flash: Optional[bool] = None):
    """Per-device body: exact attention with K/V rotating around the ring.

    Call inside ``shard_map`` with the sequence axis sharded over
    ``axis_name``. q/k/v: (B, T_local, H, D) — this device's sequence
    shard. ``bias``: this device's ROW stripe of the additive score bias
    in (B|1, Tl|1, H|1, T_global) layout — columns for the held block are
    dynamically sliced each ring step, so padding masks and dense biases
    stay on the ring path. ``dropout``/``dropout_key``: probability
    dropout; the key folds per (destination shard, source block), so the
    mask is a pure function of global tile coordinates (backward's scan
    recompute regenerates it). Returns (B, T_local, H, D).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    B, Tl, H, D = q.shape
    Tk = k.shape[1]
    my = jax.lax.axis_index(axis_name)
    if kv_len is None:
        kv_len = n_shards * Tk
    row0 = my * Tl
    rate = float(dropout)

    # r4: per-shard blocks go through the Pallas flash kernel (fwd AND
    # bwd) instead of materialized scores whenever the block layout
    # allows — long-context sp training gets blockwise-kernel math both
    # on-chip and across the ring. Fallback cases keep the dense block
    # update: ragged kv_len (the flash kernel's kv mask is static),
    # attention dropout (no interpret-mode PRNG for the CPU tests), and
    # unequal q/k shards (the diagonal case needs alignment).  The knob
    # resolves through the graph-epoch poller (never os.environ inside
    # the trace): callers that cache executables pass use_flash from
    # outside; the default still re-dispatches on toggle because
    # _ring_flash_enabled bumps the epoch the caches key on.
    if use_flash is None:
        use_flash = _ring_flash_enabled()
    if (use_flash
            and rate == 0.0 and kv_len == n_shards * Tk
            and Tl == Tk and Tl >= 8):
        return _flash_ring(q, k, v, bias, axis_name, n_shards,
                           float(scale), causal)

    m0 = jnp.full((B, Tl, H, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Tl, H, 1), jnp.float32)
    acc0 = jnp.zeros((B, Tl, H, D), jnp.float32)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    @jax.checkpoint
    def body(carry, step):
        k_blk, v_blk, m, l, acc = carry
        src = (my - step) % n_shards          # origin of the held block
        col0 = src * Tk
        bias_blk = None
        if bias is not None:
            bias_blk = jax.lax.dynamic_slice_in_dim(bias, col0, Tk, axis=3)
        keep = None
        if rate > 0.0:
            key = jax.random.fold_in(jax.random.fold_in(dropout_key, my),
                                     src)
            keep = jax.random.bernoulli(key, 1.0 - rate, (B, Tl, H, Tk))
        m, l, acc = _block_update(q, k_blk, v_blk, m, l, acc, scale,
                                  row0, col0, causal, kv_len,
                                  bias_blk=bias_blk, keep=keep, rate=rate)
        # rotate: send our block to the next device, receive from previous
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m, l, acc), None

    (_, _, m, l, acc), _ = jax.lax.scan(
        body, (k, v, m0, l0, acc0), jnp.arange(n_shards))
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: "jax.sharding.Mesh", axis: str = "sp",
                   scale: Optional[float] = None, causal: bool = False,
                   bias=None, dropout: float = 0.0, dropout_seed=None):
    """Sequence-parallel exact attention over mesh axis ``axis``.

    q/k/v: (B, T, H, D) logically global; T must divide by the axis size.
    The call shard_maps over the mesh: batch replicated over the axis,
    sequence sharded; inside, K/V blocks ride the ring via ppermute.
    Differentiable; composable with jit and other mesh axes (other axes
    see this function as purely local compute).

    bias (r3): additive score bias (B|1, H|1, Tq|1, Tk) — padding masks
    and dense biases included; its row dim shards over the ring with q,
    its column dim stays whole per device (memory Tq·Tk/n) and is sliced
    per ring step. dropout/dropout_seed ((2,) int32): attention-
    probability dropout with tile-deterministic masks, so sp training
    with padded batches and dropout STAYS on the ring path.
    """
    if axis not in mesh.axis_names:
        return _dense(q, k, v, scale, causal, bias, dropout, dropout_seed)
    n = mesh.shape[axis]
    if n == 1 or q.shape[1] % n != 0 or k.shape[1] % n != 0 or \
            (bias is not None and
             (bias.shape[2] not in (1, q.shape[1])
              or bias.shape[3] != k.shape[1])):
        return _dense(q, k, v, scale, causal, bias, dropout, dropout_seed)

    # Collective accounting (traced: this usually runs under jit, so one
    # count per compiled program, not per executed step — the eager
    # kvstore path is the per-step accounting). Wire bytes per rotation:
    # each K/V element crosses the ring n-1 times.
    from .. import metrics as _metrics
    _metrics.COLLECTIVE_CALLS.labels(
        collective="ring_attention", traced="1").inc()
    _metrics.COLLECTIVE_BYTES.labels(
        collective="ring_attention", traced="1").inc(
        (n - 1) * (k.size * k.dtype.itemsize + v.size * v.dtype.itemsize))

    # carry the surrounding dp/tp layout through the shard_map so GSPMD
    # does not insert gathers around it (SPMDTrainer shards batch over dp
    # and heads over tp)
    def _axis_if(name, dim_size):
        return name if (name in mesh.axis_names and name != axis
                        and dim_size % mesh.shape[name] == 0) else None

    bax = _axis_if("dp", q.shape[0])
    hax = _axis_if("tp", q.shape[2])
    spec = P(bax, axis, hax, None)
    key = None
    if dropout > 0.0:
        key = jax.random.wrap_key_data(
            jnp.asarray(dropout_seed, jnp.uint32).reshape(2,),
            impl="threefry2x32")

    in_specs = [spec, spec, spec]
    args = [q, k, v]
    if bias is not None:
        # (B|1, H|1, Tq|1, Tk) -> the ring layout (B|1, Tq|1, H|1, Tk);
        # rows shard with q, columns stay whole per device
        bias_t = jnp.swapaxes(bias, 1, 2)
        in_specs.append(P(
            bax if bias_t.shape[0] > 1 else None,
            axis if bias_t.shape[1] > 1 else None,
            hax if bias_t.shape[2] > 1 else None, None))
        args.append(bias_t)

    use_flash = _ring_flash_enabled()   # resolved OUTSIDE the traced fn

    def fn(qq, kk, vv, *rest):
        return local_ring_attention(
            qq, kk, vv, axis_name=axis, n_shards=n, scale=scale,
            causal=causal, bias=rest[0] if rest else None,
            dropout=dropout, dropout_key=key, use_flash=use_flash)

    try:
        from jax import shard_map
        kw = {"check_vma": False}
    except ImportError:     # jax < 0.8
        from jax.experimental.shard_map import shard_map
        kw = {"check_rep": False}
    return shard_map(fn, mesh=mesh, in_specs=tuple(in_specs),
                     out_specs=spec, **kw)(*args)


def _dense(q, k, v, scale, causal, bias=None, dropout: float = 0.0,
           dropout_seed=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bqhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + jnp.swapaxes(bias, 1, 2).astype(jnp.float32)
    if causal:
        # top-left alignment (col <= row), matching the ring path and
        # jax.nn.dot_product_attention(is_causal=True)
        Tq, Tk = s.shape[1], s.shape[3]
        mask = jnp.tril(jnp.ones((Tq, Tk), bool))[None, :, None, :]
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=3)
    if dropout > 0.0:
        key = jax.random.wrap_key_data(
            jnp.asarray(dropout_seed, jnp.uint32).reshape(2,),
            impl="threefry2x32")
        keep = jax.random.bernoulli(key, 1.0 - dropout, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout), 0.0)
    return jnp.einsum("bqhk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
