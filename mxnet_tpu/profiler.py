"""Profiler — op instrumentation, Chrome-trace dump, aggregate stats.

Reference parity (leezu/mxnet): ``src/profiler/profiler.{h,cc}`` (singleton
``Profiler``, engine hooks around Opr execution, per-device stats,
chrome://tracing JSON dump, ``AggregateStats`` tables) and the Python
surface ``python/mxnet/profiler.py`` (``set_config``/``set_state``/
``pause``/``resume``/``dump``/``dumps``, ``ProfileTask``/``ProfileEvent``/
``ProfileCounter``/``ProfileFrame``/``ProfileDomain``).

Design (tpu-first): ops are instrumented at the one dispatch point
(``ndarray.register.invoke``); device-side detail comes from wrapping the
XLA profiler (``start_xla_trace``/``stop_xla_trace`` → TensorBoard xplane,
the TPU analog of the reference's NVTX emitter). Eager timings measure
dispatch by default (the reference likewise measures engine-op execution,
not python); set ``MXNET_PROFILER_SYNC=1`` to block per op and capture
true device latency.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .base import MXNetError, getenv, register_env

__all__ = ["set_config", "set_state", "start", "stop", "pause", "resume",
           "dump", "dumps", "reset", "state",
           "ProfileDomain", "ProfileTask", "ProfileEvent", "ProfileCounter",
           "ProfileFrame", "ProfileMarker", "scope",
           "start_xla_trace", "stop_xla_trace"]

register_env("MXNET_PROFILER_AUTOSTART", 0,
             "Start the profiler at import time (1 = on).")
register_env("MXNET_PROFILER_SYNC", 0,
             "Block after each profiled op to capture device latency.")

# checked on the hot dispatch path (mirrors register._amp_state pattern)
_active = {"on": False}

_LOCK = threading.Lock()


class _ProfilerState:
    def __init__(self) -> None:
        self.filename = "profile.json"
        self.profile_all = False
        self.profile_symbolic = True
        self.profile_imperative = True
        self.profile_memory = False
        self.profile_api = False
        self.aggregate_stats = True
        self.continuous_dump = False
        self.running = False
        self.paused = False
        self.events: List[Dict[str, Any]] = []
        self.agg: Dict[str, Dict[str, float]] = {}
        self.t0 = time.perf_counter()


_P = _ProfilerState()


def set_config(**kwargs: Any) -> None:
    """Configure the profiler (reference ``profiler.set_config``); accepts
    filename, profile_all, profile_symbolic, profile_imperative,
    profile_memory, profile_api, aggregate_stats, continuous_dump."""
    allowed = {"filename", "profile_all", "profile_symbolic",
               "profile_imperative", "profile_memory", "profile_api",
               "aggregate_stats", "continuous_dump"}
    for k, v in kwargs.items():
        if k not in allowed:
            raise MXNetError(f"profiler.set_config: unknown key {k!r} "
                             f"(allowed: {sorted(allowed)})")
        setattr(_P, k, v)


def state() -> str:
    return "run" if _P.running else "stop"


def _sync_flags() -> None:
    on = _P.running and not _P.paused
    _active["on"] = on
    from .ndarray import register as _reg
    _reg._profiler_state["on"] = on


def set_state(new_state: str = "stop") -> None:
    if new_state not in ("run", "stop"):
        raise MXNetError("profiler state must be 'run' or 'stop'")
    _P.running = new_state == "run"
    _P.paused = False
    _sync_flags()
    if _P.running and not _P.events:
        _P.t0 = time.perf_counter()


def start() -> None:
    set_state("run")


def stop() -> None:
    set_state("stop")


def pause() -> None:
    _P.paused = True
    _sync_flags()


def resume() -> None:
    _P.paused = False
    _sync_flags()


def reset() -> None:
    _P.events.clear()
    _P.agg.clear()
    _P.t0 = time.perf_counter()


def _now_us() -> float:
    return (time.perf_counter() - _P.t0) * 1e6


def record_op(name: str, begin_us: float, end_us: float,
              category: str = "operator") -> None:
    """Append one op execution record (called from register.invoke)."""
    with _LOCK:
        _P.events.append({"name": name, "cat": category, "ph": "X",
                          "ts": begin_us, "dur": end_us - begin_us,
                          "pid": 0, "tid": threading.get_ident() % 100000})
        a = _P.agg.setdefault(name, {"count": 0, "total": 0.0,
                                     "min": float("inf"), "max": 0.0})
        d = end_us - begin_us
        a["count"] += 1
        a["total"] += d
        a["min"] = min(a["min"], d)
        a["max"] = max(a["max"], d)


def record_span(name: str, begin_us: float, end_us: float,
                tid: Optional[int] = None,
                args: Optional[Dict[str, Any]] = None) -> None:
    """Mirror one finished tracing span into the profiler's event list
    (category ``"trace"``) so a single dump shows spans and ops on one
    timeline.  This is a direct event append — it never goes through
    the op-dispatch layer, so spans cannot fire monitor hooks, count as
    dispatched ops, or double-publish into ``mxnet_monitor_stat``."""
    ev: Dict[str, Any] = {
        "name": name, "cat": "trace", "ph": "X", "ts": begin_us,
        "dur": max(0.0, end_us - begin_us), "pid": 0,
        "tid": threading.get_ident() % 100000 if tid is None else tid}
    if args:
        ev["args"] = args
    with _LOCK:
        _P.events.append(ev)


class _OpTimer:
    """Context used by the dispatch hook."""

    __slots__ = ("name", "begin")

    def __init__(self, name: str) -> None:
        self.name = name

    def __enter__(self) -> "_OpTimer":
        self.begin = _now_us()
        return self

    def __exit__(self, *exc: Any) -> None:
        if getenv("MXNET_PROFILER_SYNC", 0):
            from . import engine
            engine.waitall()
        record_op(self.name, self.begin, _now_us())


def op_timer(name: str) -> Optional[_OpTimer]:
    if not _active["on"]:
        return None
    return _OpTimer(name)


def dump(finished: bool = True) -> str:
    """Write accumulated events as chrome://tracing JSON; returns path."""
    payload = {
        "traceEvents": [
            {"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": "mxnet_tpu"}},
            *_P.events,
        ],
        "displayTimeUnit": "ms",
    }
    with open(_P.filename, "w") as f:
        json.dump(payload, f)
    if finished:
        reset()
    return _P.filename


def dumps(reset_stats: bool = False) -> str:
    """Aggregate per-op summary table (reference ``AggregateStats``)."""
    lines = [f"{'Name':<40}{'Count':>8}{'Total(us)':>14}"
             f"{'Min(us)':>12}{'Max(us)':>12}{'Avg(us)':>12}"]
    with _LOCK:
        for name, a in sorted(_P.agg.items(),
                              key=lambda kv: -kv[1]["total"]):
            avg = a["total"] / max(a["count"], 1)
            lines.append(f"{name:<40}{int(a['count']):>8}"
                         f"{a['total']:>14.1f}{a['min']:>12.1f}"
                         f"{a['max']:>12.1f}{avg:>12.1f}")
        if reset_stats:
            _P.agg.clear()
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# User-level markers (reference: c_api_profile.cc objects)
# ---------------------------------------------------------------------------

class ProfileDomain:
    """Named grouping for marker objects (reference ``ProfileDomain``)."""

    def __init__(self, name: str) -> None:
        self.name = name


class ProfileTask:
    """start()/stop() span attributed to a domain."""

    def __init__(self, name: str, domain: Optional[ProfileDomain] = None) -> None:
        self.name = name
        self.domain = domain
        self._begin: Optional[float] = None

    def start(self) -> None:
        self._begin = _now_us()

    def stop(self) -> None:
        if self._begin is None:
            raise MXNetError(f"ProfileTask {self.name!r}: stop before start")
        cat = self.domain.name if self.domain else "task"
        record_op(self.name, self._begin, _now_us(), category=cat)
        self._begin = None

    def __enter__(self) -> "ProfileTask":
        self.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()


ProfileFrame = ProfileTask  # frames are tasks that may nest (same record)


class ProfileEvent(ProfileTask):
    """Instant or spanning user event."""

    def mark(self) -> None:
        t = _now_us()
        with _LOCK:
            _P.events.append({"name": self.name, "cat": "event", "ph": "i",
                              "ts": t, "pid": 0, "s": "g",
                              "tid": threading.get_ident() % 100000})


class ProfileCounter:
    """Named counter emitted into the trace (reference ProfileCounter)."""

    def __init__(self, name: str, domain: Optional[ProfileDomain] = None) -> None:
        self.name = name
        self.value = 0

    def set_value(self, value: float) -> None:
        self.value = value
        with _LOCK:
            _P.events.append({"name": self.name, "ph": "C", "ts": _now_us(),
                              "pid": 0, "args": {self.name: value}})

    def increment(self, delta: float = 1) -> None:
        self.set_value(self.value + delta)

    def decrement(self, delta: float = 1) -> None:
        self.set_value(self.value - delta)

    def __iadd__(self, delta: float) -> "ProfileCounter":
        self.increment(delta)
        return self

    def __isub__(self, delta: float) -> "ProfileCounter":
        self.decrement(delta)
        return self


class ProfileMarker(ProfileEvent):
    pass


class scope:
    """``with profiler.scope('phase'):`` convenience span."""

    def __init__(self, name: str) -> None:
        self._task = ProfileTask(name)

    def __enter__(self) -> "scope":
        self._task.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._task.stop()


# ---------------------------------------------------------------------------
# XLA device-side tracing (TPU analog of the NVTX emitter)
# ---------------------------------------------------------------------------

_xla_trace_dir: Optional[str] = None


def start_xla_trace(logdir: str = "/tmp/mxnet_tpu_trace") -> None:
    """Start the XLA/xplane profiler; view in TensorBoard."""
    global _xla_trace_dir
    import jax
    jax.profiler.start_trace(logdir)
    _xla_trace_dir = logdir


def stop_xla_trace() -> Optional[str]:
    global _xla_trace_dir
    import jax
    jax.profiler.stop_trace()
    d, _xla_trace_dir = _xla_trace_dir, None
    return d


if getenv("MXNET_PROFILER_AUTOSTART", 0):
    set_state("run")
