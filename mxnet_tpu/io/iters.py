"""Named data iterators — the reference's C++-registered iterator set.

Reference parity (leezu/mxnet): ``src/io/`` — ``ImageRecordIter``
(iter_image_recordio_2.cc), ``CSVIter`` (iter_csv.cc), ``LibSVMIter``
(iter_libsvm.cc), ``MNISTIter`` (iter_mnist.cc) — created by name with
string kwargs through the IO registry.

Design (tpu-first): decode/augment runs on host workers (the C++
prefetcher in ``src/recordio.cc`` + PIL decode), batches land as jax
arrays ready for device_put; there is no per-backend iterator zoo.
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .io import DataBatch, DataDesc, DataIter

__all__ = ["ImageRecordIter", "CSVIter", "LibSVMIter", "MNISTIter",
           "create", "register_iter"]


def ImageRecordIter(path_imgrec: str, data_shape, batch_size: int,
                    path_imgidx: Optional[str] = None,
                    shuffle: bool = False, rand_crop: bool = False,
                    rand_mirror: bool = False, mean_r: float = 0.0,
                    mean_g: float = 0.0, mean_b: float = 0.0,
                    std_r: float = 1.0, std_g: float = 1.0,
                    std_b: float = 1.0, scale: float = 1.0,
                    resize: int = -1, part_index: int = 0,
                    num_parts: int = 1, label_width: int = 1,
                    preprocess_threads: int = 0, **kwargs: Any):
    """RecordIO image iterator with C++-iterator kwargs
    (reference ``mx.io.ImageRecordIter``).  Builds the augmenter chain
    the reference's ``DefaultImageAugmenter`` would apply."""
    from ..image import (CastAug, CenterCropAug, HorizontalFlipAug,
                         ImageIter, RandomCropAug, ResizeAug)
    c, h, w = data_shape
    augs: List[Any] = []
    if resize > 0:
        augs.append(ResizeAug(resize))
    augs.append(RandomCropAug((w, h)) if rand_crop
                else CenterCropAug((w, h)))
    if rand_mirror:
        augs.append(HorizontalFlipAug(0.5))
    augs.append(CastAug())

    mean = onp.array([mean_r, mean_g, mean_b], dtype=onp.float32)
    std = onp.array([std_r, std_g, std_b], dtype=onp.float32)

    class _NormAug:
        # reference DefaultImageAugmenter order: (pixel - mean) / std,
        # then scale
        def __call__(self, src):
            out = src
            if mean.any():
                out = out - NDArray(mean.reshape(1, 1, 3))
            if (std != 1.0).any():
                out = out / NDArray(std.reshape(1, 1, 3))
            if scale != 1.0:
                out = out * scale
            return out

    augs.append(_NormAug())
    return ImageIter(batch_size=batch_size, data_shape=tuple(data_shape),
                     path_imgrec=path_imgrec, path_imgidx=path_imgidx,
                     shuffle=shuffle, aug_list=augs,
                     part_index=part_index, num_parts=num_parts,
                     label_width=label_width, **kwargs)


class CSVIter(DataIter):
    """CSV reader (reference ``mx.io.CSVIter`` / iter_csv.cc)."""

    def __init__(self, data_csv: str, data_shape,
                 label_csv: Optional[str] = None, label_shape=(1,),
                 batch_size: int = 1, round_batch: bool = True,
                 dtype: str = "float32", **kwargs: Any) -> None:
        super().__init__(batch_size)
        self._data = onp.loadtxt(data_csv, delimiter=",",
                                 dtype=dtype, ndmin=2)
        n = self._data.shape[0]
        self._data = self._data.reshape((n,) + tuple(data_shape))
        if label_csv is not None:
            self._label = onp.loadtxt(label_csv, delimiter=",",
                                      dtype="float32", ndmin=2)
            self._label = self._label.reshape((n,) + tuple(label_shape))
        else:
            self._label = onp.zeros((n,) + tuple(label_shape),
                                    dtype="float32")
        self._round = round_batch
        self._cursor = 0
        self.provide_data = [DataDesc("data",
                                      (batch_size,) + tuple(data_shape),
                                      dtype)]
        self.provide_label = [DataDesc(
            "label", (batch_size,) + tuple(label_shape), "float32")]

    def reset(self) -> None:
        self._cursor = 0

    def next(self) -> DataBatch:
        n = self._data.shape[0]
        if self._cursor >= n:
            raise StopIteration
        end = self._cursor + self.batch_size
        idx = onp.arange(self._cursor, end)
        pad = 0
        if end > n:
            if self._round:
                idx = idx % n               # wrap (reference round_batch)
            else:
                pad = end - n
                idx = onp.minimum(idx, n - 1)
        self._cursor = end
        return DataBatch([NDArray(self._data[idx])],
                         [NDArray(self._label[idx])], pad=pad)


class LibSVMIter(DataIter):
    """LibSVM sparse reader -> CSR batches (reference iter_libsvm.cc)."""

    def __init__(self, data_libsvm: str, data_shape,
                 batch_size: int = 1, **kwargs: Any) -> None:
        super().__init__(batch_size)
        self._dim = int(data_shape[0] if hasattr(data_shape, "__len__")
                        else data_shape)
        labels, rows = [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                row = {}
                for kv in parts[1:]:
                    k, v = kv.split(":")
                    row[int(k)] = float(v)
                rows.append(row)
        self._labels = onp.asarray(labels, dtype=onp.float32)
        self._rows = rows
        self._cursor = 0
        self.provide_data = [DataDesc("data",
                                      (batch_size, self._dim), "float32")]
        self.provide_label = [DataDesc("label", (batch_size,), "float32")]

    def reset(self) -> None:
        self._cursor = 0

    def next(self) -> DataBatch:
        from ..ndarray import sparse as _sp
        n = len(self._rows)
        if self._cursor >= n:
            raise StopIteration
        end = min(self._cursor + self.batch_size, n)
        pad = self._cursor + self.batch_size - end
        indptr = [0]
        indices: List[int] = []
        values: List[float] = []
        for i in range(self._cursor, end):
            for k in sorted(self._rows[i]):
                indices.append(k)
                values.append(self._rows[i][k])
            indptr.append(len(indices))
        for _ in range(pad):
            indptr.append(len(indices))
        label = onp.zeros((self.batch_size,), dtype=onp.float32)
        label[: end - self._cursor] = self._labels[self._cursor:end]
        self._cursor += self.batch_size
        data = _sp.csr_matrix(
            (onp.asarray(values, dtype=onp.float32),
             onp.asarray(indices, dtype=onp.int64),
             onp.asarray(indptr, dtype=onp.int64)),
            shape=(self.batch_size, self._dim))
        return DataBatch([data], [NDArray(label)], pad=pad)


def _read_idx(path: str) -> onp.ndarray:
    """Parse an IDX file (optionally gzipped) — the raw MNIST format."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        dtype_code = (magic >> 8) & 0xFF
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        dt = {0x08: onp.uint8, 0x09: onp.int8, 0x0B: onp.int16,
              0x0C: onp.int32, 0x0D: onp.float32,
              0x0E: onp.float64}[dtype_code]
        data = onp.frombuffer(f.read(), dtype=onp.dtype(dt).newbyteorder(
            ">"))
        return data.reshape(dims).astype(dt)


class MNISTIter(DataIter):
    """Raw-IDX MNIST iterator (reference iter_mnist.cc)."""

    def __init__(self, image: str, label: str, batch_size: int = 128,
                 shuffle: bool = False, flat: bool = False,
                 seed: int = 0, **kwargs: Any) -> None:
        super().__init__(batch_size)
        imgs = _read_idx(image).astype(onp.float32) / 255.0
        self._labels = _read_idx(label).astype(onp.float32)
        if flat:
            imgs = imgs.reshape(imgs.shape[0], -1)
        else:
            imgs = imgs.reshape(imgs.shape[0], 1,
                                imgs.shape[1], imgs.shape[2])
        if shuffle:
            order = onp.random.RandomState(seed).permutation(len(imgs))
            imgs, self._labels = imgs[order], self._labels[order]
        self._imgs = imgs
        self._cursor = 0
        self.provide_data = [DataDesc(
            "data", (batch_size,) + imgs.shape[1:], "float32")]
        self.provide_label = [DataDesc("label", (batch_size,), "float32")]

    def reset(self) -> None:
        self._cursor = 0

    def next(self) -> DataBatch:
        n = len(self._imgs)
        if self._cursor + self.batch_size > n:
            raise StopIteration
        sl = slice(self._cursor, self._cursor + self.batch_size)
        self._cursor += self.batch_size
        return DataBatch([NDArray(self._imgs[sl])],
                         [NDArray(self._labels[sl])])


# -- registry (MXListDataIters analog) --------------------------------------

_ITER_REGISTRY: Dict[str, Any] = {
    "ImageRecordIter": ImageRecordIter,
    "CSVIter": CSVIter,
    "LibSVMIter": LibSVMIter,
    "MNISTIter": MNISTIter,
}


def register_iter(name: str, fn: Any) -> Any:
    _ITER_REGISTRY[name] = fn
    return fn


def create(name: str, **kwargs: Any):
    """Create an iterator by registry name (C-iterator creation analog)."""
    try:
        cls = _ITER_REGISTRY[name]
    except KeyError:
        raise MXNetError(f"unknown data iter {name!r} (registered: "
                         f"{sorted(_ITER_REGISTRY)})") from None
    return cls(**kwargs)
