"""``mx.io`` — legacy DataIter interface (reference:
``python/mxnet/io/io.py`` — DataBatch/DataDesc/DataIter/NDArrayIter/
ResizeIter/PrefetchingIter; the C++ iterator registry collapses into
Python iterators over the same batch protocol)."""
from .io import (DataBatch, DataDesc, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter)
from .iters import (ImageRecordIter, CSVIter, LibSVMIter, MNISTIter,
                    create, register_iter)
from .prefetch import DevicePrefetcher

__all__ = ["DataBatch", "DataDesc", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "ImageRecordIter", "CSVIter", "LibSVMIter",
           "MNISTIter", "create", "register_iter", "DevicePrefetcher"]
