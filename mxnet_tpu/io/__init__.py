"""``mx.io`` — legacy DataIter interface (reference:
``python/mxnet/io/io.py`` — DataBatch/DataDesc/DataIter/NDArrayIter/
ResizeIter/PrefetchingIter; the C++ iterator registry collapses into
Python iterators over the same batch protocol)."""
from .io import (DataBatch, DataDesc, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter)

__all__ = ["DataBatch", "DataDesc", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter"]
