"""Legacy DataIter protocol (reference: ``python/mxnet/io/io.py``)."""
from __future__ import annotations

from collections import namedtuple
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["DataBatch", "DataDesc", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)

    @staticmethod
    def get_batch_axis(layout: Optional[str]) -> int:
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One batch: lists of data/label NDArrays + pad/index metadata."""

    def __init__(self, data: Sequence[NDArray],
                 label: Optional[Sequence[NDArray]] = None,
                 pad: int = 0, index: Any = None,
                 provide_data: Any = None, provide_label: Any = None) -> None:
        self.data = list(data) if data is not None else None
        self.label = list(label) if label is not None else None
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self) -> str:
        shapes = [d.shape for d in self.data] if self.data else []
        return f"DataBatch: data shapes: {shapes} pad: {self.pad}"


class DataIter:
    """Base iterator (reference protocol: reset/next/iter_next +
    provide_data/provide_label)."""

    def __init__(self, batch_size: int = 0) -> None:
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self) -> None:
        pass

    def __next__(self) -> DataBatch:
        return self.next()

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(),
                             self.getpad(), self.getindex())
        raise StopIteration

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self) -> int:
        return 0


def _init_data(data, allow_empty: bool, default_name: str):
    if data is None:
        return []
    if isinstance(data, (NDArray, _np.ndarray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        data = {f"{default_name}{('_%d' % i) if i else ''}": d
                for i, d in enumerate(data)}
    out = []
    for name, arr in data.items():
        if not isinstance(arr, NDArray):
            arr = NDArray(_np.asarray(arr))
        out.append((name, arr))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: mx.io.NDArrayIter) with
    pad/discard/roll_over last-batch handling."""

    def __init__(self, data: Any, label: Any = None, batch_size: int = 1,
                 shuffle: bool = False, last_batch_handle: str = "pad",
                 data_name: str = "data", label_name: str = "softmax_label"
                 ) -> None:
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]
        if shuffle:
            order = _np.random.permutation(self.num_data)
            self.data = [(n, NDArray(d.asnumpy()[order])) for n, d in self.data]
            self.label = [(n, NDArray(d.asnumpy()[order]))
                          for n, d in self.label]
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        # roll_over: a modular stream position persisting across epochs;
        # leftover samples carry into the next epoch (reference semantics)
        self._pos = 0
        self._avail = self.num_data
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) // batch_size

    @property
    def provide_data(self) -> List[DataDesc]:
        return [DataDesc(n, (self.batch_size,) + d.shape[1:], d.dtype)
                for n, d in self.data]

    @property
    def provide_label(self) -> List[DataDesc]:
        return [DataDesc(n, (self.batch_size,) + d.shape[1:], d.dtype)
                for n, d in self.label]

    def reset(self) -> None:
        if self.last_batch_handle == "roll_over":
            self._avail += self.num_data  # leftover carries into new epoch
        else:
            self.cursor = -self.batch_size

    def iter_next(self) -> bool:
        if self.last_batch_handle == "roll_over":
            if self._avail < self.batch_size:
                return False
            self._batch_start = self._pos
            self._pos = (self._pos + self.batch_size) % self.num_data
            self._avail -= self.batch_size
            return True
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _slice(self, arrs) -> List[NDArray]:
        from ..ndarray import ops
        out = []
        for _, a in arrs:
            if self.last_batch_handle == "roll_over":
                start = self._batch_start
                end = start + self.batch_size
                if end <= self.num_data:
                    out.append(a[start:end])
                else:
                    out.append(ops.concatenate(
                        [a[start:self.num_data], a[0:end - self.num_data]],
                        axis=0))
                continue
            end = self.cursor + self.batch_size
            if end <= self.num_data:
                out.append(a[self.cursor:end])
            else:
                # pad by wrapping (reference 'pad' semantics)
                out.append(ops.concatenate(
                    [a[self.cursor:self.num_data], a[0:end - self.num_data]],
                    axis=0))
        return out

    def getdata(self) -> List[NDArray]:
        return self._slice(self.data)

    def getlabel(self) -> List[NDArray]:
        return self._slice(self.label)

    def getpad(self) -> int:
        end = self.cursor + self.batch_size
        if self.last_batch_handle == "pad" and end > self.num_data:
            return end - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches."""

    def __init__(self, data_iter: DataIter, size: int,
                 reset_internal: bool = True) -> None:
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch: Optional[DataBatch] = None

    def reset(self) -> None:
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self) -> bool:
        if self.cur == self.size:
            return False
        try:
            self.current_batch = next(self.data_iter)
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = next(self.data_iter)
        self.cur += 1
        return True

    def next(self) -> DataBatch:
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iterators."""

    def __init__(self, iters: Union[DataIter, Sequence[DataIter]],
                 rename_data=None, rename_label=None) -> None:
        import threading
        import queue
        if isinstance(iters, DataIter):
            iters = [iters]
        if len(iters) != 1:
            raise MXNetError("PrefetchingIter supports a single iterator "
                             "here; compose datasets upstream instead")
        self.iter = iters[0]
        super().__init__(self.iter.batch_size)
        self.current_batch: Optional[DataBatch] = None
        self._queue = None
        self._thread = None
        self._start_epoch()

    def _start_epoch(self) -> None:
        import threading
        import queue
        self._queue = queue.Queue(maxsize=4)
        self._thread = threading.Thread(target=self._worker,
                                        args=(self._queue,), daemon=True)
        self._thread.start()

    def _worker(self, q) -> None:
        while True:
            try:
                batch = next(self.iter)
            except StopIteration:
                q.put(None)
                break
            q.put(batch)

    def reset(self) -> None:
        """Restart prefetching for a new epoch (joins the old producer)."""
        if self._thread is not None and self._thread.is_alive():
            # drain so the producer can finish, then join
            while self._queue.get() is not None:
                pass
            self._thread.join()
        self.iter.reset()
        self._start_epoch()

    def iter_next(self) -> bool:
        batch = self._queue.get()
        if batch is None:
            return False
        self.current_batch = batch
        return True

    def next(self) -> DataBatch:
        if self.iter_next():
            return self.current_batch
        raise StopIteration
