"""Asynchronous device-prefetch input pipeline — overlap host input
work with device compute.

The MXNet paper's dependency engine exists to hide host latency behind
asynchronous device execution (arXiv:1512.01274 §4); the Julia→TPU
full-compilation work makes the same point from the other side: the hot
loop must stay free of host↔device round-trips.  On this stack the
compiled step already runs asynchronously — what serialized the loop
was the INPUT side: every batch paid host preprocessing plus a
synchronous ``device_put`` between two steps, so the device idled for
exactly that long each step (the single-chip resnet50 plateau,
BENCH_r02–r05).

:class:`DevicePrefetcher` moves that work onto a background thread: while
step N executes, the thread fetches batch N+1, runs host preprocessing,
and commits it to the device (sharded ``jax.device_put`` when attached
to an :class:`~mxnet_tpu.parallel.SPMDTrainer`), queueing up to
``MXNET_PREFETCH_DEPTH`` batches ahead.  The step loop's only per-step
input work is a queue pop of an already-device-resident batch.

Two modes, one class:

* **callable mode** — wrap a ``batch_fn(step[, salt])``; the consumer
  pulls with :meth:`get`.  ``SPMDTrainer.fit`` detects the wrapper and
  drives it directly, composing with checkpoint resume and HealthGuard
  rewind: a non-consecutive ``step`` or a changed ``salt`` invalidates
  every prefetched batch and reseeks the producer (counted in
  ``mxnet_prefetch_invalidated_total``).
* **iterable mode** — wrap a ``DataLoader`` / ``DataIter`` / any
  iterable of ``(data, label)`` batches; each ``iter()`` starts a fresh
  epoch producer.  Drop-in for ``Estimator.fit(train_data=...)`` and
  hand-written gluon loops.

Failure semantics: the ``dataloader.worker`` fault site fires inside
the prefetch thread (per batch), and any producer error — injected or
real — surfaces as a structured :class:`~mxnet_tpu.base.MXNetError` on
the consumer's next pull, never a hang.  A *wedged* producer is a named
stall: the blocking pull is armed on the PR-5 hang watchdog as site
``prefetch.get`` (``MXNET_HEALTH_STEP_DEADLINE_S``), so a stuck loader
dumps all-thread stacks instead of silently stalling the job.

Instrumentation (the overlap is provable, not vibes):
``mxnet_prefetch_queue_depth``, ``mxnet_prefetch_h2d_seconds``,
``mxnet_prefetch_stall_seconds`` (time the step loop waited on input),
``mxnet_prefetch_batches_total``, ``mxnet_prefetch_invalidated_total``.
"""
from __future__ import annotations

import inspect
import queue as _queue
import threading
import time
from typing import Any, Callable, Iterator, Optional

from ..base import MXNetError, getenv, register_env
from .. import metrics as _metrics

__all__ = ["DevicePrefetcher", "default_placement", "takes_salt"]

register_env(
    "MXNET_PREFETCH_DEPTH", 2,
    "Queue depth of the DevicePrefetcher (io/prefetch.py): how many "
    "batches the background thread fetches, preprocesses, and commits "
    "to the device ahead of the training step. 2 (default) double-"
    "buffers: batch N+1 lands while step N executes. Deeper only helps "
    "loaders with high per-batch jitter; every queued batch holds "
    "device memory.")
register_env(
    "MXNET_PREFETCH_DONATE", 1,
    "When 1 (default), SPMDTrainer.fit donates prefetched batch "
    "buffers to the compiled step (XLA reuses the input memory for "
    "outputs). Safe because the prefetcher hands every step a fresh "
    "batch; set 0 if a custom loop re-reads batch arrays after the "
    "step (a donated buffer is deleted by the call). Only applies to "
    "prefetched fit() loops — manual step() calls never donate "
    "inputs.")


def takes_salt(fn: Any) -> bool:
    """Whether ``fn(step, salt=...)`` is accepted — the HealthGuard
    rewind-replay perturbation contract, shared by the prefetched and
    bare-callable ``SPMDTrainer.fit`` paths (``**kwargs``-only
    signatures read as salt-less: the salt must be a named, consumed
    parameter to perturb anything)."""
    try:
        return "salt" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False


def default_placement(batch: Any) -> Any:
    """Commit every array in ``batch`` (nested tuples/lists of NDArray /
    numpy / jax arrays) to the default device with ``jax.device_put``.

    Committed placement matters beyond the transfer itself: jit caches
    key on committed-ness, so an uncommitted batch can force the slow
    uncommitted-argument dispatch path on every consuming call (the
    PR-6 KV-cache lesson).  Consumers with sharding requirements
    (SPMDTrainer) install their own placement via
    :meth:`DevicePrefetcher.attach`."""
    import jax
    from ..ndarray.ndarray import NDArray, from_jax
    from .. import engine as _engine
    dev = jax.devices()[0]

    def place(x: Any) -> Any:
        if isinstance(x, (tuple, list)):
            return type(x)(place(v) for v in x)
        if isinstance(x, NDArray):
            a = jax.device_put(x._data, dev)
            _engine.mark_clean(a)
            x._data = a
            return x
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            a = jax.device_put(x, dev)
            _engine.mark_clean(a)
            return from_jax(a)
        return x

    return place(batch)


def _raise_producer_error(exc: BaseException) -> None:
    """Surface a prefetch-thread failure as a structured error on the
    consumer thread (FaultInjected and other MXNetErrors pass through
    typed — the blast radius is the consuming run, exactly as a
    DataLoader worker error)."""
    if isinstance(exc, MXNetError):
        raise exc
    if isinstance(exc, StopIteration):
        raise exc
    raise MXNetError(
        f"prefetch worker failed: {type(exc).__name__}: {exc} "
        "[mxnet_tpu.io.prefetch]") from exc


class _EpochIterator:
    """One epoch's background producer over ``iter(source)`` (iterable
    mode): fetch + place on the thread, stall-timed pops on the
    consumer."""

    def __init__(self, pf: "DevicePrefetcher") -> None:
        self._pf = pf
        self._q: "_queue.Queue" = _queue.Queue(maxsize=pf.depth)
        self._closed = False
        self._dead: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name="mxnet-prefetch-epoch", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        from .. import faults as _faults
        try:
            it = iter(self._pf._source)
        except BaseException as exc:   # noqa: BLE001 - relay to consumer
            self._dead = exc
            self._put((None, exc))
            return
        while not self._closed:
            try:
                if _faults._ARMED:
                    _faults.maybe_fault("dataloader.worker",
                                        thread="prefetch")
                batch = next(it)
                t0 = time.perf_counter()
                batch = self._pf._placement(batch)
                _metrics.PREFETCH_H2D_SECONDS.observe(
                    time.perf_counter() - t0)
            except StopIteration:
                self._put((None, None))          # clean end of epoch
                return
            except BaseException as exc:   # noqa: BLE001 - relay
                self._dead = exc
                self._put((None, exc))
                return
            self._put((batch, None))
            _metrics.PREFETCH_BATCHES_TOTAL.inc()
            _metrics.PREFETCH_QUEUE_DEPTH.set(self._q.qsize())

    def _put(self, item: Any) -> None:
        while not self._closed:
            try:
                self._q.put(item, timeout=0.1)
                return
            except _queue.Full:
                continue

    def __iter__(self) -> "_EpochIterator":
        return self

    def __next__(self) -> Any:
        from .. import health as _health
        t0 = time.perf_counter()
        with _health.watch_section("prefetch.get"):
            while True:
                if self._dead is not None and self._q.empty():
                    _raise_producer_error(self._dead)
                if self._closed and self._q.empty():
                    # exhausted (or externally closed) epoch: the
                    # producer is gone, nothing more can arrive
                    raise StopIteration
                try:
                    batch, exc = self._q.get(timeout=0.2)
                    break
                except _queue.Empty:
                    continue
        _metrics.PREFETCH_STALL_SECONDS.observe(time.perf_counter() - t0)
        _metrics.PREFETCH_QUEUE_DEPTH.set(self._q.qsize())
        if exc is not None:
            _raise_producer_error(exc)
        if batch is None:
            self.close()
            raise StopIteration
        return batch

    def close(self) -> None:
        self._closed = True
        while True:
            try:
                self._q.get_nowait()
            except _queue.Empty:
                break
        # wait the producer out before the caller tears down the
        # underlying source (a RecordIO loader closed under an
        # in-flight next() is a native use-after-close); a producer
        # wedged inside the source itself is bounded by the timeout
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=2.0)

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:   # noqa: BLE001 - interpreter teardown
            pass


class DevicePrefetcher:
    """Background host-fetch + device-commit of batch N+1 while step N
    executes (module docstring has the full story).

    Parameters
    ----------
    source : callable ``step[, salt] -> (data, labels)`` or an iterable
        of batches.  Callable mode supports :meth:`get` with seek/salt
        invalidation (checkpoint resume, HealthGuard rewind replay);
        iterable mode supports ``iter()`` per epoch.
    depth : queue depth (default ``MXNET_PREFETCH_DEPTH``).
    placement : ``batch -> batch`` moving arrays to the device; default
        commits to the default device.  ``SPMDTrainer.fit`` installs
        its sharded placement via :meth:`attach`.
    donate : whether a prefetched ``fit`` loop may donate batch buffers
        to the compiled step (default ``MXNET_PREFETCH_DONATE``).
    start_step : first step the callable producer fetches (resume can
        also just call ``get(restored_step)`` — the seek is automatic).
    """

    def __init__(self, source: Any, depth: Optional[int] = None,
                 placement: Optional[Callable[[Any], Any]] = None,
                 donate: Optional[bool] = None,
                 start_step: int = 0) -> None:
        self._source = source
        self.is_callable = callable(source)
        self.depth = int(depth if depth is not None
                         else getenv("MXNET_PREFETCH_DEPTH", 2))
        if self.depth < 1:
            raise MXNetError(
                f"prefetch depth must be >= 1, got {self.depth} "
                "(MXNET_PREFETCH_DEPTH)")
        self.donate = (bool(int(getenv("MXNET_PREFETCH_DONATE", 1)))
                       if donate is None else bool(donate))
        self._placement = placement or default_placement
        self.takes_salt = self.is_callable and takes_salt(source)
        # callable-mode producer state (guarded by _lock; the consumer
        # side of _expect/_salt is single-threaded by contract)
        self._lock = threading.Lock()
        self._gen = 0
        self._closed = False
        self._dead: Optional[BaseException] = None
        self._next_step = int(start_step)
        self._salt = 0
        self._expect = int(start_step)
        self._q: "_queue.Queue" = _queue.Queue(maxsize=self.depth)
        self._thread: Optional[threading.Thread] = None

    # -- wiring --------------------------------------------------------------
    def attach(self, trainer: Any) -> "DevicePrefetcher":
        """Bind this prefetcher's placement to a trainer's input
        shardings (``SPMDTrainer.fit`` calls this): batches then arrive
        at the step already committed to their mesh shardings, and
        ``step()``'s own placement short-circuits to a no-op."""
        placer = getattr(trainer, "input_placement", None)
        if placer is not None:
            self._placement = placer()
        return self

    def _ensure_started(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            if self._dead is not None or self._closed:
                return
            self._thread = threading.Thread(
                target=self._run, name="mxnet-prefetch", daemon=True)
            self._thread.start()

    # -- callable-mode producer ----------------------------------------------
    def _run(self) -> None:
        from .. import faults as _faults
        while True:
            with self._lock:
                if self._closed:
                    return
                gen, step, salt = self._gen, self._next_step, self._salt
                self._next_step += 1
            try:
                if _faults._ARMED:
                    _faults.maybe_fault("dataloader.worker", step=step,
                                        thread="prefetch")
                batch = (self._source(step, salt=salt) if self.takes_salt
                         else self._source(step))
                t0 = time.perf_counter()
                batch = self._placement(batch)
                _metrics.PREFETCH_H2D_SECONDS.observe(
                    time.perf_counter() - t0)
            except BaseException as exc:   # noqa: BLE001 - relay
                # the producer dies with the error (DataLoader worker
                # blast radius); _dead wakes a consumer even if the
                # queue item itself is dropped as stale
                self._dead = exc
                self._force_put((gen, step, None, exc))
                return
            if not self._put((gen, step, batch, None)):
                continue            # seek happened mid-fetch: dropped
            _metrics.PREFETCH_BATCHES_TOTAL.inc()
            _metrics.PREFETCH_QUEUE_DEPTH.set(self._q.qsize())

    def _put(self, item: Any) -> bool:
        """Queue ``item`` unless it became stale (gen changed) or the
        pipeline closed; returns whether it was queued."""
        while True:
            with self._lock:
                if self._closed:
                    return False
                if item[0] != self._gen:
                    return False
            try:
                self._q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue

    def _force_put(self, item: Any) -> None:
        """Best-effort wake-up put for terminal errors (staleness does
        not matter: ``_dead`` is authoritative)."""
        try:
            self._q.put_nowait(item)
        except _queue.Full:
            pass

    # -- callable-mode consumer ----------------------------------------------
    def get(self, step: int, salt: int = 0) -> Any:
        """Device-resident batch for ``step`` — the callable-mode pull.

        Consecutive steps stream straight off the queue; a
        non-consecutive ``step`` (resume, rewind) or a changed ``salt``
        (HealthGuard replay perturbation) invalidates every prefetched
        batch and reseeks the producer.  Blocks until the batch is
        ready (the wait is the ``mxnet_prefetch_stall_seconds``
        observation and is watchdog-armed as ``prefetch.get``)."""
        if not self.is_callable:
            raise MXNetError(
                "DevicePrefetcher.get(step) needs a callable batch_fn "
                "source; iterable sources are consumed with iter()")
        if self._closed:
            raise MXNetError(
                "DevicePrefetcher is closed; create a new prefetcher "
                "to keep training [mxnet_tpu.io.prefetch]")
        if self._dead is not None:
            _raise_producer_error(self._dead)
        if step != self._expect or salt != self._salt:
            self._seek(step, salt)
        self._ensure_started()
        from .. import health as _health
        from .. import tracing as _tracing
        t0 = time.perf_counter()
        with _tracing.child_span("prefetch.get", step=step), \
                _health.watch_section("prefetch.get", step=step):
            while True:
                if self._dead is not None and self._q.empty():
                    _raise_producer_error(self._dead)
                if self._closed:
                    raise MXNetError(
                        "DevicePrefetcher closed while a consumer was "
                        "waiting on step "
                        f"{step} [mxnet_tpu.io.prefetch]")
                try:
                    item = self._q.get(timeout=0.2)
                except _queue.Empty:
                    continue
                gen, istep, batch, exc = item
                if exc is not None:
                    _raise_producer_error(exc)
                if gen != self._gen:
                    continue                     # pre-seek leftover
                break
        _metrics.PREFETCH_STALL_SECONDS.observe(time.perf_counter() - t0)
        _metrics.PREFETCH_QUEUE_DEPTH.set(self._q.qsize())
        if istep != step:
            raise MXNetError(
                f"prefetch stream out of order: expected step {step}, "
                f"got {istep} [mxnet_tpu.io.prefetch]")
        self._expect = step + 1
        return batch

    def _seek(self, step: int, salt: int) -> None:
        reason = "salt" if salt != self._salt else "seek"
        with self._lock:
            self._gen += 1
            self._next_step = int(step)
            self._salt = int(salt)
        self._expect = int(step)
        # stale batches are NOT drained here: the producer may enqueue a
        # fresh-generation batch between the gen bump and a drain, and
        # draining it would deadlock the stream one step ahead of the
        # consumer forever.  get() filters stale generations instead
        # (bounded by depth, so the memory overhang is one queue).
        _metrics.PREFETCH_INVALIDATED.labels(reason=reason).inc()

    def _drain(self) -> None:
        while True:
            try:
                self._q.get_nowait()
            except _queue.Empty:
                return

    # -- iterable mode -------------------------------------------------------
    def __iter__(self) -> Iterator[Any]:
        if self.is_callable:
            raise MXNetError(
                "callable-mode DevicePrefetcher is consumed via "
                "get(step) — SPMDTrainer.fit does this automatically; "
                "wrap an iterable to use iter()")
        return _EpochIterator(self)

    # -- shutdown ------------------------------------------------------------
    def close(self) -> None:
        """Stop the producer and drop queued batches (idempotent)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._gen += 1
        self._drain()
        _metrics.PREFETCH_INVALIDATED.labels(reason="close").inc()
        _metrics.PREFETCH_QUEUE_DEPTH.set(0)
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:   # noqa: BLE001 - interpreter teardown
            pass
