"""Symbol attribute scopes.

Reference parity (leezu/mxnet): ``python/mxnet/attribute.py`` —
``AttrScope``: attributes applied to every symbol created inside the
``with`` block (e.g. ``ctx_group`` for manual model parallelism,
``lr_mult``/``wd_mult``).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["AttrScope"]


class _Current(threading.local):
    def __init__(self) -> None:
        self.scope: Optional["AttrScope"] = None


_CURRENT = _Current()


class AttrScope:
    """``with AttrScope(ctx_group='dev1'):`` — every symbol created in the
    block carries these user attributes (stackable; inner wins)."""

    def __init__(self, **attrs: str) -> None:
        for v in attrs.values():
            if not isinstance(v, str):
                raise ValueError("AttrScope values must be strings")
        self._attrs = attrs
        self._effective = dict(attrs)   # attrs + outer scopes while active
        self._old: Optional[AttrScope] = None

    def get(self, user_attrs: Optional[Dict[str, str]]
            ) -> Dict[str, str]:
        merged = dict(self._effective)
        if user_attrs:
            merged.update(user_attrs)
        return merged

    @staticmethod
    def current() -> Optional["AttrScope"]:
        return _CURRENT.scope

    def __enter__(self) -> "AttrScope":
        self._old = _CURRENT.scope
        if self._old is not None:
            # effective attrs for this activation only; self._attrs must
            # stay pristine so the scope object is reusable elsewhere
            merged = dict(self._old._effective)
            merged.update(self._attrs)
            self._effective = merged
        else:
            self._effective = dict(self._attrs)
        _CURRENT.scope = self
        return self

    def __exit__(self, *exc) -> None:
        _CURRENT.scope = self._old
        self._effective = dict(self._attrs)
