"""Async parameter service — ``kvstore='dist_async'``.

Reference parity (leezu/mxnet): ``kvstore_dist.h`` async branch +
``kvstore_dist_server.h`` (``KVStoreDistServer::DataHandleDefault``) over
ps-lite — workers push gradients and pull weights at their own pace; the
server applies the optimizer IMMEDIATELY per push (Hogwild-style, no
worker synchronization), which tolerates slow workers by design.

Design (tpu-first, SURVEY.md 2.3/5.8): ICI collectives have no async
analog, so this is the prescribed "host-driven DCN parameter service" —
plain TCP between host processes (the reference's ZMQ van), weights and
optimizer state live host-side in the server process, device work stays
on each worker. The wire protocol is a length-prefixed binary frame
(json header + raw array bytes) — no pickle, so a malicious peer cannot
execute code in the server; ``set_optimizer`` ships (name, scalar
hyperparams) and the server instantiates from the optimizer registry
(the reference pickled the optimizer object to the server — same
capability, safer encoding).

Roles follow the reference env contract: ``tools/launch.py -s S`` starts
``S`` server processes (``DMLC_ROLE=server``, this module's ``main``)
and points workers at them via ``DMLC_PS_ROOT_URI`` /
``DMLC_PS_ROOT_PORT`` / ``DMLC_NUM_SERVER``. With S > 1, small keys are
assigned whole to servers by stable hash, and arrays at or above
``MXNET_KVSTORE_BIGARRAY_BOUND`` elements are sliced contiguously
across ALL servers (the reference's PSKV ``EncodeDefaultKey`` big-array
slicing, ``kvstore_dist.h``) so one giant embedding table load-balances
instead of landing on one server; each slice still updates atomically
on its server.

Push payloads optionally compress on the wire
(``set_gradient_compression``): 2-bit with per-worker error-feedback
residuals (well-defined under Hogwild — each worker carries its own
deferred mass), blockwise int8, or bf16/fp16. The server decodes before
applying. Servers bind the interface implied by ``DMLC_PS_ROOT_URI``
(loopback under the local launcher) and, when ``MXNET_PS_TOKEN`` is
set, reject frames without the shared token.

Elastic-training contract (the MXNet paper's PS rationale — durable
server state, restartable workers — made real; docs/fault_tolerance.md
"Elastic distributed training"):

* **Durable PS**: with ``MXNET_PS_SNAPSHOT_DIR`` set, each server
  snapshots its key table + server-side optimizer state + push-dedupe
  table through :class:`~mxnet_tpu.checkpoint.CheckpointManager`
  (fsync + SHA-256) every ``MXNET_PS_SNAPSHOT_EVERY`` applied pushes,
  and ``run_server`` restores the newest verified snapshot on start —
  a restarted server comes back with its weights, not empty.
* **Generation token**: every reply frame carries the server's
  ``gen`` (a snapshot-persisted incarnation counter).  Workers detect
  a restart as a generation change, re-``init`` any keys the snapshot
  missed (init is first-wins, so restored keys are untouched),
  re-ship the optimizer config if the snapshot predates it, and their
  per-worker push ``seq`` numbers (persisted in the snapshot) let the
  server drop replayed pushes instead of double-applying them.
* **Liveness**: workers piggyback their rank on every frame and send
  idle-period ``HEARTBEAT`` frames on a dedicated connection; a rank
  whose lease goes stale past ``MXNET_PS_HEARTBEAT_DEADLINE_S`` is
  named **dead** in structured barrier / coordinated-checkpoint
  errors long before the full recv timeout would expire.
* **Coordinated checkpoints**: the ``C`` command is a two-phase
  mark-then-commit rendezvous (:meth:`KVStoreDistAsync.ckpt_mark` /
  :meth:`~KVStoreDistAsync.ckpt_commit`) backing
  :class:`~mxnet_tpu.checkpoint.CoordinatedCheckpointManager` — all
  ranks agree on one checkpoint step before any rank commits it.
"""
from __future__ import annotations

import json
import os
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as onp

from .base import MXNetError, getenv, register_env
from . import faults as _faults
from . import metrics as _metrics
from . import tracing as _tracing
from .retry import retry_call

__all__ = ["PSServer", "KVStoreDistAsync", "run_server"]

register_env(
    "MXNET_PS_TOKEN", "",
    "Shared-secret frame token for dist_async parameter-server RPCs: "
    "when set (the launcher exports one token to every rank), each "
    "frame carries it and servers reject mismatches — a stray client "
    "from another job cannot corrupt the key table. Empty (default) "
    "disables the check for single-job local runs.")

register_env(
    "MXNET_PS_BIND_URI", "",
    "Interface a dist_async parameter server listens on. Empty "
    "(default) picks loopback when DMLC_PS_ROOT_URI names a local "
    "root and 0.0.0.0 for a genuinely remote job; set explicitly to "
    "pin a specific interface on multi-homed hosts.")

register_env(
    "MXNET_PS_FRAME_CAP", 1 << 30,
    "Soft byte cap for one dist_async multi-key push/pull frame: "
    "batched key groups split so no frame approaches the u32 framing "
    "limit. Lower it to bound per-RPC memory on busy servers.")

register_env(
    "MXNET_PS_RECV_TIMEOUT", 300,
    "Per-reply socket timeout (seconds) for dist_async worker RPCs: a "
    "silently dead parameter server surfaces as a structured, "
    "rank-naming error after this long instead of hanging the worker "
    "forever. Generous by default; 0 restores the old infinite wait. "
    "Barrier RPCs automatically widen to MXNET_PS_BARRIER_TIMEOUT.")

register_env(
    "MXNET_PS_SNAPSHOT_DIR", "",
    "Durable parameter-server state: when set, each dist_async server "
    "snapshots its key table + server-side optimizer state + push-"
    "dedupe table into '<dir>/server-<sid>/' through CheckpointManager "
    "(fsync + SHA-256 verified) and restores the newest verified "
    "snapshot on start, so a restarted server resumes with state "
    "instead of empty.  Workers in the same job (same env) detect the "
    "restart via the server generation token and transparently re-init "
    "only the keys the snapshot missed.  Empty (default) keeps the "
    "PR-3 loud-failure behavior: a restarted server raises "
    "'uninitialized key' on the first push.")

register_env(
    "MXNET_PS_SNAPSHOT_EVERY", 200,
    "Applied pushes between automatic parameter-server snapshots when "
    "MXNET_PS_SNAPSHOT_DIR is set (plus one snapshot at startup to "
    "persist the new generation).  Smaller = tighter bound on the "
    "update window a server crash can lose, at more disk traffic.")

register_env(
    "MXNET_PS_HEARTBEAT_INTERVAL_S", 2.0,
    "Period of the dist_async worker heartbeat thread (rank -> every "
    "server, a dedicated connection so a long barrier wait cannot "
    "starve the lease).  Every ordinary frame also refreshes the "
    "lease.  0 disables heartbeats (dead ranks are then only surfaced "
    "by the full recv/barrier timeouts).")

register_env(
    "MXNET_PS_HEARTBEAT_DEADLINE_S", 10.0,
    "Heartbeat lease: a worker rank not heard from (heartbeat or any "
    "frame) for this long is declared DEAD, and blocked barrier / "
    "coordinated-checkpoint waits abandon with a structured error "
    "naming the dead rank(s) instead of waiting out "
    "MXNET_PS_BARRIER_TIMEOUT or the 300 s recv timeout.  0 disables "
    "the early naming.")

register_env(
    "MXNET_LAUNCH_MAX_RESTARTS", 3,
    "Per-process restart budget for tools/launch.py --supervise: a "
    "dead server or worker child is restarted (jittered exponential "
    "backoff, MXNET_LAUNCH_RESTART_BACKOFF_MS) at most this many "
    "times; past it the launcher degrades explicitly — structured "
    "error, whole job terminated — instead of crash-looping.")

register_env(
    "MXNET_LAUNCH_RESTART_BACKOFF_MS", 500,
    "First-restart backoff for tools/launch.py --supervise child "
    "restarts; doubles per restart of the same process (jittered, "
    "shared schedule with MXNET_RETRY_* via retry.backoff_delays).")

register_env(
    "MXNET_PS_PORT_FILE", "",
    "Path prefix for dist_async parameter-server port publication: "
    "server ID s binds its requested port (or an OS-assigned one when "
    "DMLC_PS_ROOT_PORT=0) and atomically writes the chosen port to "
    "'<prefix>.<s>'; workers resolve each server's port from that file "
    "instead of DMLC_PS_ROOT_PORT+s. Eliminates launcher port-range "
    "races (tools/launch.py sets it automatically for local jobs). "
    "Empty (default) keeps the fixed base-port+offset contract.")

PS_RECV_TIMEOUTS = _metrics.counter(
    "mxnet_ps_recv_timeouts_total",
    "dist_async worker RPCs that timed out waiting for a parameter-"
    "server reply (MXNET_PS_RECV_TIMEOUT) and raised a structured "
    "error.")
PS_SNAPSHOTS = _metrics.counter(
    "mxnet_ps_snapshots_total",
    "Durable parameter-server state snapshots written "
    "(MXNET_PS_SNAPSHOT_DIR / MXNET_PS_SNAPSHOT_EVERY).")
PS_RESTORES = _metrics.counter(
    "mxnet_ps_restores_total",
    "Parameter-server starts that restored a verified state snapshot "
    "(a restart came back with weights instead of empty).")
PS_GENERATION = _metrics.gauge(
    "mxnet_ps_server_generation",
    "This parameter-server process's generation token (snapshot-"
    "persisted incarnation counter; workers detect a restart as a "
    "change).")
PS_DEDUPED_PUSHES = _metrics.counter(
    "mxnet_ps_deduped_pushes_total",
    "Replayed worker pushes the server acknowledged but did NOT apply "
    "(per-worker seq already seen — exactly-once across reconnects "
    "and snapshot-restored restarts).")
PS_HEARTBEAT_AGE = _metrics.gauge(
    "mxnet_ps_heartbeat_age_seconds",
    "Seconds since the parameter server last heard from each worker "
    "rank (heartbeat or any frame); refreshed when liveness is "
    "checked.", labels=("rank",))
DIST_DEAD_RANKS = _metrics.gauge(
    "mxnet_dist_dead_ranks",
    "Ranks currently past the heartbeat lease "
    "(MXNET_PS_HEARTBEAT_DEADLINE_S) as seen by this parameter "
    "server, by role.", labels=("role",))
DIST_RANK_RESTARTS = _metrics.counter(
    "mxnet_dist_rank_restarts_total",
    "Dead server/worker processes restarted by the launch supervisor "
    "(tools/launch.py --supervise), by role.", labels=("role",))

# Per-stream cap on the out-of-order push dedupe window (gap seqs kept
# applicable below the high-water mark).  Far above any real in-flight
# window — the wire is serialized per (client, server) — so only
# phantom gaps from a snapshot older than the live stream ever hit it.
_SEQ_GAP_CAP = 512

_MAGIC = b"MXPS"
# Slice-subkey separator for PSKV big-array slicing.  Contains the ASCII
# unit-separator control char so no printable user key can collide with
# the slice-routing rule (a user key named 'w@s1' used to be routed as a
# slice subkey on some paths and by hash on others).
_SLICE_SEP = "\x1fs"


# ---------------------------------------------------------------------------
# framing: MXPS | uint32 body_len | cmd(1) | uint32 hdr_len | hdr json | raw
# ---------------------------------------------------------------------------

def _send_frame(sock: socket.socket, cmd: bytes, header: Dict[str, Any],
                payload: bytes = b"") -> None:
    hdr = json.dumps(header).encode()
    body = cmd + struct.pack("<I", len(hdr)) + hdr + payload
    if len(body) > 0xFFFFFFFF:
        raise MXNetError(
            f"PS frame too large: {len(body)} bytes exceeds the u32 "
            f"framing cap (4 GiB) for key(s) "
            f"{header.get('key', header.get('keys', '?'))!r} — lower "
            "MXNET_KVSTORE_BIGARRAY_BOUND so big arrays slice, or push "
            "fewer keys per call")
    sock.sendall(_MAGIC + struct.pack("<I", len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket):
    magic = _recv_exact(sock, 4)
    if magic != _MAGIC:
        raise MXNetError("bad frame magic (not an mxnet_tpu PS peer)")
    (blen,) = struct.unpack("<I", _recv_exact(sock, 4))
    body = _recv_exact(sock, blen)
    cmd = body[0:1]
    (hlen,) = struct.unpack("<I", body[1:5])
    header = json.loads(body[5:5 + hlen].decode())
    payload = body[5 + hlen:]
    return cmd, header, payload


def _arr_payload(a: onp.ndarray):
    a = onp.ascontiguousarray(a)
    return ({"dtype": str(a.dtype), "shape": list(a.shape)}, a.tobytes())


def _payload_arr(header: Dict[str, Any], payload: bytes) -> onp.ndarray:
    return onp.frombuffer(payload, dtype=header["dtype"]).reshape(
        header["shape"]).copy()


# ---------------------------------------------------------------------------
# optimizer-state codec: a restricted, pickle-free structural encoding for
# shipping Updater.states over the wire (arrays ride the payload; the
# structure is JSON). Covers everything our optimizers produce: nested
# tuples/lists/dicts, numbers, None, arrays, MasterWeightState.
# ---------------------------------------------------------------------------

def _enc_state(s, leaves: List[onp.ndarray]):
    from .optimizer import MasterWeightState
    if s is None:
        return {"t": "none"}
    if isinstance(s, bool):
        return {"t": "bool", "v": s}
    if isinstance(s, (int, float)):
        return {"t": "num", "v": s}
    if isinstance(s, MasterWeightState):
        return {"t": "mws", "m": _enc_state(s.master, leaves),
                "s": _enc_state(s.inner, leaves)}
    if isinstance(s, tuple):
        return {"t": "tup", "v": [_enc_state(x, leaves) for x in s]}
    if isinstance(s, list):
        return {"t": "list", "v": [_enc_state(x, leaves) for x in s]}
    if isinstance(s, dict):
        return {"t": "dict",
                "v": {str(k): _enc_state(x, leaves)
                      for k, x in s.items()}}
    a = onp.asarray(getattr(s, "_data", s))
    leaves.append(onp.ascontiguousarray(a))
    return {"t": "arr", "i": len(leaves) - 1,
            "dtype": str(a.dtype), "shape": list(a.shape)}


def _dec_state(obj, leaves: Sequence[onp.ndarray]):
    from .optimizer import MasterWeightState
    t = obj["t"]
    if t == "none":
        return None
    if t in ("bool", "num"):
        return obj["v"]
    if t == "mws":
        return MasterWeightState(_dec_state(obj["m"], leaves),
                                 _dec_state(obj["s"], leaves))
    if t == "tup":
        return tuple(_dec_state(x, leaves) for x in obj["v"])
    if t == "list":
        return [_dec_state(x, leaves) for x in obj["v"]]
    if t == "dict":
        return {k: _dec_state(x, leaves) for k, x in obj["v"].items()}
    if t == "arr":
        return leaves[obj["i"]]
    raise MXNetError(f"bad state encoding tag {t!r}")


def _pack_leaves(leaves: Sequence[onp.ndarray]):
    specs = [{"dtype": str(a.dtype), "shape": list(a.shape),
              "nbytes": a.nbytes} for a in leaves]
    return specs, b"".join(a.tobytes() for a in leaves)


def _unpack_leaves(specs, payload: bytes) -> List[onp.ndarray]:
    out, off = [], 0
    for sp in specs:
        n = sp["nbytes"]
        out.append(_decode_entry(sp, payload[off:off + n]))
        off += n
    return out


# ---------------------------------------------------------------------------
# wire codecs (host-side analogs of kvstore.py's compressed collectives —
# reference: src/kvstore/gradient_compression.cc). Pure numpy: servers and
# workers never need a device to move gradients.
# ---------------------------------------------------------------------------

_INT8_BLOCK = 256


def _bf16_dtype():
    import ml_dtypes                    # jax dependency, always present
    return onp.dtype(ml_dtypes.bfloat16)


def _q2bit_np(flat: onp.ndarray, thr: float):
    """Quantize to {-thr, 0, +thr} packed 4 codes/byte; returns
    (packed uint8, dequantized f32) — caller keeps acc - deq as the
    error-feedback residual."""
    codes = onp.where(flat >= thr, 2,
                      onp.where(flat <= -thr, 0, 1)).astype(onp.uint8)
    deq = (codes.astype(onp.float32) - 1.0) * thr
    pad = (-len(codes)) % 4
    if pad:
        codes = onp.concatenate([codes, onp.ones(pad, onp.uint8)])
    c = codes.reshape(-1, 4)
    packed = (c[:, 0] | (c[:, 1] << 2) | (c[:, 2] << 4)
              | (c[:, 3] << 6)).astype(onp.uint8)
    return packed, deq


def _unq2bit_np(packed: onp.ndarray, n: int, thr: float) -> onp.ndarray:
    parts = [(packed >> s) & 3 for s in (0, 2, 4, 6)]
    codes = onp.stack(parts, axis=1).reshape(-1)[:n]
    return (codes.astype(onp.float32) - 1.0) * thr


def _qint8_np(flat: onp.ndarray):
    """Blockwise max-abs int8 (EQuARX-style): returns (codes int8,
    scales f32, n)."""
    n = len(flat)
    pad = (-n) % _INT8_BLOCK
    f = flat.astype(onp.float32)
    if pad:
        f = onp.concatenate([f, onp.zeros(pad, onp.float32)])
    b = f.reshape(-1, _INT8_BLOCK)
    scale = (onp.abs(b).max(axis=1) / 127.0).astype(onp.float32)
    safe = onp.where(scale == 0, 1.0, scale)
    codes = onp.clip(onp.rint(b / safe[:, None]), -127, 127) \
        .astype(onp.int8)
    return codes.reshape(-1), scale, n


def _unqint8_np(codes: onp.ndarray, scales: onp.ndarray,
                n: int) -> onp.ndarray:
    vals = codes.reshape(-1, _INT8_BLOCK).astype(onp.float32) \
        * scales[:, None]
    return vals.reshape(-1)[:n]


def _decode_entry(spec: Dict[str, Any], raw: bytes) -> onp.ndarray:
    """Decode one wire entry to a numpy array (inverse of the client's
    ``_encode_entry``); plain entries pass through untouched."""
    codec = spec.get("codec")
    if not codec:
        return onp.frombuffer(raw, dtype=spec["dtype"]) \
            .reshape(spec["shape"]).copy()
    shape, dt = spec["shape"], spec["dtype"]
    if codec in ("fp16", "bf16"):
        src = onp.float16 if codec == "fp16" else _bf16_dtype()
        return onp.frombuffer(raw, dtype=src).astype(dt).reshape(shape)
    if codec == "int8":
        nsc = spec["nblocks"]
        scales = onp.frombuffer(raw[:4 * nsc], dtype=onp.float32)
        codes = onp.frombuffer(raw[4 * nsc:], dtype=onp.int8)
        return _unqint8_np(codes, scales, spec["n"]).astype(dt) \
            .reshape(shape)
    if codec == "2bit":
        packed = onp.frombuffer(raw, dtype=onp.uint8)
        return _unq2bit_np(packed, spec["n"], spec["thr"]).astype(dt) \
            .reshape(shape)
    raise MXNetError(f"unknown wire codec {codec!r}")


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        srv: "PSServer" = self.server.ps          # type: ignore[attr-defined]
        try:
            while True:
                cmd, header, payload = _recv_frame(self.request)
                # the ps.server chaos site fires OUTSIDE the per-request
                # error handling below: kind=crash os._exits the server
                # process (the SIGKILL analog the supervisor + snapshot
                # restore train against), kind=error kills the serve
                # loop itself.  Seedable like serving.worker: hits count
                # per received frame, EXCLUDING heartbeats — their
                # cadence is wall-clock-dependent and would perturb the
                # deterministic schedule (the serving.worker busy-pass
                # gate precedent).
                if _faults._ARMED and cmd != b"T":
                    try:
                        _faults.maybe_fault("ps.server",
                                            cmd=cmd.decode("latin1"))
                    except Exception:
                        # close the LISTENER synchronously before the
                        # (async, up-to-poll_interval-late) shutdown:
                        # a client reconnecting into the dying window
                        # must get ECONNREFUSED now, not a zombie
                        # connection that only surfaces as a 120 s
                        # MXNET_PS_CONNECT_TIMEOUT recv hang
                        try:
                            self.server.socket.close()
                        except OSError:
                            pass
                        threading.Thread(target=self.server.shutdown,
                                         daemon=True).start()
                        return
                import hmac
                if srv.token and not hmac.compare_digest(
                        str(header.pop("tok", "") or ""), srv.token):
                    # shared-secret gate (MXNET_PS_TOKEN from the
                    # launcher): an unauthenticated peer cannot read or
                    # tamper with weights, replace the optimizer, or
                    # stop the server
                    _send_frame(self.request, b"E",
                                {"error": "bad or missing auth token"})
                    return
                srv.note_heard(header.get("wrank"))
                # remote child span: a frame that carries the worker's
                # traceparent parents the server-side handling under
                # the worker's trace id (popped — srv.handle's header
                # contract is unchanged)
                rctx = _tracing.parse_traceparent(
                    header.pop("traceparent", None))
                if cmd == b"S":
                    srv.stop_requested = True
                    srv.snapshot()        # graceful stop is lossless
                    _send_frame(self.request, b"K",
                                {"gen": srv.generation})
                    threading.Thread(target=self.server.shutdown,
                                     daemon=True).start()
                    return
                try:
                    if rctx is not None:
                        with _tracing.attach(rctx), _tracing.span(
                                "ps.handle",
                                cmd=cmd.decode("latin1"),
                                wrank=header.get("wrank")):
                            reply = srv.handle(cmd, header, payload)
                    else:
                        reply = srv.handle(cmd, header, payload)
                except Exception as e:   # report, keep the connection
                    reply = (b"E", {"error": str(e)}, b"")
                rcmd, rhdr, rpayload = reply
                # every reply carries the server's generation token so
                # workers detect a restarted server on their next RPC
                rhdr = dict(rhdr)
                rhdr.setdefault("gen", srv.generation)
                _send_frame(self.request, rcmd, rhdr, rpayload)
        except (ConnectionError, OSError):
            return


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _PSSnapshotIO:
    """``save_checkpoint``/``load_checkpoint`` adapter so a PSServer
    state snapshot rides :class:`~mxnet_tpu.checkpoint.CheckpointManager`
    unchanged (staging + fsync + per-file SHA-256 + verified-fallback
    restore)."""

    def __init__(self, ps: "PSServer") -> None:
        self.ps = ps
        self.loaded: Optional[Dict[str, Any]] = None

    def save_checkpoint(self, prefix: str) -> None:
        import pickle
        with open(prefix + ".psstate", "wb") as f:
            pickle.dump(self.ps._snapshot_payload(), f)

    def load_checkpoint(self, prefix: str) -> None:
        import pickle
        with open(prefix + ".psstate", "rb") as f:
            self.loaded = pickle.load(f)


class PSServer:
    """In-process parameter server state + request handler
    (``KVStoreDistServer`` analog)."""

    def __init__(self, num_workers: int, server_id: int = 0) -> None:
        self.num_workers = num_workers
        self.server_id = int(server_id)
        self.token = os.environ.get("MXNET_PS_TOKEN", "")
        self.store: Dict[str, onp.ndarray] = {}
        self.locks: Dict[str, threading.Lock] = {}
        self.updater = None                      # optimizer.Updater
        self._opt_config: Optional[tuple] = None  # (name, params) as set
        self._global_lock = threading.Lock()
        self._barrier_lock = threading.Lock()
        self._barrier_cv = threading.Condition(self._barrier_lock)
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_ranks: set = set()
        self.pushes = 0
        # -- elastic-training state ----------------------------------------
        self.generation = 1           # incarnation token (snapshot-persisted)
        # push dedupe per "rank:cid" stream: high-water mark of applied
        # seqs + the (small, transient) set of gap seqs below it still
        # outstanding — concurrent client pushes can legitimately land
        # out of order, and a reordered or retried lower seq must apply
        # exactly once, not be mistaken for a replay
        self.last_seq: Dict[str, int] = {}
        self.seq_gaps: Dict[str, set] = {}
        self.gap_evictions = 0
        self._ckpt_committed = -1     # _restore_snapshot may overwrite
        self.stop_requested = False   # set by a deliberate STOP ('S')
        self.last_heard: Dict[int, float] = {}  # rank -> time.monotonic()
        self._snap_lock = threading.Lock()
        self._dirty_pushes = 0
        self._snapshot_mgr = None
        snap_dir = os.environ.get("MXNET_PS_SNAPSHOT_DIR", "")
        if snap_dir:
            from .checkpoint import CheckpointManager
            self._snapshot_mgr = CheckpointManager(
                os.path.join(snap_dir, f"server-{self.server_id}"),
                max_to_keep=2)
            self._restore_snapshot()
        PS_GENERATION.set(self.generation)
        # coordinated-checkpoint rendezvous (cmd 'C'): per-phase
        # {rank: step} tables released barrier-style on the min step
        self._ckpt_cv = threading.Condition()
        self._ckpt_state: Dict[str, Dict[str, Any]] = {
            "mark": {"vals": {}, "gen": 0, "agreed": None, "done": {}},
            "commit": {"vals": {}, "gen": 0, "agreed": None, "done": {}}}

    def _lock_for(self, key: str) -> threading.Lock:
        with self._global_lock:
            if key not in self.locks:
                self.locks[key] = threading.Lock()
            return self.locks[key]

    # -- durable state -----------------------------------------------------
    def _snapshot_payload(self) -> Dict[str, Any]:
        """One consistent host-side copy of everything a restarted
        server needs: key table, optimizer config + states + schedule
        counts, the push-dedupe table, and the generation.  Taken under
        ``_global_lock`` — store values are replaced (never mutated in
        place) by updates, so a shallow dict copy is a consistent cut
        even while Hogwild pushes continue on other keys."""
        leaves: List[onp.ndarray] = []
        with self._global_lock:
            payload: Dict[str, Any] = {
                "format": 1,
                "generation": self.generation,
                "pushes": self.pushes,
                "last_seq": dict(self.last_seq),
                "seq_gaps": {k: sorted(v)
                             for k, v in self.seq_gaps.items()},
                "store": dict(self.store),
                "opt_config": self._opt_config,
                "states": None, "specs": [], "raw": b"", "counts": None,
                "ckpt_committed": self._ckpt_committed,
            }
            if self.updater is not None:
                enc = {str(k): _enc_state(s, leaves)
                       for k, s in self.updater.states.items()}
                o = self.updater.optimizer
                payload.update(
                    states=enc,
                    counts={"num_update": o.num_update,
                            "index_update_count":
                                {str(k): v for k, v
                                 in o._index_update_count.items()}})
        if leaves:
            # the O(model-bytes) flatten happens OUTSIDE the lock:
            # the refs collected above are immutable (updates rebind
            # store values and state leaves, never write in place), so
            # concurrent Hogwild pushes don't block on the encode and
            # the cut stays consistent
            specs, raw = _pack_leaves(leaves)
            payload.update(specs=specs, raw=raw)
        return payload

    def snapshot(self) -> None:
        """Write a durable state snapshot (no-op without
        ``MXNET_PS_SNAPSHOT_DIR``).  Serialized: one snapshot at a
        time; the step label is the applied-push count."""
        if self._snapshot_mgr is None:
            return
        with self._snap_lock:
            io = _PSSnapshotIO(self)
            self._snapshot_mgr.save(io, step=self.pushes)
            with self._global_lock:
                self._dirty_pushes = 0
        PS_SNAPSHOTS.inc()

    def _restore_snapshot(self) -> None:
        """Load the newest verified snapshot (if any) and advance the
        generation past the incarnation that wrote it."""
        io = _PSSnapshotIO(self)
        if self._snapshot_mgr.restore(io) is None or io.loaded is None:
            return                                # fresh start, gen 1
        p = io.loaded
        self.store = dict(p["store"])
        self.last_seq = dict(p.get("last_seq", {}))
        self.seq_gaps = {k: set(v)
                         for k, v in p.get("seq_gaps", {}).items() if v}
        self.pushes = int(p.get("pushes", 0))
        self._ckpt_committed = int(p.get("ckpt_committed", -1))
        cfg = p.get("opt_config")
        if cfg is not None:
            from . import optimizer as opt
            name, params = cfg
            self.updater = opt.get_updater(opt.create(name, **params))
            self._opt_config = (name, dict(params))
            if p.get("states"):
                leaves = _unpack_leaves(p["specs"], p["raw"])
                self.updater.states = {
                    k: _dec_state(obj, leaves)
                    for k, obj in p["states"].items()}
            counts = p.get("counts")
            if counts:
                o = self.updater.optimizer
                o.num_update = counts.get("num_update", 0)
                o._index_update_count.update(
                    counts.get("index_update_count", {}))
        self.generation = int(p.get("generation", 0)) + 1
        PS_RESTORES.inc()

    def _note_push(self) -> None:
        with self._global_lock:
            self.pushes += 1
            self._dirty_pushes += 1
            due = (self._snapshot_mgr is not None
                   and self._dirty_pushes >= int(
                       getenv("MXNET_PS_SNAPSHOT_EVERY", 200)))
        if due:
            self.snapshot()

    @staticmethod
    def _seq_key(header: Dict[str, Any]) -> Optional[str]:
        """Dedupe stream identity: rank + client incarnation id.  The
        cid keeps a RESTARTED worker's fresh seq 1..N from colliding
        with its dead predecessor's snapshot-persisted entries."""
        rank, cid = header.get("wrank"), header.get("cid")
        if rank is None or cid is None:
            return None
        return f"{rank}:{cid}"

    def _seq_is_fresh(self, header: Dict[str, Any]) -> bool:
        """True when this push was not applied before (by this or a
        snapshot-restored previous incarnation); a replay is
        acknowledged, not re-applied.  Sliding-window semantics: fresh
        means above the stream's high-water mark OR one of the gap
        seqs an out-of-order arrival left open below it.  The seq is
        recorded AFTER the update lands (:meth:`_seq_record`) so a
        snapshot can never capture the seq without its update — the
        failure mode then degrades to the pre-dedupe double-apply
        Hogwild tolerates, never to a lost update."""
        key, seq = self._seq_key(header), header.get("seq")
        if key is None or seq is None:
            return True
        seq = int(seq)
        with self._global_lock:
            fresh = seq > self.last_seq.get(key, 0) \
                or seq in self.seq_gaps.get(key, ())
        if not fresh:
            PS_DEDUPED_PUSHES.inc()
        return fresh

    def _seq_record(self, header: Dict[str, Any]) -> None:
        key, seq = self._seq_key(header), header.get("seq")
        if key is None or seq is None:
            return
        seq = int(seq)
        with self._global_lock:
            hw = self.last_seq.get(key, 0)   # seq streams are 1-based
            if seq > hw:
                if seq > hw + 1:
                    # arrivals the stream skipped over: keep them
                    # applicable.  Real gaps are bounded by the
                    # client's concurrently-pushing threads (the wire
                    # is serialized per server) and resolve fast;
                    # PHANTOM gaps — a restored snapshot older than
                    # the live stream leaves seqs the dead incarnation
                    # applied and will never re-send — would persist
                    # forever, so the set is capped: the oldest
                    # entries are evicted as already-applied.
                    gaps = self.seq_gaps.setdefault(key, set())
                    gaps.update(range(hw + 1, seq))
                    if len(gaps) > _SEQ_GAP_CAP:
                        for s in sorted(gaps)[:len(gaps)
                                              - _SEQ_GAP_CAP]:
                            gaps.discard(s)
                            self.gap_evictions += 1
                self.last_seq[key] = seq
            else:
                gaps = self.seq_gaps.get(key)
                if gaps is not None:
                    gaps.discard(seq)
                    if not gaps:
                        del self.seq_gaps[key]

    # -- liveness ----------------------------------------------------------
    def note_heard(self, rank: Any) -> None:
        if rank is None:
            return
        with self._global_lock:
            self.last_heard[int(rank)] = time.monotonic()

    def _dead_ranks(self) -> List[int]:
        """Ranks whose heartbeat lease expired.  A rank never heard
        from at all is NOT dead (it may still be importing jax); the
        lease only starts ticking after first contact."""
        deadline = float(getenv("MXNET_PS_HEARTBEAT_DEADLINE_S", 10.0))
        if deadline <= 0:
            return []
        now = time.monotonic()
        with self._global_lock:
            heard = dict(self.last_heard)
        dead = []
        for r, t in sorted(heard.items()):
            age = now - t
            PS_HEARTBEAT_AGE.labels(rank=str(r)).set(age)
            if age > deadline:
                dead.append(r)
        DIST_DEAD_RANKS.labels(role="worker").set(len(dead))
        return dead

    # -- coordinated checkpoints (cmd 'C') ---------------------------------
    def _ckpt_round(self, phase: str, rank: int, step: int,
                    timeout: float, cround: Any = None) -> int:
        """Barrier-style rendezvous: block until every worker proposed
        a step for this ``phase`` round, then release everyone with the
        agreed step (the min proposed — the cluster-consistent floor).
        A dead rank abandons the round with a structured error naming
        it; so does the timeout.  ``cround`` (the client's per-phase
        round counter) makes the RPC idempotent: a replay whose round
        already completed — the reply was lost on the wire — is
        answered from the recorded result instead of re-proposing into
        the NEXT round, which would strand every healthy rank across
        two rounds that can each never fill."""
        st = self._ckpt_state[phase]
        rank = int(rank)
        with self._ckpt_cv:
            done = st["done"]
            if cround is not None and \
                    done.get(rank, (None, None))[0] == cround:
                return done[rank][1]
            st["vals"][rank] = int(step)
            gen = st["gen"]
            if len(st["vals"]) >= self.num_workers:
                agreed = min(st["vals"].values())
                st["agreed"] = agreed
                st["vals"] = {}
                st["gen"] += 1
                done[rank] = (cround, agreed)
                self._ckpt_cv.notify_all()
                return agreed
            deadline = time.monotonic() + timeout
            while True:
                remaining = deadline - time.monotonic()
                if self._ckpt_cv.wait_for(
                        lambda: st["gen"] != gen,
                        timeout=min(1.0, max(0.0, remaining))):
                    done[rank] = (cround, st["agreed"])
                    return st["agreed"]
                dead = sorted(set(self._dead_ranks())
                              - set(st["vals"]))
                if dead:
                    st["vals"].pop(int(rank), None)
                    raise MXNetError(
                        f"coordinated checkpoint {phase} abandoned: "
                        f"rank(s) {dead} are DEAD (no heartbeat for > "
                        f"{getenv('MXNET_PS_HEARTBEAT_DEADLINE_S', 10.0)}"
                        "s, MXNET_PS_HEARTBEAT_DEADLINE_S) — restart "
                        "them (tools/launch.py --supervise does this "
                        "automatically) and retry")
                if remaining <= 0:
                    st["vals"].pop(int(rank), None)
                    arrived = sorted(st["vals"])
                    missing = sorted(set(range(self.num_workers))
                                     - set(arrived) - {int(rank)})
                    raise MXNetError(
                        f"coordinated checkpoint {phase} timed out "
                        f"after {timeout:.0f}s: ranks {arrived} + "
                        f"{rank} arrived, missing ranks {missing} "
                        "(MXNET_PS_BARRIER_TIMEOUT to raise)")

    def handle(self, cmd: bytes, header: Dict[str, Any], payload: bytes):
        if cmd == b"I":                          # init (first wins)
            key = header["key"]
            with self._lock_for(key):
                if key not in self.store:
                    self.store[key] = _payload_arr(header, payload)
            return b"K", {}, b""
        if cmd == b"P":                          # push
            key = header["key"]
            if not self._seq_is_fresh(header):
                return b"K", {"dup": 1}, b""     # replay: ack, don't apply
            grad = _decode_entry(header, payload)
            with self._lock_for(key):
                if key not in self.store:
                    raise MXNetError(f"push to uninitialized key {key!r}")
                if self.updater is not None:
                    # async mode proper: apply the optimizer NOW, per
                    # worker push — no aggregation window (Hogwild)
                    self._apply_update(key, grad)
                else:
                    # no server-side optimizer: running sum (the pulled
                    # value is the sum of everything pushed since init)
                    self.store[key] = self.store[key] + grad
            self._seq_record(header)
            self._note_push()
            return b"K", {}, b""
        if cmd == b"G":                          # pull
            key = header["key"]
            with self._lock_for(key):
                if key not in self.store:
                    raise MXNetError(f"pull of uninitialized key {key!r}")
                hdr, raw = _arr_payload(self.store[key])
            return b"V", hdr, raw
        if cmd == b"p":                          # multi-key push
            if not self._seq_is_fresh(header):
                return b"K", {"dup": 1}, b""     # replay: ack, don't apply
            keys = header["keys"]
            grads = _unpack_leaves(header["specs"], payload)
            for key, grad in zip(keys, grads):
                with self._lock_for(key):
                    if key not in self.store:
                        raise MXNetError(
                            f"push to uninitialized key {key!r}")
                    if self.updater is not None:
                        self._apply_update(key, grad)
                    else:
                        self.store[key] = self.store[key] + grad
                self._note_push()
            self._seq_record(header)
            return b"K", {}, b""
        if cmd == b"g":                          # multi-key pull
            keys = header["keys"]
            vals = []
            for key in keys:
                with self._lock_for(key):
                    if key not in self.store:
                        raise MXNetError(
                            f"pull of uninitialized key {key!r}")
                    vals.append(self.store[key])
            specs, raw = _pack_leaves(vals)
            return b"v", {"specs": specs}, raw
        if cmd == b"H":                          # update live hyperparams
            with self._global_lock:
                if self.updater is None:
                    raise MXNetError("no optimizer on this server")
                o = self.updater.optimizer
                applied = {}
                for k, v in header.get("params", {}).items():
                    if k == "learning_rate":
                        o.lr = v
                        applied[k] = v
                    elif hasattr(o, k) and isinstance(
                            getattr(o, k), (int, float, bool, type(None))):
                        setattr(o, k, v)
                        applied[k] = v
                # fold into the persisted optimizer config: a snapshot-
                # restored server must come back with the LIVE schedule
                # (lr decay etc.), not the job-start hyperparams
                if self._opt_config is not None and applied:
                    name, params = self._opt_config
                    self._opt_config = (name, dict(params, **applied))
            return b"K", {}, b""
        if cmd == b"X":                          # fetch optimizer states
            with self._global_lock:
                if self.updater is None:
                    return b"v", {"states": None, "specs": []}, b""
                # snapshot under the lock that _apply_update's
                # first-touch insert takes: workers keep pushing during
                # a checkpoint by design, and encoding the live dict
                # races concurrent state creation
                items = list(self.updater.states.items())
                leaves: List[onp.ndarray] = []
                enc = {str(k): _enc_state(s, leaves) for k, s in items}
                specs, raw = _pack_leaves(leaves)
                o = self.updater.optimizer
                counts = {"num_update": o.num_update,
                          "index_update_count":
                              {str(k): v for k, v
                               in o._index_update_count.items()}}
            return b"v", {"states": enc, "specs": specs,
                          "counts": counts}, raw
        if cmd == b"Y":                          # restore optimizer states
            with self._global_lock:
                if self.updater is None:
                    raise MXNetError(
                        "set_optimizer before loading states")
                leaves = _unpack_leaves(header["specs"], payload)
                self.updater.states = {
                    k: _dec_state(obj, leaves)
                    for k, obj in header["states"].items()}
                counts = header.get("counts")
                if counts:
                    o = self.updater.optimizer
                    o.num_update = max(o.num_update,
                                       counts.get("num_update", 0))
                    o._index_update_count.update(
                        counts.get("index_update_count", {}))
            return b"K", {}, b""
        if cmd == b"O":                          # set_optimizer
            from . import optimizer as opt
            with self._global_lock:
                o = opt.create(header["name"], **header.get("params", {}))
                self.updater = opt.get_updater(o)
                self._opt_config = (header["name"],
                                    dict(header.get("params", {})))
            return b"K", {}, b""
        if cmd == b"T":                          # heartbeat (lease refresh
            return b"K", {}, b""                 # recorded in _Handler)
        if cmd == b"C":                          # coordinated checkpoint
            timeout = float(os.environ.get(
                "MXNET_PS_BARRIER_TIMEOUT", "600"))
            phase = header["phase"]
            if phase not in ("mark", "commit"):
                raise MXNetError(f"bad checkpoint phase {phase!r}")
            agreed = self._ckpt_round(phase, int(header.get("rank", 0)),
                                      int(header["step"]), timeout,
                                      cround=header.get("cround"))
            if phase == "commit":
                with self._global_lock:
                    newly = agreed > self._ckpt_committed
                    if newly:
                        self._ckpt_committed = agreed
                if newly:
                    # persist the commit record so a restarted server
                    # still knows the cluster's consistent step
                    self.snapshot()
                return b"K", {"committed": agreed}, b""
            return b"K", {"step": agreed}, b""
        if cmd == b"B":                          # barrier over all workers
            timeout = float(os.environ.get(
                "MXNET_PS_BARRIER_TIMEOUT", "600"))
            rank = header.get("rank")
            with self._barrier_cv:
                gen = self._barrier_gen
                self._barrier_count += 1
                if rank is not None:
                    self._barrier_ranks.add(int(rank))
                # release on DISTINCT ranks when clients send them: a
                # replayed 'B' after a transient connection drop must
                # not double-count one worker and release the barrier
                # early (raw count is the pre-hardening fallback)
                arrived_all = (len(self._barrier_ranks)
                               if self._barrier_ranks
                               else self._barrier_count) \
                    >= self.num_workers
                if arrived_all:
                    self._barrier_count = 0
                    self._barrier_ranks = set()
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                else:
                    deadline = time.monotonic() + timeout
                    while True:
                        remaining = deadline - time.monotonic()
                        if self._barrier_cv.wait_for(
                                lambda: self._barrier_gen != gen,
                                timeout=min(1.0, max(0.0, remaining))):
                            break
                        arrived = sorted(self._barrier_ranks)
                        # heartbeat lease: a DEAD missing rank is named
                        # within MXNET_PS_HEARTBEAT_DEADLINE_S — the
                        # waiters learn who to restart in seconds, not
                        # after the full barrier/recv timeout
                        dead = sorted(set(self._dead_ranks())
                                      - set(arrived))
                        if dead:
                            self._barrier_count -= 1
                            if rank is not None:
                                self._barrier_ranks.discard(int(rank))
                            raise MXNetError(
                                f"barrier abandoned: rank(s) {dead} "
                                "are DEAD (heartbeat lease > "
                                f"{getenv('MXNET_PS_HEARTBEAT_DEADLINE_S', 10.0)}"
                                "s old, MXNET_PS_HEARTBEAT_DEADLINE_S); "
                                f"{len(arrived)}/{self.num_workers} "
                                f"arrived (ranks {arrived}) — restart "
                                "the dead rank(s) (tools/launch.py "
                                "--supervise does this automatically)")
                        if remaining <= 0:
                            self._barrier_count -= 1
                            # name the missing ranks: "who is holding
                            # the job up" is THE question during an
                            # incident
                            if rank is not None:
                                self._barrier_ranks.discard(int(rank))
                            missing = sorted(
                                set(range(self.num_workers))
                                - set(arrived))
                            raise MXNetError(
                                f"barrier timed out after "
                                f"{timeout:.0f}s: {len(arrived)}/"
                                f"{self.num_workers} workers arrived "
                                f"(ranks {arrived}), missing ranks "
                                f"{missing} "
                                "(MXNET_PS_BARRIER_TIMEOUT to raise)")
            return b"K", {}, b""
        if cmd == b"Q":                          # stats (introspection)
            with self._global_lock:
                seqs = dict(self.last_seq)
            return b"K", {"pushes": self.pushes,
                          "keys": sorted(self.store),
                          "has_optimizer": self.updater is not None,
                          "generation": self.generation,
                          "snapshots": self._snapshot_mgr is not None,
                          "push_streams": seqs,
                          "gap_evictions": self.gap_evictions,
                          "ckpt_committed": self._ckpt_committed}, b""
        raise MXNetError(f"unknown PS command {cmd!r}")

    def _apply_update(self, key: str, grad: onp.ndarray) -> None:
        from .ndarray.ndarray import NDArray
        import jax.numpy as jnp
        w = NDArray(jnp.asarray(self.store[key]), _wrap=True)
        g = NDArray(jnp.asarray(grad), _wrap=True)
        if key not in self.updater.states:
            # first touch inserts a dict entry — serialize against the
            # 'X' snapshot (checkpoint concurrent with pushes) without
            # serializing the steady-state Hogwild updates
            with self._global_lock:
                if key not in self.updater.states:
                    self.updater.states[key] = (
                        self.updater.optimizer
                        .create_state_multi_precision(key, w))
        self.updater(key, g, w)                  # mutates w in place
        self.store[key] = onp.asarray(w._data)


def _bind_host() -> str:
    """The interface to listen on: explicit ``MXNET_PS_BIND_URI`` wins;
    otherwise loopback when the root URI says the job is local (the
    launcher default), all interfaces only for a genuinely remote job."""
    host = os.environ.get("MXNET_PS_BIND_URI")
    if host:
        return host
    root = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    return "127.0.0.1" if root in ("127.0.0.1", "localhost") else "0.0.0.0"


def _publish_port(port: int) -> None:
    """Write this server's chosen port to '<MXNET_PS_PORT_FILE>.<sid>'
    (atomic tmp+rename, fsynced) so workers can resolve it without a
    pre-agreed port — the fix for bind-probe races in the launcher."""
    prefix = os.environ.get("MXNET_PS_PORT_FILE", "")
    if not prefix:
        return
    sid = os.environ.get("DMLC_SERVER_ID", "0")
    path = f"{prefix}.{sid}"
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(str(port))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def run_server(port: int, num_workers: int,
               ready_event: Optional[threading.Event] = None) -> None:
    """Serve until a STOP frame arrives (blocking).

    ``port=0`` binds an OS-assigned free port (never collides); the
    chosen port is published via ``MXNET_PS_PORT_FILE`` when set.  A
    fixed port retries briefly on ``EADDRINUSE`` (a just-killed
    predecessor's socket lingering in TIME_WAIT).

    With ``MXNET_PS_SNAPSHOT_DIR`` set, the newest verified state
    snapshot is restored before serving (generation bumps past the
    incarnation that wrote it) and the new generation is persisted
    immediately, so even a crash before the first periodic snapshot
    cannot reuse a generation token."""
    ps = PSServer(num_workers,
                  server_id=int(os.environ.get("DMLC_SERVER_ID", "0")))
    ps.snapshot()                 # durable: persist the new generation
    host = _bind_host()
    if port:
        server = retry_call(
            lambda: _TCPServer((host, port), _Handler),
            site="kvstore.bind", retryable=(OSError,),
            attempts=8, base_ms=100, max_ms=1000)
    else:
        server = _TCPServer((host, 0), _Handler)
    with server:
        server.ps = ps                           # type: ignore[attr-defined]
        _publish_port(server.server_address[1])
        if ready_event is not None:
            ready_event.set()
        try:
            server.serve_forever(poll_interval=0.1)
        except OSError:
            # a dying handler (ps.server chaos kill) closed the
            # listener under the poll loop so reconnects get refused
            # immediately; the death itself is reported below
            pass
    if not ps.stop_requested:
        # the serve loop died WITHOUT a deliberate STOP ('S') — an
        # internal error or the ps.server chaos site.  Exit nonzero so
        # a supervisor can tell this death from rank 0's graceful
        # stop_servers by rc alone; SystemExit stays silent in the
        # in-thread test harness but gives a server PROCESS rc=1 plus
        # this line on stderr.
        raise SystemExit(
            f"parameter server {ps.server_id}: serve loop ended "
            "without a STOP — treating as a death")


# ---------------------------------------------------------------------------
# worker-side client
# ---------------------------------------------------------------------------

class KVStoreDistAsync:
    """Worker-side ``kvstore='dist_async'`` client.

    API-compatible subset of KVStore: init/push/pull/pushpull,
    set_optimizer (ships to the servers), set_gradient_compression
    (push-payload wire codecs), barrier, rank/num_workers. Small keys go
    whole to ``hash(key) % num_servers``; arrays at/over
    ``MXNET_KVSTORE_BIGARRAY_BOUND`` slice contiguously across ALL
    servers (reference PSKV big-array slicing).
    """

    type = "dist_async"

    def __init__(self) -> None:
        self.uri = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        self.port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9876"))
        self.num_servers = int(os.environ.get("DMLC_NUM_SERVER", "1"))
        self._rank = int(os.environ.get("DMLC_WORKER_ID",
                                        os.environ.get("JAX_PROCESS_ID",
                                                       "0")))
        self._num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        self._token = os.environ.get("MXNET_PS_TOKEN", "")
        self._socks: List[Optional[socket.socket]] = \
            [None] * self.num_servers
        # one lock per server connection: requests to different servers
        # may overlap; frames on one socket are serialized
        self._locks = [threading.Lock() for _ in range(self.num_servers)]
        self._shipped_params: Dict[str, Any] = {}
        self._compression: Dict[str, Any] = {}
        self._residuals: Dict[str, onp.ndarray] = {}   # per-wire-key EF
        self._shapes: Dict[str, tuple] = {}            # sliced-key shapes
        # payload bytes this worker pushed (post-compression) — the
        # wire-traffic introspection the tests assert against
        self.push_wire_bytes = 0
        # -- elastic-training state ----------------------------------------
        # restart recovery only engages when the job runs a durable PS
        # (same env on workers and servers via the launcher); without
        # it a restarted server keeps the PR-3 loud-failure contract
        self._durable = bool(os.environ.get("MXNET_PS_SNAPSHOT_DIR", ""))
        self._server_gen: List[Optional[int]] = [None] * self.num_servers
        self._gen_lock = threading.Lock()
        self._inits: Dict[str, tuple] = {}   # wire_key->(sidx, hdr, raw)
        self._shipped_opt: Optional[tuple] = None      # (name, params)
        # push-dedupe identity: one seq stream per (CLIENT INCARNATION,
        # server).  Per incarnation because a restarted worker's fresh
        # seq 1..N must not collide with its dead predecessor's entries
        # in the server's snapshot-persisted table; per SERVER so each
        # server sees a dense stream (a shared counter would leave
        # permanent gaps for seqs routed elsewhere and grow the
        # server's reorder window without bound)
        self._client_id = os.urandom(8).hex()
        self._seqs = [0] * self.num_servers
        self._seq_lock = threading.Lock()
        # per-phase coordinated-checkpoint round counters: ride every
        # 'C' frame so a replayed RPC is answered idempotently (cid-
        # prefixed — a restarted worker's counter restarts from 1)
        self._ckpt_rounds = {"mark": 0, "commit": 0}
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        self._hb_lock = threading.Lock()
        # snapshot-gap recovery claim: thread ident of the one thread
        # recovering each server (None = idle) + the Event concurrent
        # RPC threads wait on before their one warranted replay
        self._recovering: List[Optional[int]] = [None] * self.num_servers
        self._recover_done: List[Optional[threading.Event]] = \
            [None] * self.num_servers

    # -- plumbing ----------------------------------------------------------
    @staticmethod
    def _recv_timeout() -> float:
        return float(getenv("MXNET_PS_RECV_TIMEOUT", 300))

    def _server_port(self, sidx: int, wait: bool = False) -> int:
        """The port server ``sidx`` listens on: the published port file
        entry when ``MXNET_PS_PORT_FILE`` is set (``wait=True`` rides
        out a slow server start), else ``DMLC_PS_ROOT_PORT + sidx``.
        Deliberately NOT cached: a restarted server republishes a NEW
        OS-assigned port, and a reconnect must pick it up — resolution
        only happens at (re)connect time, never per RPC."""
        prefix = os.environ.get("MXNET_PS_PORT_FILE", "")
        if not prefix:
            return self.port + sidx
        path = f"{prefix}.{sidx}"
        deadline = time.monotonic() + (float(
            os.environ.get("MXNET_PS_CONNECT_TIMEOUT", "120"))
            if wait else 0.0)
        while True:
            try:
                with open(path) as f:
                    return int(f.read().strip())
            except (OSError, ValueError):
                if time.monotonic() >= deadline:
                    raise MXNetError(
                        f"rank {self._rank}: parameter server "
                        f"{sidx} never published its port to "
                        f"{path} (MXNET_PS_PORT_FILE) — is the "
                        "server process up?") from None
                time.sleep(0.05)

    def _drop_sock(self, sidx: int) -> None:
        if self._socks[sidx] is not None:
            try:
                self._socks[sidx].close()
            except OSError:
                pass
            self._socks[sidx] = None

    def _sock(self, sidx: int) -> socket.socket:
        s = self._socks[sidx]
        if s is None:
            # the server process imports the framework (jax) before it
            # listens — allow for a slow cold start on a loaded machine,
            # with jittered exponential backoff so a worker fleet does
            # not hammer a restarting server in lockstep
            connect_s = float(
                os.environ.get("MXNET_PS_CONNECT_TIMEOUT", "120"))
            port = self._server_port(sidx, wait=True)

            def _connect():
                # re-resolve INSIDE the retry: a restarting server may
                # republish a new port between attempts
                return socket.create_connection(
                    (self.uri, self._server_port(sidx)), timeout=30)

            try:
                s = retry_call(
                    _connect,
                    site="kvstore.connect", retryable=(OSError,),
                    attempts=1_000_000, base_ms=100, max_ms=2000,
                    deadline_s=connect_s)
            except OSError as e:                 # budget spent
                raise MXNetError(
                    f"rank {self._rank}: cannot reach parameter server "
                    f"at {self.uri}:{port} after "
                    f"{connect_s:.0f}s (MXNET_PS_CONNECT_TIMEOUT): {e}")
            # bounded per-reply wait (MXNET_PS_RECV_TIMEOUT): a silently
            # dead server surfaces as a structured timeout error instead
            # of wedging the worker forever.  Barrier RPCs widen the
            # window per-exchange in _rpc_server.
            s.settimeout(self._recv_timeout() or None)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks[sidx] = s
        return s

    # -- elastic-training plumbing -----------------------------------------
    def _next_seq(self, sidx: int) -> int:
        with self._seq_lock:
            self._seqs[sidx] += 1
            return self._seqs[sidx]

    def _remember_init(self, wire_key: str, sidx: int,
                       hdr: Dict[str, Any], raw: bytes) -> None:
        """Keep the init value so a restarted durable server's snapshot
        gap can be re-seeded (init is first-wins: keys the snapshot
        restored are untouched by a re-init).  Durable mode therefore
        costs one host-side copy of the INIT values per worker for the
        life of the client — the price of recovering a server that
        died between a key's init and its first covering snapshot
        (documented in docs/fault_tolerance.md)."""
        if not self._durable:
            return
        with self._gen_lock:
            self._inits[wire_key] = (sidx, dict(hdr), raw)

    def _note_generation(self, sidx: int, gen: Any,
                         failed: bool = False) -> bool:
        """Record the server's generation token from a reply; on a
        change (the server restarted) run snapshot-gap recovery.
        Returns True when recovery ran — the caller's cue that one
        replay of its failed RPC on the recovered state is warranted.
        A FAILED recovery (e.g. the server died again mid-re-init)
        rolls the recorded generation back, so the next reply
        re-detects the change and retries — latching the new token up
        front would silently disable recovery for that incarnation
        forever.

        Concurrency: exactly one thread claims the recovery (atomically
        with the token latch, under ``_gen_lock``); a concurrently-
        pushing peer whose RPC FAILED (``failed=True``) against the
        restarted server waits out that recovery instead of surfacing
        a spurious 'uninitialized key' error that recovery was about
        to cure — peers whose RPC succeeded don't wait at all.  The
        claimant's OWN inner RPCs (Q/O/I inside
        :meth:`_recover_server`) see ``owner == me`` and fall through
        — waiting there would deadlock on ourselves."""
        if gen is None:
            return False
        me = threading.get_ident()
        with self._gen_lock:
            old = self._server_gen[sidx]
            self._server_gen[sidx] = gen
            claimed = (self._durable and old is not None and old != gen
                       and self._recovering[sidx] is None)
            if claimed:
                self._recovering[sidx] = me
                self._recover_done[sidx] = threading.Event()
            owner = self._recovering[sidx]
            done = self._recover_done[sidx]
        if not self._durable or old is None:
            return False
        if claimed:
            try:
                self._recover_server(sidx, old, gen)
            except BaseException:
                with self._gen_lock:
                    if self._server_gen[sidx] == gen:
                        self._server_gen[sidx] = old
                raise
            finally:
                with self._gen_lock:
                    self._recovering[sidx] = None
                done.set()
            return True
        if not failed or owner is None or owner == me:
            return False
        done.wait(timeout=self._recv_timeout() or 60)
        return True

    def _recover_server(self, sidx: int, old: Any, gen: Any) -> None:
        """A durable server restarted: its snapshot restored most
        state; re-seed exactly what the snapshot can miss — the
        optimizer config if it predates set_optimizer, and any keys
        initialized after the last snapshot (first-wins init makes
        this idempotent)."""
        import logging
        logging.getLogger("mxnet_tpu.kvstore_async").warning(
            "parameter server %d restarted (generation %s -> %s): "
            "re-seeding keys/optimizer missing from its snapshot",
            sidx, old, gen)
        _, stats, _ = self._rpc_server(sidx, b"Q", {})
        if not stats.get("has_optimizer") and self._shipped_opt:
            name, params = self._shipped_opt
            self._rpc_server(sidx, b"O",
                             {"name": name, "params": dict(params)})
        with self._gen_lock:
            items = [(wk, hdr, raw)
                     for wk, (si, hdr, raw) in self._inits.items()
                     if si == sidx]
        for wk, hdr, raw in items:
            self._rpc_server(sidx, b"I", dict(hdr), raw)

    def _ensure_heartbeat(self) -> None:
        """Start the per-worker heartbeat thread on first RPC: a
        dedicated connection per server (a minutes-long barrier
        exchange on the main socket must not starve the lease).
        ``_hb_lock`` serializes the check-then-spawn — two pusher
        threads making their first RPCs concurrently must not each
        start a beat loop."""
        interval = float(getenv("MXNET_PS_HEARTBEAT_INTERVAL_S", 2.0))
        if interval <= 0:
            return
        with self._hb_lock:
            if self._hb_thread is not None or self._hb_stop.is_set():
                return
            t = threading.Thread(target=self._hb_loop,
                                 args=(interval, self._hb_stop),
                                 name=f"mxps-heartbeat-r{self._rank}",
                                 daemon=True)
            self._hb_thread = t
            t.start()

    def _hb_loop(self, interval: float, stop: threading.Event) -> None:
        # the stop Event is CAPTURED, not re-read from self: if
        # restart_heartbeat's bounded join expires while this loop is
        # blocked in a connect/recv and then swaps self._hb_stop, the
        # old loop must still see its own (set) event and exit on the
        # next tick instead of beating forever beside its replacement
        socks: List[Optional[socket.socket]] = [None] * self.num_servers
        while not stop.wait(interval):
            for sidx in range(self.num_servers):
                try:
                    # worker.heartbeat chaos site: an injected error
                    # SUPPRESSES this beat (the wedged-not-dead
                    # simulation the dead-rank lease trains against)
                    _faults.maybe_fault("worker.heartbeat",
                                        rank=self._rank, server=sidx)
                except MXNetError:
                    continue
                except OSError:          # kind=timeout: also suppress
                    continue
                try:
                    s = socks[sidx]
                    if s is None:
                        s = socket.create_connection(
                            (self.uri, self._server_port(sidx)),
                            timeout=5)
                        s.settimeout(5)
                        socks[sidx] = s
                    hdr: Dict[str, Any] = {"wrank": self._rank}
                    if self._token:
                        hdr["tok"] = self._token
                    _send_frame(s, b"T", hdr)
                    _recv_frame(s)
                    # deliberately NOT noting the reply's generation:
                    # recovery from this thread would block on the main
                    # RPC locks (up to a full recv timeout) and starve
                    # the beats to every OTHER server — expiring the
                    # very lease this thread exists to keep fresh.  A
                    # restart is recovered on the next real RPC, which
                    # is also the first moment recovery matters.
                except (OSError, MXNetError, ValueError):
                    # dead/restarting server: drop and re-dial next tick
                    if socks[sidx] is not None:
                        try:
                            socks[sidx].close()
                        except OSError:
                            pass
                        socks[sidx] = None
        for s in socks:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass

    def stop_heartbeat(self) -> None:
        with self._hb_lock:
            self._hb_stop.set()

    def restart_heartbeat(self) -> None:
        """Inverse of :meth:`stop_heartbeat`, for in-process reuse
        (tools/tests that stop the servers, restart them, and keep the
        client): joins the old beat thread BEFORE re-arming, so the
        next RPC's :meth:`_ensure_heartbeat` can never race a stale
        loop into two beat threads.  Even if the bounded join expires
        (old loop blocked in a 5s connect/recv), the old loop holds a
        reference to the OLD — set — stop event and exits on its next
        tick."""
        self.stop_heartbeat()
        t = self._hb_thread
        if t is not None and t.is_alive():
            t.join(timeout=10)
        with self._hb_lock:
            self._hb_stop = threading.Event()
            self._hb_thread = None

    def _server_of(self, key: Any) -> int:
        import zlib
        return zlib.crc32(str(key).encode()) % self.num_servers

    def _server_of_wire(self, wk: str) -> int:
        """Server of a WIRE key: slice subkeys (``base<US>sJ``) route by
        the slicing rule, plain keys by hash.  The separator contains
        the ASCII unit-separator control char, which cannot appear in a
        user key name, so a user key like ``'w@s1'`` can never be
        mistaken for a slice subkey (it routes by plain hash on every
        path — init/push/pull/load_optimizer_states agree)."""
        if _SLICE_SEP in wk:
            base_key, _, j = wk.rpartition(_SLICE_SEP)
            if j.isdigit():
                return (self._server_of(base_key) + int(j)) \
                    % self.num_servers
        return self._server_of(wk)

    def _plan(self, key: Any, size: int):
        """Wire layout of one logical key: ``[(wire_key, server, start,
        stop)]`` over the flattened array, or None for a whole-key
        assignment. Arrays at/over ``MXNET_KVSTORE_BIGARRAY_BOUND``
        elements slice contiguously across ALL servers (reference PSKV
        ``EncodeDefaultKey``). The rule is a pure function of (key, size,
        num_servers), so every worker computes the identical layout with
        no metadata exchange — keep the bound env identical across the
        job."""
        bound = int(os.environ.get("MXNET_KVSTORE_BIGARRAY_BOUND",
                                   "1000000"))
        n = self.num_servers
        if n <= 1 or size < bound:
            return None
        base = self._server_of(key)
        cuts = [size * j // n for j in range(n + 1)]
        return [(f"{key}{_SLICE_SEP}{j}", (base + j) % n,
                 cuts[j], cuts[j + 1])
                for j in range(n) if cuts[j + 1] > cuts[j]]

    def _encode_entry(self, wire_key: str, a: onp.ndarray):
        """(spec, payload) for one pushed array, applying the configured
        wire codec; 2-bit error-feedback residuals live per worker per
        wire key."""
        a = onp.ascontiguousarray(a)
        ctype = self._compression.get("type")
        spec: Dict[str, Any] = {"dtype": str(a.dtype),
                                "shape": list(a.shape)}
        if not ctype:
            raw = a.tobytes()
        elif ctype in ("fp16", "bf16"):
            dt = onp.float16 if ctype == "fp16" else _bf16_dtype()
            raw = a.astype(dt).tobytes()
            spec["codec"] = ctype
        elif ctype == "int8":
            codes, scales, n = _qint8_np(a.ravel())
            raw = scales.tobytes() + codes.tobytes()
            spec.update(codec="int8", n=n, nblocks=len(scales))
        else:                                    # 2bit + error feedback
            thr = float(self._compression.get("threshold", 0.5))
            flat = a.ravel().astype(onp.float32)
            res = self._residuals.get(wire_key)
            acc = flat if res is None or len(res) != len(flat) \
                else flat + res
            packed, deq = _q2bit_np(acc, thr)
            self._residuals[wire_key] = acc - deq
            raw = packed.tobytes()
            spec.update(codec="2bit", n=int(flat.size), thr=thr)
        spec["nbytes"] = len(raw)
        self.push_wire_bytes += len(raw)
        from .kvstore import KV_COMPRESSED_BYTES, KV_RAW_BYTES
        KV_RAW_BYTES.labels(ctype=ctype or "none").inc(a.nbytes)
        KV_COMPRESSED_BYTES.labels(ctype=ctype or "none").inc(len(raw))
        return spec, raw

    def _rpc_server(self, sidx: int, cmd: bytes, header: Dict[str, Any],
                    payload: bytes = b""):
        self._ensure_heartbeat()
        # every frame carries the rank: it refreshes this worker's
        # heartbeat lease on the server and lets push frames dedupe
        header = dict(header)
        header.setdefault("wrank", self._rank)
        if self._token:
            header["tok"] = self._token
        # cross-wire trace propagation: the active span's W3C
        # traceparent rides the frame header, so the PS-side handling
        # shows up as a remote child span in this worker's trace
        tp = _tracing.traceparent()
        if tp is not None:
            header["traceparent"] = tp
        cmd_name = cmd.decode("latin1")

        def _exchange():
            with self._locks[sidx]:
                s = self._sock(sidx)
                widened = False
                if cmd in (b"B", b"C"):
                    # a barrier / checkpoint-rendezvous reply
                    # legitimately takes up to the server-side barrier
                    # timeout — widen this exchange's recv window past
                    # it
                    rt = self._recv_timeout()
                    if rt:
                        widened = True
                        s.settimeout(float(os.environ.get(
                            "MXNET_PS_BARRIER_TIMEOUT", "600")) + rt)
                try:
                    _faults.maybe_fault("kvstore.send", cmd=cmd_name,
                                        server=sidx, rank=self._rank)
                    _send_frame(s, cmd, header, payload)
                    _faults.maybe_fault("kvstore.recv", cmd=cmd_name,
                                        server=sidx, rank=self._rank)
                    return _recv_frame(s)
                except socket.timeout as e:
                    # dead-or-wedged server: ONE bounded wait, then a
                    # structured rank-naming error — never retried (a
                    # replay would silently double the hang) and never
                    # an infinite recv
                    self._drop_sock(sidx)
                    PS_RECV_TIMEOUTS.inc()
                    raise MXNetError(
                        f"rank {self._rank}/{self._num_workers}: "
                        f"parameter-server RPC {cmd_name!r} to "
                        f"{self.uri}:{self._server_port(sidx)} timed "
                        f"out after {self._recv_timeout():.0f}s "
                        "(MXNET_PS_RECV_TIMEOUT) — the server is dead "
                        "or wedged; restart it (workers reconnect with "
                        "backoff) or raise the timeout") from e
                except (ConnectionError, OSError):
                    # a half-done exchange leaves the stream desynced —
                    # drop the socket so the next attempt reconnects
                    self._drop_sock(sidx)
                    raise
                except BaseException:
                    # same desync risk for ANY mid-exchange raise (an
                    # injected kind=error fault after the send, a
                    # KeyboardInterrupt between frames): the server's
                    # reply would be read as the NEXT call's reply —
                    # drop so the next RPC starts on a clean stream
                    self._drop_sock(sidx)
                    raise
                finally:
                    if widened and self._socks[sidx] is not None:
                        self._socks[sidx].settimeout(
                            self._recv_timeout() or None)

        # bounded replay with jittered backoff: a restarted server
        # accepts fresh connections; if it lost its state the retry
        # fails loudly ('uninitialized key') instead of the worker dying
        # on a transient drop. A replayed push carries its seq, so a
        # server that already applied it (or restored a snapshot
        # covering it) acks without re-applying; a push the dead server
        # applied AFTER its last snapshot may still apply twice —
        # tolerated by Hogwild semantics.  STOP frames never retry (a
        # dead server is already stopped).  Three attempts, not two: a
        # dying serve loop resets peers for up to its ~100ms shutdown
        # poll, and the first backoff sleep (~25-50ms) can land the
        # replay back inside that window — the third attempt outlasts
        # it into either a served frame or the connect-retry path.
        rcmd, rhdr, rpayload = retry_call(
            _exchange, site="kvstore.rpc",
            retryable=(ConnectionError, OSError),
            attempts=1 if cmd == b"S" else 3)
        if self._note_generation(sidx, rhdr.get("gen"),
                                 failed=rcmd == b"E") and rcmd == b"E":
            # the reply came from a RESTARTED durable server and the
            # RPC failed — recovery just re-initialized the keys its
            # snapshot missed, so one replay on the recovered state is
            # warranted (e.g. 'uninitialized key' for a key created
            # after the last snapshot)
            rcmd, rhdr, rpayload = retry_call(
                _exchange, site="kvstore.rpc",
                retryable=(ConnectionError, OSError), attempts=2)
            self._note_generation(sidx, rhdr.get("gen"))
        if rcmd == b"E":
            raise MXNetError(f"parameter server: {rhdr.get('error')}")
        return rcmd, rhdr, rpayload

    def _rpc(self, key: Any, cmd: bytes, header: Dict[str, Any],
             payload: bytes = b""):
        return self._rpc_server(self._server_of(key), cmd, header, payload)

    @staticmethod
    def _to_numpy(v) -> onp.ndarray:
        if isinstance(v, (list, tuple)):          # per-device list: local sum
            acc = onp.asarray(v[0].asnumpy(), onp.float32)
            for x in v[1:]:
                acc = acc + onp.asarray(x.asnumpy(), onp.float32)
            return acc
        return onp.asarray(v.asnumpy())

    # -- KVStore API -------------------------------------------------------
    def init(self, key, value) -> None:
        keys, vals = self._pair(key, value)
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):
                v = v[0]
            a = onp.asarray(v.asnumpy())
            parts = self._plan(k, int(a.size))
            if parts is None:
                hdr, raw = _arr_payload(a)
                hdr["key"] = str(k)
                self._rpc(k, b"I", hdr, raw)
                self._remember_init(str(k), self._server_of(k), hdr, raw)
                continue
            self._shapes[str(k)] = tuple(a.shape)
            flat = onp.ascontiguousarray(a).ravel()
            for wk, sidx, st, sp in parts:
                hdr, raw = _arr_payload(flat[st:sp])
                hdr["key"] = wk
                self._rpc_server(sidx, b"I", hdr, raw)
                self._remember_init(wk, sidx, hdr, raw)

    def push(self, key, value, priority: int = 0,
             _reserved_seqs: Optional[Dict[int, int]] = None) -> None:
        """Push gradient(s) to the parameter service.

        ``priority`` (int, or a per-key list on batched pushes; higher
        first) orders the per-server frame layout so the
        highest-priority keys land in the earliest frames when a big
        push chunks at ``MXNET_PS_FRAME_CAP`` — the scheduler
        (kvstore_sched.py) additionally orders whole buckets by it.
        ``_reserved_seqs`` carries seqs pre-drawn at enqueue time by
        :meth:`reserve_push_seqs` (one per server), so a push pipelined
        onto the comm thread replays with the seq it was ENQUEUED
        with — exactly-once no matter how dispatch reorders or retries
        the sends."""
        from . import health as _health
        with _health.watch_section("kvstore.push", rank=self._rank):
            self._push_impl(key, value, priority, _reserved_seqs)

    def reserve_push_seqs(self, keys, sizes) -> Dict[int, int]:
        """Pre-draw one push seq per server the given key set will
        touch (``sizes`` = element counts, for the big-array slicing
        rule).  Called at ENQUEUE time by the gradient-reduction
        scheduler: the dedupe identity of a scheduled bucket is fixed
        before the comm thread ever runs it, so a bucket retried after
        a reconnect — or one whose sends the schedule reordered — is
        acknowledged, never double-applied (the PR-8 (cid,seq)
        exactly-once contract).  Chunk-overflow frames past the first
        per server draw fresh seqs at send time; the server's
        out-of-order window absorbs the gap either way."""
        sidxs = set()
        for k, n in zip(keys, sizes):
            parts = self._plan(k, int(n))
            if parts is None:
                sidxs.add(self._server_of(k))
            else:
                sidxs.update(s for _, s, _, _ in parts)
        return {s: self._next_seq(s) for s in sorted(sidxs)}

    def _push_impl(self, key, value, priority: Any = 0,
                   reserved_seqs: Optional[Dict[int, int]] = None) -> None:
        keys, vals = self._pair(key, value)
        prios = self._norm_priorities(keys, priority)
        entries = []            # (wire_key, server, flat array, prio)
        for k, v, p in zip(keys, vals, prios):
            a = self._to_numpy(v)
            parts = self._plan(k, int(a.size))
            if parts is None:
                entries.append((str(k), self._server_of(k), a, p))
            else:
                flat = onp.ascontiguousarray(a).ravel()
                for wk, sidx, st, sp in parts:
                    entries.append((wk, sidx, flat[st:sp], p))
        # group by server: a multi-key push crosses the wire as one
        # frame per server (the ICI path's bucketing analog), chunked so
        # no frame approaches the u32 framing cap.  Within a server the
        # highest-priority keys go first, so when the cap splits the
        # group the urgent keys ride the first frame (stable sort:
        # equal priorities keep key order).
        by_server: Dict[int, List[Any]] = {}
        for wk, sidx, a, p in entries:
            by_server.setdefault(sidx, []).append((wk, a, p))
        cap = int(os.environ.get("MXNET_PS_FRAME_CAP", str(1 << 30)))
        reserved = dict(reserved_seqs or {})
        for sidx, items in by_server.items():
            items.sort(key=lambda e: -e[2])
            enc = [(wk,) + self._encode_entry(wk, a)
                   for wk, a, _ in items]
            group: List[Any] = []
            size = 0
            for e in enc:
                if group and size + len(e[2]) > cap:
                    self._push_group(sidx, group,
                                     seq=reserved.pop(sidx, None))
                    group, size = [], 0
                group.append(e)
                size += len(e[2])
            if group:
                self._push_group(sidx, group,
                                 seq=reserved.pop(sidx, None))

    def _push_group(self, sidx: int, enc,
                    seq: Optional[int] = None) -> None:
        # each push frame carries a per-worker seq: a replay (RPC retry
        # across a reconnect or a snapshot-restored server restart) is
        # acknowledged but never double-applied.  ``seq`` is the
        # enqueue-time reservation when the scheduler pipelined this
        # push; frames without one draw at send time.
        if seq is None:
            seq = self._next_seq(sidx)
        if len(enc) == 1:
            wk, spec, raw = enc[0]
            self._rpc_server(sidx, b"P",
                             dict(spec, key=wk, seq=seq,
                                  cid=self._client_id),
                             raw)
            return
        self._rpc_server(sidx, b"p",
                         {"keys": [e[0] for e in enc],
                          "specs": [e[1] for e in enc],
                          "seq": seq,
                          "cid": self._client_id},
                         b"".join(e[2] for e in enc))

    def pull(self, key, out=None, priority: int = 0,
             ignore_sparse: bool = True):
        from . import health as _health
        with _health.watch_section("kvstore.pull", rank=self._rank):
            return self._pull_impl(key, out)

    def _pull_impl(self, key, out=None):
        from .ndarray.ops import array
        keys, outs = self._pair(key, out)
        # resolve each logical key's wire layout: sliced keys expand to
        # per-server parts reassembled below. The slicing decision needs
        # the array size — known from ``out`` or a local init; a key
        # never seen locally pulls whole (correct unless sliced, in
        # which case the server's 'uninitialized key' error names it).
        requests = []                    # (server, wire_key, li, start)
        shapes: List[Optional[tuple]] = [None] * len(keys)
        for li, (k, o) in enumerate(zip(keys, outs)):
            t = None
            if o is not None:
                t = o[0] if isinstance(o, (list, tuple)) else o
            if t is not None:
                shape, size = tuple(t.shape), int(t.size)
            elif str(k) in self._shapes:
                shape = self._shapes[str(k)]
                size = int(onp.prod(shape, dtype=onp.int64)) \
                    if shape else 1
            else:
                shape, size = None, None
            shapes[li] = shape
            parts = self._plan(k, size) if size is not None else None
            if parts is None:
                requests.append((self._server_of(k), str(k), li, None))
            else:
                for wk, sidx, st, sp in parts:
                    requests.append((sidx, wk, li, st))
        by_server: Dict[int, List[Any]] = {}
        for r in requests:
            by_server.setdefault(r[0], []).append(r)
        pieces: Dict[int, List[Any]] = {}         # li -> [(start, flat)]
        for sidx, rs in by_server.items():
            if len(rs) == 1:
                _, wk, li, st = rs[0]
                cmd, hdr, payload = self._rpc_server(sidx, b"G",
                                                     {"key": wk})
                if cmd != b"V":
                    raise MXNetError(f"pull failed for key {wk!r}")
                pieces.setdefault(li, []).append(
                    (st, _payload_arr(hdr, payload)))
            else:
                cmd, hdr, payload = self._rpc_server(
                    sidx, b"g", {"keys": [r[1] for r in rs]})
                if cmd != b"v":
                    raise MXNetError("multi-pull failed")
                for r, a in zip(rs, _unpack_leaves(hdr["specs"],
                                                   payload)):
                    pieces.setdefault(r[2], []).append((r[3], a))
        arrays: List[onp.ndarray] = []
        for li in range(len(keys)):
            got = pieces[li]
            if len(got) == 1 and got[0][0] is None:
                arrays.append(got[0][1])
            else:
                got.sort(key=lambda t: t[0])
                flat = onp.concatenate([a.ravel() for _, a in got])
                arrays.append(flat.reshape(shapes[li]))
        results = []
        for a, o in zip(arrays, outs):
            nd = array(a)
            if o is not None:
                targets = o if isinstance(o, (list, tuple)) else [o]
                for t in targets:
                    t._data = nd._data
            results.append(nd)
        return results[0] if not isinstance(key, (list, tuple)) else results

    def set_optimizer(self, optimizer) -> None:
        """Ship the optimizer to every server (reference: pickled via
        ``_send_command_to_servers``; here name + scalar hyperparams)."""
        from . import optimizer as opt
        if isinstance(optimizer, str):
            name, params = optimizer, {}
        elif isinstance(optimizer, opt.Optimizer):
            name = type(optimizer).__name__.lower()
            params = {"learning_rate": optimizer.lr,
                      "wd": optimizer.wd,
                      "rescale_grad": optimizer.rescale_grad}
            if optimizer.clip_gradient is not None:
                params["clip_gradient"] = optimizer.clip_gradient
            for attr, val in vars(optimizer).items():
                if attr.startswith("_") or attr in (
                        "lr", "wd", "rescale_grad", "clip_gradient",
                        "num_update", "begin_num_update", "aggregate_num",
                        "multi_precision", "param_dict", "lr_scheduler"):
                    continue
                if isinstance(val, (int, float, bool)):
                    params[attr] = val
        else:
            raise MXNetError("set_optimizer expects a name or Optimizer")
        for sidx in range(self.num_servers):
            self._rpc_server(sidx, b"O", {"name": name, "params": params})
        self._shipped_params = dict(params)
        self._shipped_opt = (name, dict(params))

    def update_optimizer_params(self, params: Dict[str, Any]) -> None:
        """Push changed scalar hyperparams (lr, rescale_grad, wd, ...) to
        the live server-side optimizer WITHOUT resetting its state —
        how lr schedules and loss scaling reach the service."""
        changed = {k: v for k, v in params.items()
                   if self._shipped_params.get(k) != v}
        if not changed:
            return
        for sidx in range(self.num_servers):
            self._rpc_server(sidx, b"H", {"params": changed})
        self._shipped_params.update(changed)
        if self._shipped_opt is not None:
            # keep the restart re-ship config current: a server restarted
            # with a pre-optimizer snapshot must receive the LIVE
            # hyperparams, not the job-start ones
            name, params = self._shipped_opt
            self._shipped_opt = (name, dict(params, **changed))

    def save_optimizer_states(self, fname: str,
                              dump_weight: bool = False) -> None:
        """Fetch server-side Updater states and write the Trainer states
        pickle format (reference: update_on_kvstore state saving)."""
        import pickle
        states: Dict[str, Any] = {}
        num_update = 0
        index_counts: Dict[str, int] = {}
        for sidx in range(self.num_servers):
            _, hdr, payload = self._rpc_server(sidx, b"X", {})
            if hdr.get("states") is None:
                continue
            leaves = _unpack_leaves(hdr["specs"], payload)
            for k, obj in hdr["states"].items():
                states[k] = _dec_state(obj, leaves)
            counts = hdr.get("counts", {})
            num_update = max(num_update, counts.get("num_update", 0))
            index_counts.update(counts.get("index_update_count", {}))
        with open(fname, "wb") as f:
            pickle.dump({"format": 2, "num_update": num_update,
                         "index_update_count": index_counts,
                         "states": states}, f)

    def load_optimizer_states(self, fname: str) -> None:
        import pickle
        import re
        with open(fname, "rb") as f:
            payload = pickle.load(f)
        by_server: Dict[int, Dict[str, Any]] = {}
        for k, s in payload["states"].items():
            k = str(k)
            # migrate state files saved before the control-char slice
            # separator: a trailing '@s<digits>' was the old slice
            # subkey form (user keys can't be disambiguated in old
            # files; slice subkeys vastly dominate, so rewrite)
            m = re.fullmatch(r"(.+)@s(\d+)", k)
            if m and _SLICE_SEP not in k:
                k = f"{m.group(1)}{_SLICE_SEP}{m.group(2)}"
            by_server.setdefault(self._server_of_wire(k), {})[k] = s
        counts = {"num_update": payload.get("num_update", 0),
                  "index_update_count":
                      {str(k): v for k, v in
                       payload.get("index_update_count", {}).items()}}
        for sidx, chunk in by_server.items():
            leaves: List[onp.ndarray] = []
            enc = {k: _enc_state(s, leaves) for k, s in chunk.items()}
            specs, raw = _pack_leaves(leaves)
            self._rpc_server(sidx, b"Y",
                             {"states": enc, "specs": specs,
                              "counts": counts}, raw)

    def set_gradient_compression(self, compression_params) -> None:
        """Compress push payloads on the DCN wire (reference:
        gradient_compression.cc over ps-lite). 2-bit error-feedback
        residuals live PER WORKER — each worker carries its own deferred
        gradient mass, which stays well-defined under Hogwild updates
        (server-side residuals would not). Pulls (weights) stay
        uncompressed, as in the reference."""
        ctype = compression_params.get("type", "2bit")
        if ctype not in ("2bit", "fp16", "bf16", "int8", "none"):
            raise MXNetError(f"unknown compression type {ctype!r}")
        if ctype == "2bit" and float(
                compression_params.get("threshold", 0.5)) <= 0:
            raise MXNetError("2bit compression threshold must be > 0")
        self._compression = {} if ctype == "none" \
            else dict(compression_params, type=ctype)
        self._residuals = {}

    def barrier(self) -> None:
        # the rank rides the frame so a barrier timeout can NAME the
        # missing workers in the server's error; the health watchdog
        # (when armed via MXNET_HEALTH_STEP_DEADLINE_S) dumps all-thread
        # stacks if the barrier outlives the deadline — the "which rank
        # is holding the job up" diagnostic for a wedged fleet
        from . import health as _health
        with _health.watch_section("kvstore.barrier", rank=self._rank):
            for sidx in range(self.num_servers):
                self._rpc_server(sidx, b"B", {"rank": self._rank})

    def server_stats(self) -> List[Dict[str, Any]]:
        return [self._rpc_server(sidx, b"Q", {})[1]
                for sidx in range(self.num_servers)]

    def _next_ckpt_round(self, phase: str) -> str:
        with self._seq_lock:
            self._ckpt_rounds[phase] += 1
            return f"{self._client_id}:{self._ckpt_rounds[phase]}"

    def ckpt_mark(self, step: int) -> int:
        """Phase 1 of the coordinated cluster checkpoint: propose
        ``step`` and block until every worker proposed; returns the
        agreed step (the min proposed — the cluster-consistent floor).
        Server 0 is the coordinator.  A dead rank abandons the round
        with a structured error naming it."""
        from . import health as _health
        with _health.watch_section("kvstore.ckpt_mark", rank=self._rank):
            _, hdr, _ = self._rpc_server(
                0, b"C", {"phase": "mark", "step": int(step),
                          "rank": self._rank,
                          "cround": self._next_ckpt_round("mark")})
        return int(hdr["step"])

    def ckpt_commit(self, step: int) -> int:
        """Phase 2: report this rank's checkpoint for ``step`` is
        durably on disk; blocks until every rank committed, after
        which the cluster as a whole can resume from ``step``."""
        from . import health as _health
        with _health.watch_section("kvstore.ckpt_commit",
                                   rank=self._rank):
            _, hdr, _ = self._rpc_server(
                0, b"C", {"phase": "commit", "step": int(step),
                          "rank": self._rank,
                          "cround": self._next_ckpt_round("commit")})
        return int(hdr.get("committed", step))

    def ckpt_last_committed(self) -> int:
        """The coordinator's record of the newest fully committed
        cluster checkpoint step (-1: none)."""
        _, hdr, _ = self._rpc_server(0, b"Q", {})
        return int(hdr.get("ckpt_committed", -1))

    def stop_servers(self) -> None:
        """Ask every server process to exit (rank 0, end of job)."""
        self.stop_heartbeat()
        for sidx in range(self.num_servers):
            try:
                self._rpc_server(sidx, b"S", {})
            except (ConnectionError, OSError, MXNetError):
                pass

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def num_workers(self) -> int:
        return self._num_workers

    def __repr__(self) -> str:
        return (f"KVStoreDistAsync(servers={self.num_servers} @ "
                f"{self.uri}:{self.port}, rank={self._rank}/"
                f"{self._num_workers})")


# key/value normalization and pushpull are the base store's — one
# implementation, one behavior (kvstore.py)
from .kvstore import KVStore as _KVStoreBase
KVStoreDistAsync._pair = staticmethod(_KVStoreBase._pair)  # type: ignore
KVStoreDistAsync._norm_priorities = \
    staticmethod(_KVStoreBase._norm_priorities)      # type: ignore
KVStoreDistAsync.pushpull = _KVStoreBase.pushpull    # type: ignore


def main() -> None:
    """Server-process entry (``DMLC_ROLE=server``):
    ``python -m mxnet_tpu.kvstore_async``."""
    root = int(os.environ.get("DMLC_PS_ROOT_PORT", "9876"))
    # root port 0 = OS-assigned per server (published via
    # MXNET_PS_PORT_FILE); a fixed root keeps the +server_id contract
    port = 0 if root == 0 else root + \
        int(os.environ.get("DMLC_SERVER_ID", "0"))
    nw = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    run_server(port, nw)


if __name__ == "__main__":
    main()
