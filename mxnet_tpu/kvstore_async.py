"""Async parameter service — ``kvstore='dist_async'``.

Reference parity (leezu/mxnet): ``kvstore_dist.h`` async branch +
``kvstore_dist_server.h`` (``KVStoreDistServer::DataHandleDefault``) over
ps-lite — workers push gradients and pull weights at their own pace; the
server applies the optimizer IMMEDIATELY per push (Hogwild-style, no
worker synchronization), which tolerates slow workers by design.

Design (tpu-first, SURVEY.md 2.3/5.8): ICI collectives have no async
analog, so this is the prescribed "host-driven DCN parameter service" —
plain TCP between host processes (the reference's ZMQ van), weights and
optimizer state live host-side in the server process, device work stays
on each worker. The wire protocol is a length-prefixed binary frame
(json header + raw array bytes) — no pickle, so a malicious peer cannot
execute code in the server; ``set_optimizer`` ships (name, scalar
hyperparams) and the server instantiates from the optimizer registry
(the reference pickled the optimizer object to the server — same
capability, safer encoding).

Roles follow the reference env contract: ``tools/launch.py -s S`` starts
``S`` server processes (``DMLC_ROLE=server``, this module's ``main``)
and points workers at them via ``DMLC_PS_ROOT_URI`` /
``DMLC_PS_ROOT_PORT`` / ``DMLC_NUM_SERVER``. With S > 1, keys are
assigned whole to servers by stable hash (the reference sliced single
big arrays across servers — PSKV; whole-key assignment keeps each
update atomic on one server).
"""
from __future__ import annotations

import json
import os
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as onp

from .base import MXNetError

__all__ = ["PSServer", "KVStoreDistAsync", "run_server"]

_MAGIC = b"MXPS"


# ---------------------------------------------------------------------------
# framing: MXPS | uint32 body_len | cmd(1) | uint32 hdr_len | hdr json | raw
# ---------------------------------------------------------------------------

def _send_frame(sock: socket.socket, cmd: bytes, header: Dict[str, Any],
                payload: bytes = b"") -> None:
    hdr = json.dumps(header).encode()
    body = cmd + struct.pack("<I", len(hdr)) + hdr + payload
    sock.sendall(_MAGIC + struct.pack("<I", len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket):
    magic = _recv_exact(sock, 4)
    if magic != _MAGIC:
        raise MXNetError("bad frame magic (not an mxnet_tpu PS peer)")
    (blen,) = struct.unpack("<I", _recv_exact(sock, 4))
    body = _recv_exact(sock, blen)
    cmd = body[0:1]
    (hlen,) = struct.unpack("<I", body[1:5])
    header = json.loads(body[5:5 + hlen].decode())
    payload = body[5 + hlen:]
    return cmd, header, payload


def _arr_payload(a: onp.ndarray):
    a = onp.ascontiguousarray(a)
    return ({"dtype": str(a.dtype), "shape": list(a.shape)}, a.tobytes())


def _payload_arr(header: Dict[str, Any], payload: bytes) -> onp.ndarray:
    return onp.frombuffer(payload, dtype=header["dtype"]).reshape(
        header["shape"]).copy()


# ---------------------------------------------------------------------------
# optimizer-state codec: a restricted, pickle-free structural encoding for
# shipping Updater.states over the wire (arrays ride the payload; the
# structure is JSON). Covers everything our optimizers produce: nested
# tuples/lists/dicts, numbers, None, arrays, MasterWeightState.
# ---------------------------------------------------------------------------

def _enc_state(s, leaves: List[onp.ndarray]):
    from .optimizer import MasterWeightState
    if s is None:
        return {"t": "none"}
    if isinstance(s, bool):
        return {"t": "bool", "v": s}
    if isinstance(s, (int, float)):
        return {"t": "num", "v": s}
    if isinstance(s, MasterWeightState):
        return {"t": "mws", "m": _enc_state(s.master, leaves),
                "s": _enc_state(s.inner, leaves)}
    if isinstance(s, tuple):
        return {"t": "tup", "v": [_enc_state(x, leaves) for x in s]}
    if isinstance(s, list):
        return {"t": "list", "v": [_enc_state(x, leaves) for x in s]}
    if isinstance(s, dict):
        return {"t": "dict",
                "v": {str(k): _enc_state(x, leaves)
                      for k, x in s.items()}}
    a = onp.asarray(getattr(s, "_data", s))
    leaves.append(onp.ascontiguousarray(a))
    return {"t": "arr", "i": len(leaves) - 1,
            "dtype": str(a.dtype), "shape": list(a.shape)}


def _dec_state(obj, leaves: Sequence[onp.ndarray]):
    from .optimizer import MasterWeightState
    t = obj["t"]
    if t == "none":
        return None
    if t in ("bool", "num"):
        return obj["v"]
    if t == "mws":
        return MasterWeightState(_dec_state(obj["m"], leaves),
                                 _dec_state(obj["s"], leaves))
    if t == "tup":
        return tuple(_dec_state(x, leaves) for x in obj["v"])
    if t == "list":
        return [_dec_state(x, leaves) for x in obj["v"]]
    if t == "dict":
        return {k: _dec_state(x, leaves) for k, x in obj["v"].items()}
    if t == "arr":
        return leaves[obj["i"]]
    raise MXNetError(f"bad state encoding tag {t!r}")


def _pack_leaves(leaves: Sequence[onp.ndarray]):
    specs = [{"dtype": str(a.dtype), "shape": list(a.shape),
              "nbytes": a.nbytes} for a in leaves]
    return specs, b"".join(a.tobytes() for a in leaves)


def _unpack_leaves(specs, payload: bytes) -> List[onp.ndarray]:
    out, off = [], 0
    for sp in specs:
        n = sp["nbytes"]
        out.append(onp.frombuffer(payload[off:off + n],
                                  dtype=sp["dtype"]).reshape(sp["shape"])
                   .copy())
        off += n
    return out


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        srv: "PSServer" = self.server.ps          # type: ignore[attr-defined]
        try:
            while True:
                cmd, header, payload = _recv_frame(self.request)
                if cmd == b"S":
                    _send_frame(self.request, b"K", {})
                    threading.Thread(target=self.server.shutdown,
                                     daemon=True).start()
                    return
                try:
                    reply = srv.handle(cmd, header, payload)
                except Exception as e:   # report, keep the connection
                    reply = (b"E", {"error": str(e)}, b"")
                _send_frame(self.request, *reply)
        except (ConnectionError, OSError):
            return


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class PSServer:
    """In-process parameter server state + request handler
    (``KVStoreDistServer`` analog)."""

    def __init__(self, num_workers: int) -> None:
        self.num_workers = num_workers
        self.store: Dict[str, onp.ndarray] = {}
        self.locks: Dict[str, threading.Lock] = {}
        self.updater = None                      # optimizer.Updater
        self._global_lock = threading.Lock()
        self._barrier_lock = threading.Lock()
        self._barrier_cv = threading.Condition(self._barrier_lock)
        self._barrier_count = 0
        self._barrier_gen = 0
        self.pushes = 0

    def _lock_for(self, key: str) -> threading.Lock:
        with self._global_lock:
            if key not in self.locks:
                self.locks[key] = threading.Lock()
            return self.locks[key]

    def handle(self, cmd: bytes, header: Dict[str, Any], payload: bytes):
        if cmd == b"I":                          # init (first wins)
            key = header["key"]
            with self._lock_for(key):
                if key not in self.store:
                    self.store[key] = _payload_arr(header, payload)
            return b"K", {}, b""
        if cmd == b"P":                          # push
            key = header["key"]
            grad = _payload_arr(header, payload)
            with self._lock_for(key):
                if key not in self.store:
                    raise MXNetError(f"push to uninitialized key {key!r}")
                if self.updater is not None:
                    # async mode proper: apply the optimizer NOW, per
                    # worker push — no aggregation window (Hogwild)
                    self._apply_update(key, grad)
                else:
                    # no server-side optimizer: running sum (the pulled
                    # value is the sum of everything pushed since init)
                    self.store[key] = self.store[key] + grad
            with self._global_lock:
                self.pushes += 1
            return b"K", {}, b""
        if cmd == b"G":                          # pull
            key = header["key"]
            with self._lock_for(key):
                if key not in self.store:
                    raise MXNetError(f"pull of uninitialized key {key!r}")
                hdr, raw = _arr_payload(self.store[key])
            return b"V", hdr, raw
        if cmd == b"p":                          # multi-key push
            keys = header["keys"]
            grads = _unpack_leaves(header["specs"], payload)
            for key, grad in zip(keys, grads):
                with self._lock_for(key):
                    if key not in self.store:
                        raise MXNetError(
                            f"push to uninitialized key {key!r}")
                    if self.updater is not None:
                        self._apply_update(key, grad)
                    else:
                        self.store[key] = self.store[key] + grad
                with self._global_lock:
                    self.pushes += 1
            return b"K", {}, b""
        if cmd == b"g":                          # multi-key pull
            keys = header["keys"]
            vals = []
            for key in keys:
                with self._lock_for(key):
                    if key not in self.store:
                        raise MXNetError(
                            f"pull of uninitialized key {key!r}")
                    vals.append(self.store[key])
            specs, raw = _pack_leaves(vals)
            return b"v", {"specs": specs}, raw
        if cmd == b"H":                          # update live hyperparams
            with self._global_lock:
                if self.updater is None:
                    raise MXNetError("no optimizer on this server")
                o = self.updater.optimizer
                for k, v in header.get("params", {}).items():
                    if k == "learning_rate":
                        o.lr = v
                    elif hasattr(o, k) and isinstance(
                            getattr(o, k), (int, float, bool, type(None))):
                        setattr(o, k, v)
            return b"K", {}, b""
        if cmd == b"X":                          # fetch optimizer states
            with self._global_lock:
                if self.updater is None:
                    return b"v", {"states": None, "specs": []}, b""
                leaves: List[onp.ndarray] = []
                enc = {str(k): _enc_state(s, leaves)
                       for k, s in self.updater.states.items()}
                specs, raw = _pack_leaves(leaves)
                o = self.updater.optimizer
                counts = {"num_update": o.num_update,
                          "index_update_count":
                              {str(k): v for k, v
                               in o._index_update_count.items()}}
            return b"v", {"states": enc, "specs": specs,
                          "counts": counts}, raw
        if cmd == b"Y":                          # restore optimizer states
            with self._global_lock:
                if self.updater is None:
                    raise MXNetError(
                        "set_optimizer before loading states")
                leaves = _unpack_leaves(header["specs"], payload)
                self.updater.states = {
                    k: _dec_state(obj, leaves)
                    for k, obj in header["states"].items()}
                counts = header.get("counts")
                if counts:
                    o = self.updater.optimizer
                    o.num_update = max(o.num_update,
                                       counts.get("num_update", 0))
                    o._index_update_count.update(
                        counts.get("index_update_count", {}))
            return b"K", {}, b""
        if cmd == b"O":                          # set_optimizer
            from . import optimizer as opt
            with self._global_lock:
                o = opt.create(header["name"], **header.get("params", {}))
                self.updater = opt.get_updater(o)
            return b"K", {}, b""
        if cmd == b"B":                          # barrier over all workers
            timeout = float(os.environ.get(
                "MXNET_PS_BARRIER_TIMEOUT", "600"))
            with self._barrier_cv:
                gen = self._barrier_gen
                self._barrier_count += 1
                if self._barrier_count >= self.num_workers:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                else:
                    ok = self._barrier_cv.wait_for(
                        lambda: self._barrier_gen != gen, timeout=timeout)
                    if not ok:
                        self._barrier_count -= 1
                        raise MXNetError(
                            f"barrier timed out after {timeout:.0f}s "
                            f"waiting for {self.num_workers} workers "
                            "(MXNET_PS_BARRIER_TIMEOUT to raise)")
            return b"K", {}, b""
        if cmd == b"Q":                          # stats (introspection)
            return b"K", {"pushes": self.pushes,
                          "keys": sorted(self.store)}, b""
        raise MXNetError(f"unknown PS command {cmd!r}")

    def _apply_update(self, key: str, grad: onp.ndarray) -> None:
        from .ndarray.ndarray import NDArray
        import jax.numpy as jnp
        w = NDArray(jnp.asarray(self.store[key]), _wrap=True)
        g = NDArray(jnp.asarray(grad), _wrap=True)
        self.updater(key, g, w)                  # mutates w in place
        self.store[key] = onp.asarray(w._data)


def run_server(port: int, num_workers: int,
               ready_event: Optional[threading.Event] = None) -> None:
    """Serve until a STOP frame arrives (blocking)."""
    ps = PSServer(num_workers)
    with _TCPServer(("0.0.0.0", port), _Handler) as server:
        server.ps = ps                           # type: ignore[attr-defined]
        if ready_event is not None:
            ready_event.set()
        server.serve_forever(poll_interval=0.1)


# ---------------------------------------------------------------------------
# worker-side client
# ---------------------------------------------------------------------------

class KVStoreDistAsync:
    """Worker-side ``kvstore='dist_async'`` client.

    API-compatible subset of KVStore: init/push/pull/pushpull,
    set_optimizer (ships to the servers), barrier, rank/num_workers.
    Per-key requests go whole to ``hash(key) % num_servers``.
    """

    type = "dist_async"

    def __init__(self) -> None:
        self.uri = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        self.port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9876"))
        self.num_servers = int(os.environ.get("DMLC_NUM_SERVER", "1"))
        self._rank = int(os.environ.get("DMLC_WORKER_ID",
                                        os.environ.get("JAX_PROCESS_ID",
                                                       "0")))
        self._num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        self._socks: List[Optional[socket.socket]] = \
            [None] * self.num_servers
        # one lock per server connection: requests to different servers
        # may overlap; frames on one socket are serialized
        self._locks = [threading.Lock() for _ in range(self.num_servers)]
        self._shipped_params: Dict[str, Any] = {}

    # -- plumbing ----------------------------------------------------------
    def _sock(self, sidx: int) -> socket.socket:
        s = self._socks[sidx]
        if s is None:
            # the server process imports the framework (jax) before it
            # listens — allow for a slow cold start on a loaded machine
            deadline = time.time() + float(
                os.environ.get("MXNET_PS_CONNECT_TIMEOUT", "120"))
            last: Optional[Exception] = None
            while time.time() < deadline:
                try:
                    s = socket.create_connection(
                        (self.uri, self.port + sidx), timeout=30)
                    # blocking from here on: a barrier reply may take up
                    # to MXNET_PS_BARRIER_TIMEOUT, far past any sane
                    # per-recv timeout
                    s.settimeout(None)
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    self._socks[sidx] = s
                    return s
                except OSError as e:             # server still starting
                    last = e
                    time.sleep(0.2)
            raise MXNetError(
                f"cannot reach parameter server at "
                f"{self.uri}:{self.port + sidx}: {last}")
        return s

    def _server_of(self, key: Any) -> int:
        import zlib
        return zlib.crc32(str(key).encode()) % self.num_servers

    def _rpc_server(self, sidx: int, cmd: bytes, header: Dict[str, Any],
                    payload: bytes = b""):
        with self._locks[sidx]:
            try:
                s = self._sock(sidx)
                _send_frame(s, cmd, header, payload)
                rcmd, rhdr, rpayload = _recv_frame(s)
            except (ConnectionError, OSError):
                # a half-done exchange leaves the stream desynced — drop
                # the socket so the next call reconnects cleanly
                if self._socks[sidx] is not None:
                    try:
                        self._socks[sidx].close()
                    except OSError:
                        pass
                    self._socks[sidx] = None
                raise
        if rcmd == b"E":
            raise MXNetError(f"parameter server: {rhdr.get('error')}")
        return rcmd, rhdr, rpayload

    def _rpc(self, key: Any, cmd: bytes, header: Dict[str, Any],
             payload: bytes = b""):
        return self._rpc_server(self._server_of(key), cmd, header, payload)

    @staticmethod
    def _to_numpy(v) -> onp.ndarray:
        if isinstance(v, (list, tuple)):          # per-device list: local sum
            acc = onp.asarray(v[0].asnumpy(), onp.float32)
            for x in v[1:]:
                acc = acc + onp.asarray(x.asnumpy(), onp.float32)
            return acc
        return onp.asarray(v.asnumpy())

    # -- KVStore API -------------------------------------------------------
    def init(self, key, value) -> None:
        keys, vals = self._pair(key, value)
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):
                v = v[0]
            hdr, raw = _arr_payload(onp.asarray(v.asnumpy()))
            hdr["key"] = str(k)
            self._rpc(k, b"I", hdr, raw)

    def push(self, key, value, priority: int = 0) -> None:
        keys, vals = self._pair(key, value)
        if len(keys) == 1:
            hdr, raw = _arr_payload(self._to_numpy(vals[0]))
            hdr["key"] = str(keys[0])
            self._rpc(keys[0], b"P", hdr, raw)
            return
        # group by server: the whole multi-key push crosses the wire as
        # ONE frame per server (the ICI path's bucketing analog)
        by_server: Dict[int, List[int]] = {}
        for i, k in enumerate(keys):
            by_server.setdefault(self._server_of(k), []).append(i)
        for sidx, idxs in by_server.items():
            arrs = [self._to_numpy(vals[i]) for i in idxs]
            specs, raw = _pack_leaves(arrs)
            self._rpc_server(sidx, b"p",
                             {"keys": [str(keys[i]) for i in idxs],
                              "specs": specs}, raw)

    def pull(self, key, out=None, priority: int = 0,
             ignore_sparse: bool = True):
        from .ndarray.ops import array
        keys, outs = self._pair(key, out)
        arrays: List[Optional[onp.ndarray]] = [None] * len(keys)
        if len(keys) == 1:
            cmd, hdr, payload = self._rpc(keys[0], b"G",
                                          {"key": str(keys[0])})
            if cmd != b"V":
                raise MXNetError(f"pull failed for key {keys[0]!r}")
            arrays[0] = _payload_arr(hdr, payload)
        else:
            by_server: Dict[int, List[int]] = {}
            for i, k in enumerate(keys):
                by_server.setdefault(self._server_of(k), []).append(i)
            for sidx, idxs in by_server.items():
                cmd, hdr, payload = self._rpc_server(
                    sidx, b"g", {"keys": [str(keys[i]) for i in idxs]})
                if cmd != b"v":
                    raise MXNetError("multi-pull failed")
                for i, a in zip(idxs, _unpack_leaves(hdr["specs"],
                                                     payload)):
                    arrays[i] = a
        results = []
        for a, o in zip(arrays, outs):
            nd = array(a)
            if o is not None:
                targets = o if isinstance(o, (list, tuple)) else [o]
                for t in targets:
                    t._data = nd._data
            results.append(nd)
        return results[0] if not isinstance(key, (list, tuple)) else results

    def set_optimizer(self, optimizer) -> None:
        """Ship the optimizer to every server (reference: pickled via
        ``_send_command_to_servers``; here name + scalar hyperparams)."""
        from . import optimizer as opt
        if isinstance(optimizer, str):
            name, params = optimizer, {}
        elif isinstance(optimizer, opt.Optimizer):
            name = type(optimizer).__name__.lower()
            params = {"learning_rate": optimizer.lr,
                      "wd": optimizer.wd,
                      "rescale_grad": optimizer.rescale_grad}
            if optimizer.clip_gradient is not None:
                params["clip_gradient"] = optimizer.clip_gradient
            for attr, val in vars(optimizer).items():
                if attr.startswith("_") or attr in (
                        "lr", "wd", "rescale_grad", "clip_gradient",
                        "num_update", "begin_num_update", "aggregate_num",
                        "multi_precision", "param_dict", "lr_scheduler"):
                    continue
                if isinstance(val, (int, float, bool)):
                    params[attr] = val
        else:
            raise MXNetError("set_optimizer expects a name or Optimizer")
        for sidx in range(self.num_servers):
            self._rpc_server(sidx, b"O", {"name": name, "params": params})
        self._shipped_params = dict(params)

    def update_optimizer_params(self, params: Dict[str, Any]) -> None:
        """Push changed scalar hyperparams (lr, rescale_grad, wd, ...) to
        the live server-side optimizer WITHOUT resetting its state —
        how lr schedules and loss scaling reach the service."""
        changed = {k: v for k, v in params.items()
                   if self._shipped_params.get(k) != v}
        if not changed:
            return
        for sidx in range(self.num_servers):
            self._rpc_server(sidx, b"H", {"params": changed})
        self._shipped_params.update(changed)

    def save_optimizer_states(self, fname: str,
                              dump_weight: bool = False) -> None:
        """Fetch server-side Updater states and write the Trainer states
        pickle format (reference: update_on_kvstore state saving)."""
        import pickle
        states: Dict[str, Any] = {}
        num_update = 0
        index_counts: Dict[str, int] = {}
        for sidx in range(self.num_servers):
            _, hdr, payload = self._rpc_server(sidx, b"X", {})
            if hdr.get("states") is None:
                continue
            leaves = _unpack_leaves(hdr["specs"], payload)
            for k, obj in hdr["states"].items():
                states[k] = _dec_state(obj, leaves)
            counts = hdr.get("counts", {})
            num_update = max(num_update, counts.get("num_update", 0))
            index_counts.update(counts.get("index_update_count", {}))
        with open(fname, "wb") as f:
            pickle.dump({"format": 2, "num_update": num_update,
                         "index_update_count": index_counts,
                         "states": states}, f)

    def load_optimizer_states(self, fname: str) -> None:
        import pickle
        with open(fname, "rb") as f:
            payload = pickle.load(f)
        by_server: Dict[int, Dict[str, Any]] = {}
        for k, s in payload["states"].items():
            by_server.setdefault(self._server_of(str(k)), {})[str(k)] = s
        counts = {"num_update": payload.get("num_update", 0),
                  "index_update_count":
                      {str(k): v for k, v in
                       payload.get("index_update_count", {}).items()}}
        for sidx, chunk in by_server.items():
            leaves: List[onp.ndarray] = []
            enc = {k: _enc_state(s, leaves) for k, s in chunk.items()}
            specs, raw = _pack_leaves(leaves)
            self._rpc_server(sidx, b"Y",
                             {"states": enc, "specs": specs,
                              "counts": counts}, raw)

    def set_gradient_compression(self, compression_params) -> None:
        raise MXNetError(
            "gradient compression is not supported on the async service "
            "(error-feedback residuals are undefined under Hogwild "
            "updates); use kvstore='ici' for compressed sync training")

    def barrier(self) -> None:
        for sidx in range(self.num_servers):
            self._rpc_server(sidx, b"B", {})

    def server_stats(self) -> List[Dict[str, Any]]:
        return [self._rpc_server(sidx, b"Q", {})[1]
                for sidx in range(self.num_servers)]

    def stop_servers(self) -> None:
        """Ask every server process to exit (rank 0, end of job)."""
        for sidx in range(self.num_servers):
            try:
                self._rpc_server(sidx, b"S", {})
            except (ConnectionError, OSError, MXNetError):
                pass

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def num_workers(self) -> int:
        return self._num_workers

    def __repr__(self) -> str:
        return (f"KVStoreDistAsync(servers={self.num_servers} @ "
                f"{self.uri}:{self.port}, rank={self._rank}/"
                f"{self._num_workers})")


# key/value normalization and pushpull are the base store's — one
# implementation, one behavior (kvstore.py)
from .kvstore import KVStore as _KVStoreBase
KVStoreDistAsync._pair = staticmethod(_KVStoreBase._pair)  # type: ignore
KVStoreDistAsync.pushpull = _KVStoreBase.pushpull    # type: ignore


def main() -> None:
    """Server-process entry (``DMLC_ROLE=server``):
    ``python -m mxnet_tpu.kvstore_async``."""
    port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9876")) + \
        int(os.environ.get("DMLC_SERVER_ID", "0"))
    nw = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    run_server(port, nw)


if __name__ == "__main__":
    main()
