"""``mx.npx`` — numpy-extension namespace (NN ops + runtime utilities).

Reference parity: ``python/mxnet/numpy_extension/`` — operators outside the
NumPy standard (conv, pooling, norms, sequence ops) plus ``set_np`` and
device helpers.
"""
from .ops.nn import *  # noqa: F401,F403
from .ops.nn import __all__ as _nn_all
from .ops.transformer import *  # noqa: F401,F403
from .ops.transformer import __all__ as _tr_all
from .ops.quantization import *  # noqa: F401,F403
from .ops.quantization import __all__ as _q_all
from .util import set_np, reset_np, is_np_array, is_np_shape, use_np
from .context import cpu, gpu, tpu, num_gpus, num_tpus, current_context
from .ndarray.ndarray import waitall
from .ndarray.ops import (one_hot, topk, pad, arange, reshape,  # noqa: F401
                          gather_nd, scatter_nd, sigmoid, tanh,
                          reshape_like, broadcast_like, batch_dot,
                          depth_to_space, space_to_depth,
                          shuffle, spatial_transformer, khatri_rao)

__all__ = list(_nn_all) + list(_tr_all) + list(_q_all) + [
    "set_np", "reset_np", "is_np_array", "is_np_shape", "use_np",
    "cpu", "gpu", "tpu", "num_gpus", "num_tpus", "current_context",
    "waitall", "one_hot", "topk", "pad", "arange", "reshape", "gather_nd",
    "scatter_nd", "sigmoid", "tanh", "reshape_like", "broadcast_like",
    "batch_dot", "depth_to_space", "space_to_depth",
    "shuffle", "spatial_transformer", "khatri_rao",
]
