"""``mx.np`` — numpy-semantics array namespace (the primary user API).

Reference parity: ``python/mxnet/numpy/`` (multiarray.py etc., the 2.x
NumPy interface that the leezu fork's era standardized on). Shares the one
op registry with ``mx.nd`` — same NDArray type, same functions — per the
"one op set, two execution modes" design (SURVEY.md section 0).
"""
import numpy as _onp

from ..ndarray.ndarray import NDArray as ndarray  # noqa: N813
from ..ndarray.ndarray import NDArray, from_jax
from ..ndarray.ops import *  # noqa: F401,F403
from ..ndarray.ops import __all__ as _ops_all
from ..ndarray.ops_numpy import *  # noqa: F401,F403
from ..ndarray.ops_numpy import __all__ as _ops_np_all
from ..ndarray import random  # noqa: F401
from ..ndarray import linalg  # noqa: F401

# dtype aliases / constants
float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
bfloat16 = "bfloat16"
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
bool_ = _onp.bool_
pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
newaxis = None
dtype = _onp.dtype

__all__ = (["ndarray", "NDArray", "from_jax", "random", "linalg", "float16",
            "float32", "float64", "bfloat16", "int8", "int16", "int32",
            "int64", "uint8", "bool_", "pi", "e", "inf", "nan", "newaxis",
            "dtype"] + list(_ops_all) + list(_ops_np_all))
