"""Training health guard — NaN/Inf sentry, divergence recovery, hang
watchdog.

NEW capability beyond the reference (no leezu/mxnet analog): PR 3 made
*crash-shaped* failures routine (worker death, preemption, torn
checkpoints), but the stack stayed blind to *silent* failures — a NaN
gradient poisons every later step, a diverging loss burns the rest of
the job's budget, and a hung collective stalls the whole fleet with no
evidence of who stopped.  The asynchronous engine the MXNet paper
describes makes exactly these failures hard to observe from Python
(arXiv:1512.01274 §4), and collective-based distributed training turns
one wedged rank into a whole-job hang (arXiv:1802.06949).  This module
is the always-available answer, three cooperating pieces behind one
:class:`HealthGuard`:

1. **Numerics sentry** — ``guard.check(loss, grads)`` runs ONE fused
   on-device finite/overflow reduction over the loss and every gradient
   (no per-tensor host syncs; a single 3-scalar readback per step) plus
   a windowed loss-divergence detector (EMA + spike threshold,
   ``MXNET_HEALTH_LOSS_SPIKE``).  Under the PR-4 bulking engine the
   check rides the step boundary's existing optimizer-donation barrier
   — it never forces an extra segment flush (tests/test_health.py
   asserts the flush count).

2. **Recovery policy** (``MXNET_HEALTH_POLICY``) —

   * ``skip``   — drop the step: the update is zeroed (on-device for
     ``SPMDTrainer``'s gated step; by marking grads consumed on the
     gluon path), and an attached AMP loss scaler decays;
   * ``rewind`` — restore the newest verified checkpoint through PR 3's
     ``CheckpointManager`` and replay with a perturbed data order
     (``guard.replay_salt`` is passed to ``batch_fn(step, salt=...)``
     when the callable accepts it);
   * ``abort``  — raise a structured :class:`HealthError` naming the
     first offending array (the fused reduction returns its index).

   Budgets (``MXNET_HEALTH_MAX_SKIPS`` / ``MXNET_HEALTH_MAX_REWINDS``)
   bound both recoveries: a truly broken run fails fast with a
   structured error instead of looping forever.

3. **Hang watchdog** — a lazy daemon thread armed per training step
   (``MXNET_HEALTH_STEP_DEADLINE_S``) and around kvstore collectives /
   barriers; serving's ``ModelServer`` arms it per executed batch.  On
   deadline it dumps every thread's stack plus a metrics snapshot to a
   diagnostics file (``MXNET_HEALTH_DIAG_DIR``), counts
   ``mxnet_health_events_total{kind="hang"}``, and — when the guarded
   section eventually completes under ``policy="abort"`` — raises.
   A section that never completes cannot be recovered in-process; the
   dump (who held the lock, which rank stalled) is the deliverable.

All three training loops share this one implementation:
``SPMDTrainer.fit(health_guard=)`` (the compiled step gates its own
update on-device, so a skipped step never touches parameters),
``Estimator.fit(health_guard=)``, and ``guard.install(trainer)`` for a
hand-written gluon loop.

Deterministic testing: the ``trainer.step`` fault site with
``kind=nan`` (``mxnet_tpu.faults``) corrupts the tensors feeding the
update, so a seeded ``MXNET_FAULT_PLAN`` replays the exact same
detect/skip/rewind schedule on every run.
"""
from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .base import MXNetError, getenv, register_env
from . import metrics as _metrics

__all__ = ["HealthError", "HealthVerdict", "HealthGuard", "HangWatchdog",
           "watchdog", "watch_section", "fused_finite_check",
           "last_dump_path"]

register_env(
    "MXNET_HEALTH_POLICY", "skip",
    "Default recovery policy of mxnet_tpu.health.HealthGuard when a "
    "training step goes bad (non-finite loss/gradients or a loss "
    "spike): 'skip' drops the step (zero update, AMP loss-scale "
    "decay), 'rewind' restores the newest verified checkpoint and "
    "replays with a perturbed data order, 'abort' raises a structured "
    "HealthError naming the first offending array.")
register_env(
    "MXNET_HEALTH_LOSS_SPIKE", 0.0,
    "Loss-divergence spike threshold for the health guard: a finite "
    "loss exceeding this factor times the windowed loss EMA (after "
    "MXNET_HEALTH_LOSS_WINDOW warmup steps) triggers the recovery "
    "policy. 0 (default) disables divergence detection; non-finite "
    "detection is always on while a guard is attached.")
register_env(
    "MXNET_HEALTH_LOSS_WINDOW", 20,
    "EMA window (steps) of the health guard's loss-divergence "
    "detector; also the warmup step count before spike detection "
    "arms.")
register_env(
    "MXNET_HEALTH_MAX_SKIPS", 10,
    "Skip budget of the health guard: after this many dropped steps "
    "in one run the guard aborts with a structured HealthError "
    "instead of skipping forever.")
register_env(
    "MXNET_HEALTH_MAX_REWINDS", 2,
    "Rewind budget of the health guard: after this many checkpoint "
    "rewinds in one run the guard aborts with a structured "
    "HealthError.")
register_env(
    "MXNET_HEALTH_STEP_DEADLINE_S", 0.0,
    "Hang-watchdog deadline (seconds) armed around each training step, "
    "kvstore collective/barrier, and served batch: past the deadline "
    "the watchdog dumps all-thread stacks + a metrics snapshot to "
    "MXNET_HEALTH_DIAG_DIR and counts mxnet_health_events_total"
    "{kind=\"hang\"}. 0 (default) disarms the watchdog.")
register_env(
    "MXNET_HEALTH_DIAG_DIR", "",
    "Directory for the hang watchdog's diagnostics dumps (all-thread "
    "stacks + metrics snapshot). Empty (default) writes into the "
    "current working directory.")

HEALTH_EVENTS = _metrics.counter(
    "mxnet_health_events_total",
    "Training health events detected by mxnet_tpu.health, by kind: "
    "nonfinite (NaN/Inf loss or gradient), loss_spike (finite loss "
    "above the EMA spike threshold), hang (watchdog deadline "
    "expired).", labels=("kind",))
HEALTH_SKIPS = _metrics.counter(
    "mxnet_health_skipped_steps_total",
    "Training steps dropped by the health guard's skip policy (update "
    "zeroed, AMP loss-scale decayed).")
HEALTH_REWINDS = _metrics.counter(
    "mxnet_health_rewinds_total",
    "Checkpoint rewinds performed by the health guard's rewind "
    "policy.")
HEALTH_CHECK_SECONDS = _metrics.histogram(
    "mxnet_health_check_seconds",
    "Wall time of the health guard's fused numerics check (dispatch + "
    "the single per-step scalar readback).")
HEALTH_WATCHDOG_FIRES = _metrics.counter(
    "mxnet_health_watchdog_fires_total",
    "Hang-watchdog deadline expirations, by guarded site (each writes "
    "one diagnostics dump).", labels=("site",))
HEALTH_LOSS_EMA = _metrics.gauge(
    "mxnet_health_loss_ema",
    "The health guard's windowed loss EMA (divergence-detector "
    "state).")

_POLICIES = ("skip", "rewind", "abort")


class HealthError(MXNetError):
    """A health-guard abort: non-recoverable numerics, an exhausted
    skip/rewind budget, or a deadline overrun under policy='abort'."""


class HealthVerdict:
    """One step's health decision.  ``ok`` is True for a clean step;
    otherwise ``action`` ('skip' | 'rewind'), ``kind`` ('nonfinite' |
    'loss_spike') and ``culprit`` (the first offending array's name, or
    'loss') say what happened — aborts raise instead of returning."""

    __slots__ = ("ok", "action", "kind", "culprit", "loss")

    def __init__(self, ok: bool, action: str = "ok", kind: str = "",
                 culprit: str = "", loss: float = float("nan")) -> None:
        self.ok = ok
        self.action = action
        self.kind = kind
        self.culprit = culprit
        self.loss = loss

    def __repr__(self) -> str:
        if self.ok:
            return f"HealthVerdict(ok, loss={self.loss:g})"
        return (f"HealthVerdict({self.action}, kind={self.kind}, "
                f"culprit={self.culprit!r}, loss={self.loss:g})")


# ---------------------------------------------------------------------------
# fused numerics check (eager path): ONE compiled reduction over loss +
# every gradient, ONE small readback.  jax retraces per input signature
# and caches the executable, so steady-state training reuses one program.
# ---------------------------------------------------------------------------

_CHECK_FN = None


def fused_finite_check(loss: Any, arrays: Sequence[Any]) -> Any:
    """Device-side [any_bad, first_bad_index, loss_value] over ``loss``
    and ``arrays`` (index 0 is the loss; array i is index i+1).  Returns
    the un-fetched (3,) f32 device array — the caller owns the single
    readback."""
    global _CHECK_FN
    import jax
    import jax.numpy as jnp
    if _CHECK_FN is None:
        def _impl(loss_a, arrs):
            flags = [jnp.logical_not(jnp.all(jnp.isfinite(loss_a)))]
            for a in arrs:
                flags.append(jnp.logical_not(jnp.all(jnp.isfinite(a))))
            bad = jnp.stack(flags)
            lossv = jnp.mean(loss_a).astype(jnp.float32)
            return jnp.stack([bad.any().astype(jnp.float32),
                              jnp.argmax(bad).astype(jnp.float32),
                              lossv])
        _CHECK_FN = jax.jit(_impl)
    return _CHECK_FN(loss, tuple(arrays))


# ---------------------------------------------------------------------------
# hang watchdog
# ---------------------------------------------------------------------------

_LAST_DUMP: Dict[str, Optional[str]] = {"path": None}


def last_dump_path() -> Optional[str]:
    """Path of the most recent watchdog diagnostics dump (None if the
    watchdog never fired in this process)."""
    return _LAST_DUMP["path"]


def _write_dump(site: str, deadline_s: float, ctx: Dict[str, Any]) -> str:
    """All-thread stacks + a metrics snapshot, atomically written to the
    diagnostics dir.  This is the artifact an operator (or the chaos
    suite) reads to answer 'who is holding the job up'."""
    dirpath = str(getenv("MXNET_HEALTH_DIAG_DIR", "") or "") or os.getcwd()
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(
        dirpath,
        f"mxnet-health-dump-{os.getpid()}-{int(time.time() * 1e3)}-"
        f"{site.replace('.', '_')}.txt")
    lines = [
        "mxnet_tpu health watchdog diagnostics",
        f"site: {site}",
        f"deadline_s: {deadline_s}",
        f"context: {ctx}",
        f"time: {time.strftime('%Y-%m-%dT%H:%M:%S')}",
        f"pid: {os.getpid()}",
        "",
        "== all-thread stacks ==",
    ]
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        lines.append(f"-- thread {names.get(tid, '?')} (ident {tid}) --")
        lines.extend(l.rstrip("\n")
                     for l in traceback.format_stack(frame))
        lines.append("")
    lines.append("== active spans ==")
    try:
        from . import tracing as _tracing
        tree = _tracing.active_spans_tree()
        lines.extend(tree if tree else ["(no active spans)"])
    except Exception:   # noqa: BLE001 - diagnostics must never raise
        lines.append("(active spans unavailable)")
    lines.append("")
    lines.append("== metrics snapshot (non-zero series) ==")
    try:
        lines.append(json.dumps(_metrics._nonzero_summary(), indent=1))
    except Exception:   # noqa: BLE001 - diagnostics must never raise
        lines.append("(metrics snapshot unavailable)")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write("\n".join(lines) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _LAST_DUMP["path"] = path
    return path


class _WatchSection:
    __slots__ = ("watchdog", "site", "deadline_s", "deadline", "guard",
                 "ctx", "fired", "fire_done", "dump_path", "key")

    def __init__(self, wd: "HangWatchdog", site: str, deadline_s: float,
                 guard: Optional["HealthGuard"],
                 ctx: Dict[str, Any]) -> None:
        self.watchdog = wd
        self.site = site
        self.deadline_s = deadline_s
        self.guard = guard
        self.ctx = ctx
        self.fired = False
        self.fire_done = threading.Event()
        self.dump_path: Optional[str] = None
        self.key = None

    def __enter__(self) -> "_WatchSection":
        self.deadline = time.monotonic() + self.deadline_s
        self.watchdog._register(self)
        return self

    def __exit__(self, *exc: Any) -> None:
        self.watchdog._unregister(self)
        if self.fired and not any(exc) and self.guard is not None:
            # the watchdog thread may still be writing the dump the
            # escalation names — wait it out (bounded)
            self.fire_done.wait(10.0)
            # the section eventually completed: escalate per policy
            self.guard.note_hang(self.site, self.dump_path)


class HangWatchdog:
    """One daemon thread that fires diagnostics when a guarded section
    outlives its deadline.  Disarmed cost: ``watch`` returns a
    nullcontext when the deadline is 0."""

    def __init__(self) -> None:
        self._cv = threading.Condition()
        self._sections: Dict[int, _WatchSection] = {}
        self._seq = 0
        self._thread: Optional[threading.Thread] = None

    def watch(self, site: str, deadline_s: Optional[float] = None,
              guard: Optional["HealthGuard"] = None, **ctx: Any):
        """Context manager arming ``site`` for ``deadline_s`` seconds
        (default: ``MXNET_HEALTH_STEP_DEADLINE_S``; <=0 disarms)."""
        if deadline_s is None:
            deadline_s = float(getenv("MXNET_HEALTH_STEP_DEADLINE_S", 0.0))
        if not deadline_s or deadline_s <= 0:
            return contextlib.nullcontext()
        return _WatchSection(self, site, float(deadline_s), guard, ctx)

    # -- section registry ---------------------------------------------------
    def _register(self, sec: _WatchSection) -> None:
        with self._cv:
            self._seq += 1
            sec.key = self._seq
            self._sections[sec.key] = sec
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="mxnet-health-watchdog",
                    daemon=True)
                self._thread.start()
            self._cv.notify_all()

    def _unregister(self, sec: _WatchSection) -> None:
        with self._cv:
            self._sections.pop(sec.key, None)
            self._cv.notify_all()

    # -- the watcher thread -------------------------------------------------
    def _run(self) -> None:
        while True:
            fire: List[_WatchSection] = []
            with self._cv:
                now = time.monotonic()
                nxt: Optional[float] = None
                for sec in self._sections.values():
                    if sec.fired:
                        continue
                    if sec.deadline <= now:
                        sec.fired = True
                        fire.append(sec)
                    elif nxt is None or sec.deadline < nxt:
                        nxt = sec.deadline
                if not fire:
                    # park until the nearest deadline or a registry change
                    self._cv.wait(timeout=(None if nxt is None
                                           else max(0.005, nxt - now)))
            for sec in fire:
                self._fire(sec)

    def _fire(self, sec: _WatchSection) -> None:
        try:
            sec.dump_path = _write_dump(sec.site, sec.deadline_s, sec.ctx)
        except Exception:   # noqa: BLE001 - diagnostics must never kill
            sec.dump_path = None
        HEALTH_EVENTS.labels(kind="hang").inc()
        HEALTH_WATCHDOG_FIRES.labels(site=sec.site).inc()
        sec.fire_done.set()
        import logging
        logging.getLogger("mxnet_tpu.health").error(
            "watchdog: section %r exceeded its %.3gs deadline — "
            "all-thread stack dump at %s", sec.site, sec.deadline_s,
            sec.dump_path or "(dump failed)")


_WATCHDOG = HangWatchdog()


def watchdog() -> HangWatchdog:
    """The process-wide watchdog instance (shared by training loops,
    kvstore collectives, and the serving executor)."""
    return _WATCHDOG


def watch_section(site: str, deadline_s: Optional[float] = None,
                  guard: Optional["HealthGuard"] = None, **ctx: Any):
    """Arm the process watchdog around a with-block (module-level
    convenience used by kvstore_async and serving)."""
    return _WATCHDOG.watch(site, deadline_s=deadline_s, guard=guard,
                           **ctx)


# ---------------------------------------------------------------------------
# the guard
# ---------------------------------------------------------------------------

class HealthGuard:
    """Numerics sentry + recovery policy + watchdog handle for one
    training run.

    ::

        guard = HealthGuard(policy="skip")
        trainer.fit(batch_fn, steps, checkpoint_manager=mgr,
                    health_guard=guard)             # SPMDTrainer
        estimator.fit(data, batches=N, health_guard=guard)
        guard.install(gluon_trainer)                # hand-written loop

    Counters (``skips``, ``rewinds``, ``events``) are readable for
    assertions; the same seeded fault plan replays the identical
    decision sequence.
    """

    def __init__(self, policy: Optional[str] = None,
                 loss_spike: Optional[float] = None,
                 loss_window: Optional[int] = None,
                 max_skips: Optional[int] = None,
                 max_rewinds: Optional[int] = None,
                 step_deadline_s: Optional[float] = None) -> None:
        self.policy = (policy if policy is not None
                       else str(getenv("MXNET_HEALTH_POLICY", "skip")))
        if self.policy not in _POLICIES:
            raise MXNetError(
                f"unknown health policy {self.policy!r}; known: "
                f"{_POLICIES}")
        self.loss_spike = (float(loss_spike) if loss_spike is not None
                           else float(getenv("MXNET_HEALTH_LOSS_SPIKE",
                                             0.0)))
        self.loss_window = int(loss_window if loss_window is not None
                               else getenv("MXNET_HEALTH_LOSS_WINDOW", 20))
        self.max_skips = int(max_skips if max_skips is not None
                             else getenv("MXNET_HEALTH_MAX_SKIPS", 10))
        self.max_rewinds = int(max_rewinds if max_rewinds is not None
                               else getenv("MXNET_HEALTH_MAX_REWINDS", 2))
        self.step_deadline_s = (
            float(step_deadline_s) if step_deadline_s is not None
            else float(getenv("MXNET_HEALTH_STEP_DEADLINE_S", 0.0)))
        self.skips = 0
        self.rewinds = 0
        self.hangs = 0
        self.replay_salt = 0
        self.loss_ema: Optional[float] = None
        self._steps_seen = 0
        self._rewind_cb: Optional[Callable[[], Any]] = None
        self._pending_loss: Any = None
        self.last_verdict: Optional[HealthVerdict] = None
        self.last_hang_dump: Optional[str] = None

    # -- wiring --------------------------------------------------------------
    def set_rewind(self, cb: Optional[Callable[[], Any]]) -> None:
        """Attach the rewind action (normally
        ``lambda: manager.restore(trainer)``); without one, policy
        'rewind' degrades to 'skip'."""
        self._rewind_cb = cb

    def watch(self, site: str, **ctx: Any):
        """Arm the process watchdog for one guarded section with this
        guard's step deadline (and escalation policy).  The guard's
        resolved deadline is passed verbatim: an explicit
        ``step_deadline_s=0`` disarms even when the environment sets
        one (constructor arguments always beat the env)."""
        return _WATCHDOG.watch(site, deadline_s=self.step_deadline_s,
                               guard=self, **ctx)

    def install(self, trainer: Any) -> "HealthGuard":
        """Hook a gluon ``Trainer``: every ``step()`` runs the fused
        gradient sentry BEFORE the gradient reduction and optimizer
        update (after the same bulking donation barrier the update
        already takes), and a bad step is dropped per policy with AMP
        loss-scale decay.  Hooking ``_step_impl`` (not ``_update``)
        matters twice over: with ``update_on_kvstore`` the update runs
        server-side and ``_update`` never executes, and on the local
        path a NaN must be caught before ``allreduce_grads`` spreads it
        through the collective."""
        if getattr(trainer, "_health_guard", None) is self:
            return self
        orig_step = trainer._step_impl

        def _step_impl(batch_size: int,
                       ignore_stale_grad: bool = False) -> None:
            # ONE fused check covers the announced loss (note_loss)
            # AND every gradient — a single reduction, a single
            # readback per step
            loss, self._pending_loss = self._pending_loss, None
            verdict = self.check(loss=loss, grads_of=trainer)
            if verdict.ok:
                orig_step(batch_size, ignore_stale_grad)
                return
            if verdict.action == "rewind":
                self.do_rewind()
            self.apply_skip(trainer)

        trainer._step_impl = _step_impl
        trainer._health_guard = self
        return self

    def note_loss(self, loss: Any) -> None:
        """Announce the step's loss so the next installed-trainer check
        folds it into the same fused reduction as the gradients
        (``Estimator.fit`` calls this instead of running a separate
        loss-only check — one readback per step, not two)."""
        self._pending_loss = loss

    # -- the sentry ----------------------------------------------------------
    def check(self, loss: Any = None, grads: Optional[Sequence[Any]] = None,
              names: Optional[Sequence[str]] = None,
              grads_of: Any = None) -> HealthVerdict:
        """Run the fused numerics check over ``loss`` and the gradients
        and decide.  ``grads_of`` extracts fresh gradients (+ names)
        from a gluon Trainer.  Raises :class:`HealthError` on policy
        'abort' or an exhausted budget."""
        import numpy as onp
        import jax.numpy as jnp
        t0 = time.perf_counter()
        if grads_of is not None:
            grads, names = [], []
            for p in grads_of._params:
                if p.grad_req == "null" or not p.is_initialized:
                    continue
                w = p.data()
                if w.grad is not None and w._fresh_grad:
                    grads.append(w.grad)
                    names.append(p.name)
        grads = list(grads or ())
        # the bulking donation barrier the optimizer update takes anyway
        # — flushing HERE (instead of letting the grad reads flush as
        # host reads) keeps the total segment count identical with and
        # without the guard.  Only the CALLING thread's segment: the
        # optimizer's own barrier is now the targeted flush_holding, so
        # a global flush here would cut unrelated threads (the prefetch
        # thread's in-build segment) that the update path leaves alone
        from . import bulk as _bulk
        _bulk.flush_current("mutation")
        from .ndarray.ndarray import NDArray
        arrs = [g._data if isinstance(g, NDArray) else g for g in grads]
        has_loss = loss is not None
        loss_a = (loss._data if isinstance(loss, NDArray)
                  else jnp.zeros((), jnp.float32) if loss is None
                  else jnp.asarray(loss))
        vec = onp.asarray(fused_finite_check(loss_a, arrs))
        HEALTH_CHECK_SECONDS.observe(time.perf_counter() - t0)
        return self._decide(bad=bool(vec[0] > 0), first=int(vec[1]),
                            loss_value=float(vec[2]), names=names,
                            has_loss=has_loss)

    def check_device(self, health_vec: Any,
                     names: Optional[Sequence[str]] = None
                     ) -> HealthVerdict:
        """Decide from a device-resident [any_bad, first_index, loss]
        vector (``SPMDTrainer``'s in-program sentry output — this
        fetch is the step's single scalar readback).

        On this path the in-program gate covers FINITENESS only, so a
        finite loss spike's update has already landed by the time the
        verdict is read — a spike cannot be "skipped" retroactively
        (``spike_droppable=False``): under policy='skip' it is
        recorded as an advisory event (action='note'); use 'rewind' or
        'abort' to enforce divergence recovery on the SPMD path."""
        import numpy as onp
        t0 = time.perf_counter()
        vec = onp.asarray(health_vec)
        HEALTH_CHECK_SECONDS.observe(time.perf_counter() - t0)
        return self._decide(bad=bool(vec[0] > 0), first=int(vec[1]),
                            loss_value=float(vec[2]), names=names,
                            has_loss=True, spike_droppable=False)

    # -- decisions -----------------------------------------------------------
    def _decide(self, bad: bool, first: int, loss_value: float,
                names: Optional[Sequence[str]], has_loss: bool,
                spike_droppable: bool = True) -> HealthVerdict:
        if bad:
            if has_loss and first == 0:
                culprit = "loss"
            else:
                gi = first - 1      # vector index 0 is always the loss
                culprit = (names[gi] if names and 0 <= gi < len(names)
                           else f"gradient[{gi}]")
            return self._recover("nonfinite", culprit, loss_value)
        if has_loss:
            spiked = (self.loss_spike > 0
                      and self.loss_ema is not None
                      and self._steps_seen >= self.loss_window
                      and loss_value > self.loss_spike * abs(self.loss_ema))
            if spiked:
                if not spike_droppable and self.policy == "skip":
                    # the update already landed (SPMD deferred path):
                    # claiming a "skip" would lie — record the event as
                    # advisory and keep the spiked value out of the EMA
                    HEALTH_EVENTS.labels(kind="loss_spike").inc()
                    v = HealthVerdict(False, action="note",
                                      kind="loss_spike", culprit="loss",
                                      loss=loss_value)
                    self.last_verdict = v
                    return v
                return self._recover("loss_spike", "loss", loss_value)
            # only accepted values feed the EMA: a diverging tail must
            # not drag the baseline up after itself
            alpha = 2.0 / (self.loss_window + 1.0)
            self.loss_ema = (loss_value if self.loss_ema is None
                             else (1 - alpha) * self.loss_ema
                             + alpha * loss_value)
            self._steps_seen += 1
            HEALTH_LOSS_EMA.set(self.loss_ema)
        v = HealthVerdict(True, loss=loss_value)
        self.last_verdict = v
        return v

    def _recover(self, kind: str, culprit: str,
                 loss_value: float) -> HealthVerdict:
        HEALTH_EVENTS.labels(kind=kind).inc()
        detail = (f"non-finite values first appeared in {culprit!r}"
                  if kind == "nonfinite" else
                  f"loss {loss_value:g} spiked past "
                  f"{self.loss_spike:g}x the EMA {self.loss_ema:g}")
        if self.policy == "abort":
            raise HealthError(
                f"training health abort ({kind}): {detail} "
                "[MXNET_HEALTH_POLICY=abort]")
        action = self.policy
        if action == "rewind" and self._rewind_cb is None:
            action = "skip"          # nothing to rewind to — degrade
        if action == "rewind":
            if self.rewinds >= self.max_rewinds:
                raise HealthError(
                    f"training health abort: {detail}, and the rewind "
                    f"budget ({self.max_rewinds}, "
                    "MXNET_HEALTH_MAX_REWINDS) is exhausted")
            # budget charged at decide time (deterministic replay); the
            # metric counts in do_rewind, which refunds the charge when
            # there was nothing to restore to
            self.rewinds += 1
        else:
            if self.skips >= self.max_skips:
                raise HealthError(
                    f"training health abort: {detail}, and the skip "
                    f"budget ({self.max_skips}, MXNET_HEALTH_MAX_SKIPS) "
                    "is exhausted")
            self.skips += 1
            HEALTH_SKIPS.inc()
        v = HealthVerdict(False, action=action, kind=kind,
                          culprit=culprit, loss=loss_value)
        self.last_verdict = v
        return v

    # -- recovery actions ----------------------------------------------------
    def apply_skip(self, trainer: Any) -> None:
        """Zero the pending update on a gluon trainer: mark every fresh
        gradient consumed and decay an attached AMP loss scale."""
        for p in getattr(trainer, "_params", ()):
            if p.is_initialized and p.data().grad is not None:
                p.data()._fresh_grad = False
        scaler = getattr(trainer, "_amp_scaler", None)
        if scaler is not None:
            scaler.decay()

    def do_rewind(self) -> Any:
        """Run the attached rewind action and perturb the replay salt
        (``batch_fn(step, salt=...)`` consumers re-order their data).
        Returns what the rewind action returned — ``None`` means the
        checkpoint directory was empty (``restore``'s fresh-start
        contract): nothing was restored, so the rewind charge is
        refunded and a SKIP is accounted instead (a bad run before its
        first checkpoint must not burn the rewind budget on no-ops)."""
        if self._rewind_cb is None:
            raise MXNetError("no rewind action attached "
                             "(HealthGuard.set_rewind)")
        result = self._rewind_cb()
        if result is None:
            self.rewinds = max(0, self.rewinds - 1)
            if self.skips >= self.max_skips:
                raise HealthError(
                    "training health abort: rewind found no checkpoint "
                    f"to restore and the skip budget ({self.max_skips},"
                    " MXNET_HEALTH_MAX_SKIPS) is exhausted")
            self.skips += 1
            HEALTH_SKIPS.inc()
            return None
        self.replay_salt += 1
        # the rewound stretch replays: its EMA state belongs to the
        # abandoned trajectory
        self.loss_ema = None
        self._steps_seen = 0
        HEALTH_REWINDS.inc()
        return result

    def note_hang(self, site: str, dump_path: Optional[str]) -> None:
        """Watchdog escalation hook: the guarded section finished after
        its deadline.  policy='abort' raises; other policies keep the
        event (already counted) as diagnostics."""
        self.hangs += 1
        self.last_hang_dump = dump_path
        if self.policy == "abort":
            raise HealthError(
                f"training health abort (hang): section {site!r} "
                f"exceeded its {self.step_deadline_s:g}s deadline "
                f"(MXNET_HEALTH_STEP_DEADLINE_S); stack dump: "
                f"{dump_path or '(dump failed)'}")
