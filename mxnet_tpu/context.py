"""Device context: ``cpu()`` / ``tpu()`` (with ``gpu()`` as a compat alias).

Reference parity (leezu/mxnet): ``python/mxnet/context.py`` (``Context``,
``mx.cpu()``, ``mx.gpu()``, ``current_context``, ``num_gpus``). The reference
pins NDArrays to CUDA devices; here a Context resolves to a ``jax.Device``
and placement is via ``jax.device_put``. ``gpu(i)`` aliases the accelerator
(TPU) so reference-era scripts keep working.
"""
from __future__ import annotations

import threading
from typing import List, Optional

import jax

from .base import register_env

__all__ = [
    "Context", "cpu", "gpu", "tpu", "current_context", "num_gpus", "num_tpus",
    "cpu_pinned",
]

register_env(
    "MXNET_DEFAULT_CONTEXT", "auto",
    "Implicit default context when no `with ctx:` scope is active: "
    "'auto' (accelerator when one exists, else cpu — the reference's "
    "eager-on-accelerator default), 'cpu', 'tpu', or 'gpu' (tpu alias). "
    "Unrecognized values raise. Resolved once per process at first use.")

_ACCEL_TYPES = ("tpu", "gpu", "cuda", "rocm", "axon")


def _accel_devices() -> List["jax.Device"]:
    """Process-local non-CPU jax devices (TPU chips; empty on CPU-only
    hosts). Local, not global: in a multi-process job eager arrays must
    land on THIS process's chips — other processes' devices are not
    addressable (global placement goes through mesh shardings)."""
    try:
        devs = jax.local_devices()
    except RuntimeError:
        return []
    return [d for d in devs if d.platform != "cpu"]


class Context:
    """A device context, hashable and comparable.

    Parameters
    ----------
    device_type : str
        One of ``'cpu'``, ``'tpu'``, ``'gpu'`` (alias of tpu),
        ``'cpu_pinned'``, ``'cpu_shared'`` (aliases of cpu).
    device_id : int
        Index within devices of that type.
    """

    devtype2str = {1: "cpu", 2: "tpu", 3: "cpu_pinned", 5: "cpu_shared"}
    devstr2type = {"cpu": 1, "tpu": 2, "gpu": 2, "cuda": 2,
                   "cpu_pinned": 3, "cpu_shared": 5}

    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0) -> None:
        if device_type not in self.devstr2type:
            raise ValueError(f"unknown device type {device_type!r}")
        self.device_typeid = self.devstr2type[device_type]
        self.device_id = device_id

    @property
    def device_type(self) -> str:
        return self.devtype2str[self.device_typeid]

    # -- jax resolution ----------------------------------------------------
    @property
    def jax_device(self) -> "jax.Device":
        """Resolve to the concrete ``jax.Device`` backing this context."""
        if self.device_typeid == 2:
            accel = _accel_devices()
            if not accel:
                # CPU fallback keeps ctx=tpu code runnable on CPU-only hosts
                # (mirrors the reference's graceful "GPU not enabled" UX but
                # non-fatally, since XLA:CPU runs the same programs).
                cpus = [d for d in jax.local_devices()
                        if d.platform == "cpu"]
                return cpus[min(self.device_id, len(cpus) - 1)]
            return accel[self.device_id % len(accel)]
        if _has_cpu_backend():
            cpus = [d for d in jax.local_devices(backend="cpu")]
        else:
            cpus = jax.local_devices()
        return cpus[min(self.device_id, len(cpus) - 1)]

    # -- equality / hashing ------------------------------------------------
    def __eq__(self, other: object) -> bool:
        return (isinstance(other, Context)
                and self.device_typeid == other.device_typeid
                and self.device_id == other.device_id)

    def __hash__(self) -> int:
        return hash((self.device_typeid, self.device_id))

    def __repr__(self) -> str:
        return f"{self.device_type}({self.device_id})"

    def __str__(self) -> str:
        return self.__repr__()

    def __enter__(self) -> "Context":
        if not hasattr(Context._default_ctx, "stack"):
            Context._default_ctx.stack = []
        Context._default_ctx.stack.append(self)
        return self

    def __exit__(self, *exc: object) -> None:
        Context._default_ctx.stack.pop()

    @classmethod
    def default_ctx(cls) -> "Context":
        stack = getattr(cls._default_ctx, "stack", None)
        if stack:
            return stack[-1]
        return _implicit_default()


# Resolved once per process (device discovery initializes the backend).
_IMPLICIT = {"ctx": None}


def _implicit_default() -> "Context":
    """The context used when no ``with ctx:`` scope is active.

    r3 (VERDICT r2 item 8): when an accelerator backend exists, eager
    work lands ON THE CHIP by default — the reference's defining
    eager-on-accelerator experience, no ``with tpu():`` ceremony.
    ``MXNET_DEFAULT_CONTEXT=cpu`` opts out (e.g. to keep a shared chip
    free while preparing data); ``auto`` (default) picks the
    accelerator when present, else cpu.
    """
    if _IMPLICIT["ctx"] is None:
        import os
        pref = os.environ.get("MXNET_DEFAULT_CONTEXT", "auto").strip().lower()
        if pref == "cpu":
            _IMPLICIT["ctx"] = cpu()
        elif pref in ("tpu", "gpu"):
            _IMPLICIT["ctx"] = tpu()
        elif pref == "auto":
            _IMPLICIT["ctx"] = tpu() if _accel_devices() else cpu()
        else:
            # a typo'd opt-out must NOT silently land work on a shared
            # chip — fail loudly
            raise ValueError(
                f"MXNET_DEFAULT_CONTEXT={pref!r} not recognized; use "
                "'auto', 'cpu', 'tpu', or 'gpu' (tpu alias)")
    return _IMPLICIT["ctx"]


def _has_cpu_backend() -> bool:
    try:
        jax.devices("cpu")
        return True
    except RuntimeError:
        return False


def cpu(device_id: int = 0) -> Context:
    """Return a CPU context (reference: ``mx.cpu()``)."""
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    """Pinned-memory CPU context; alias of cpu under XLA (no pinned pools)."""
    return Context("cpu_pinned", device_id)


def tpu(device_id: int = 0) -> Context:
    """Return a TPU context — the accelerator context of this framework."""
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Compat alias for :func:`tpu` so reference-era scripts run unchanged."""
    return Context("gpu", device_id)


def num_tpus() -> int:
    """Number of visible TPU chips (reference analog: ``mx.context.num_gpus``)."""
    return len(_accel_devices())


def num_gpus() -> int:
    """Compat alias of :func:`num_tpus`."""
    return num_tpus()


def current_context() -> Context:
    """The default context: innermost ``with ctx:`` scope, else the
    implicit default (accelerator when present — MXNET_DEFAULT_CONTEXT
    overrides)."""
    return Context.default_ctx()
