"""Evaluation metrics (reference: ``python/mxnet/metric.py``).

``EvalMetric`` registry with the standard zoo: Accuracy, TopKAccuracy, F1,
MAE/MSE/RMSE, CrossEntropy, NegativeLogLikelihood, Perplexity,
PearsonCorrelation, Loss, Composite, custom-fn via ``np`` — same
``update(labels, preds)`` / ``get()`` protocol consumed by fit loops.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MAE", "MSE",
           "RMSE", "CrossEntropy", "NegativeLogLikelihood", "Perplexity",
           "PearsonCorrelation", "Loss", "CompositeEvalMetric",
           "CustomMetric", "create", "check_label_shapes", "np"]

_METRIC_REGISTRY: Dict[str, type] = {}


def _register(cls: type) -> type:
    _METRIC_REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(metric: Union[str, Callable, "EvalMetric", Sequence],
           *args: Any, **kwargs: Any) -> "EvalMetric":
    """Create a metric from name/callable/list (``mx.metric.create``)."""
    if isinstance(metric, EvalMetric):
        return metric
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m))
        return composite
    name = metric.lower()
    aliases = {"acc": "accuracy", "ce": "crossentropy", "nll_loss":
               "negativeloglikelihood", "top_k_accuracy": "topkaccuracy"}
    name = aliases.get(name, name)
    if name not in _METRIC_REGISTRY:
        raise MXNetError(f"unknown metric {metric!r}; "
                         f"known: {sorted(_METRIC_REGISTRY)}")
    return _METRIC_REGISTRY[name](*args, **kwargs)


def check_label_shapes(labels: Sequence, preds: Sequence,
                       wrap: bool = False, shape: bool = False):
    if wrap:
        if isinstance(labels, (NDArray, _np.ndarray)):
            labels = [labels]
        if isinstance(preds, (NDArray, _np.ndarray)):
            preds = [preds]
    if len(labels) != len(preds):
        raise MXNetError(f"labels/preds count mismatch: "
                         f"{len(labels)} vs {len(preds)}")
    return labels, preds


def _to_np(x: Any) -> _np.ndarray:
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


class EvalMetric:
    def __init__(self, name: str, output_names: Optional[Sequence[str]] = None,
                 label_names: Optional[Sequence[str]] = None,
                 **kwargs: Any) -> None:
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self.reset()

    def reset(self) -> None:
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels: Any, preds: Any) -> None:
        raise NotImplementedError

    def get(self) -> Tuple[str, float]:
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self) -> List[Tuple[str, float]]:
        name, value = self.get()
        if not isinstance(name, list):
            return [(name, value)]
        return list(zip(name, value))

    def __str__(self) -> str:
        return f"EvalMetric: {dict(self.get_name_value())}"


@_register
class Accuracy(EvalMetric):
    def __init__(self, axis: int = 1, name: str = "accuracy",
                 **kwargs: Any) -> None:
        self.axis = axis
        super().__init__(name, **kwargs)

    def update(self, labels, preds) -> None:
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _to_np(label)
            pred = _to_np(pred)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype("int32").reshape(-1)
            label = label.astype("int32").reshape(-1)
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@_register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k: int = 1, name: str = "top_k_accuracy",
                 **kwargs: Any) -> None:
        self.top_k = top_k
        super().__init__(f"{name}_{top_k}", **kwargs)

    def update(self, labels, preds) -> None:
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _to_np(label).astype("int32").reshape(-1)
            pred = _to_np(pred)
            topk = _np.argsort(-pred, axis=-1)[..., :self.top_k]
            topk = topk.reshape(len(label), self.top_k)
            self.sum_metric += (topk == label[:, None]).any(axis=1).sum()
            self.num_inst += len(label)


@_register
class F1(EvalMetric):
    def __init__(self, name: str = "f1", average: str = "macro",
                 **kwargs: Any) -> None:
        self.average = average
        super().__init__(name, **kwargs)

    def reset(self) -> None:
        self.tp = self.fp = self.fn = 0
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds) -> None:
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _to_np(label).reshape(-1).astype("int32")
            pred = _to_np(pred)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.reshape(-1).astype("int32")
            self.tp += int(((pred == 1) & (label == 1)).sum())
            self.fp += int(((pred == 1) & (label == 0)).sum())
            self.fn += int(((pred == 0) & (label == 1)).sum())
            self.num_inst += 1

    def get(self) -> Tuple[str, float]:
        prec = self.tp / (self.tp + self.fp) if self.tp + self.fp else 0.0
        rec = self.tp / (self.tp + self.fn) if self.tp + self.fn else 0.0
        f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        return self.name, f1


@_register
class MAE(EvalMetric):
    def __init__(self, name: str = "mae", **kwargs: Any) -> None:
        super().__init__(name, **kwargs)

    def update(self, labels, preds) -> None:
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label, pred = _to_np(label), _to_np(pred)
            self.sum_metric += _np.abs(label.reshape(pred.shape) - pred).mean() \
                * len(label)
            self.num_inst += len(label)


@_register
class MSE(EvalMetric):
    def __init__(self, name: str = "mse", **kwargs: Any) -> None:
        super().__init__(name, **kwargs)

    def update(self, labels, preds) -> None:
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label, pred = _to_np(label), _to_np(pred)
            self.sum_metric += ((label.reshape(pred.shape) - pred) ** 2).mean() \
                * len(label)
            self.num_inst += len(label)


@_register
class RMSE(MSE):
    def __init__(self, name: str = "rmse", **kwargs: Any) -> None:
        super().__init__(name, **kwargs)

    def get(self) -> Tuple[str, float]:
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, math.sqrt(self.sum_metric / self.num_inst)


@_register
class CrossEntropy(EvalMetric):
    def __init__(self, eps: float = 1e-12, name: str = "cross-entropy",
                 **kwargs: Any) -> None:
        self.eps = eps
        super().__init__(name, **kwargs)

    def update(self, labels, preds) -> None:
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _to_np(label).astype("int32").reshape(-1)
            pred = _to_np(pred).reshape(len(label), -1)
            prob = pred[_np.arange(len(label)), label]
            self.sum_metric += (-_np.log(prob + self.eps)).sum()
            self.num_inst += len(label)


@_register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps: float = 1e-12, name: str = "nll-loss",
                 **kwargs: Any) -> None:
        super().__init__(eps=eps, name=name, **kwargs)


@_register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label: Optional[int] = None, axis: int = -1,
                 name: str = "perplexity", **kwargs: Any) -> None:
        self.ignore_label = ignore_label
        self.axis = axis
        super().__init__(name, **kwargs)

    def update(self, labels, preds) -> None:
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _to_np(label).astype("int32").reshape(-1)
            pred = _to_np(pred).reshape(len(label), -1)
            prob = pred[_np.arange(len(label)), label]
            if self.ignore_label is not None:
                ignore = label == self.ignore_label
                prob = prob[~ignore]
            self.sum_metric += (-_np.log(_np.maximum(prob, 1e-10))).sum()
            self.num_inst += len(prob)

    def get(self) -> Tuple[str, float]:
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, math.exp(self.sum_metric / self.num_inst)


@_register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name: str = "pearsonr", **kwargs: Any) -> None:
        super().__init__(name, **kwargs)

    def reset(self) -> None:
        self._labels: List[_np.ndarray] = []
        self._preds: List[_np.ndarray] = []
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds) -> None:
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            self._labels.append(_to_np(label).reshape(-1))
            self._preds.append(_to_np(pred).reshape(-1))
            self.num_inst += 1

    def get(self) -> Tuple[str, float]:
        if not self._labels:
            return self.name, float("nan")
        l = _np.concatenate(self._labels)
        p = _np.concatenate(self._preds)
        return self.name, float(_np.corrcoef(l, p)[0, 1])


@_register
class Loss(EvalMetric):
    """Running mean of loss values (reference: metric.Loss)."""

    def __init__(self, name: str = "loss", **kwargs: Any) -> None:
        super().__init__(name, **kwargs)

    def update(self, _labels, preds) -> None:
        if isinstance(preds, (NDArray, _np.ndarray)):
            preds = [preds]
        for pred in preds:
            pred = _to_np(pred)
            self.sum_metric += pred.sum()
            self.num_inst += pred.size


@_register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics: Optional[Sequence[EvalMetric]] = None,
                 name: str = "composite", **kwargs: Any) -> None:
        super().__init__(name, **kwargs)
        self.metrics = list(metrics) if metrics else []

    def add(self, metric: EvalMetric) -> None:
        self.metrics.append(create(metric))

    def reset(self) -> None:
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds) -> None:
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values


class CustomMetric(EvalMetric):
    def __init__(self, feval: Callable, name: str = "custom",
                 allow_extra_outputs: bool = False, **kwargs: Any) -> None:
        self._feval = feval
        super().__init__(f"custom({name})", **kwargs)

    def update(self, labels, preds) -> None:
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            val = self._feval(_to_np(label), _to_np(pred))
            if isinstance(val, tuple):
                s, n = val
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += val
                self.num_inst += 1


def np(numpy_feval: Callable, name: Optional[str] = None,
       allow_extra_outputs: bool = False) -> CustomMetric:
    """Wrap a numpy feval into a metric (``mx.metric.np``)."""
    return CustomMetric(numpy_feval, name or numpy_feval.__name__,
                        allow_extra_outputs)
