"""Checkpoint helpers (reference: ``python/mxnet/model.py`` —
``save_checkpoint``/``load_checkpoint``: per-epoch params + architecture).

The reference saved ``prefix-symbol.json`` + ``prefix-%04d.params`` with
``arg:``/``aux:`` key prefixes; this build keeps the same file naming and
key-prefix convention over the mxnet_tpu ``.params`` container so Module
checkpoints round-trip by name.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

from .base import MXNetError
from .ndarray.ndarray import NDArray
from .ndarray_io import load_params, save_params

__all__ = ["save_checkpoint", "load_checkpoint", "BatchEndParam"]


class BatchEndParam:
    """Carrier passed to batch/epoch callbacks (reference namedtuple)."""

    def __init__(self, epoch: int, nbatch: int, eval_metric: Any,
                 locals: Any = None) -> None:  # noqa: A002
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


def save_checkpoint(prefix: str, epoch: int, symbol: Any,
                    arg_params: Dict[str, NDArray],
                    aux_params: Dict[str, NDArray]) -> None:
    """Save ``prefix-symbol.json`` (architecture metadata) +
    ``prefix-{epoch:04d}.params`` (arg:/aux:-prefixed tensors)."""
    if symbol is not None:
        meta = {"framework": "mxnet_tpu", "kind": "module_checkpoint",
                "block": type(symbol).__name__}
        with open(f"{prefix}-symbol.json", "w") as f:
            json.dump(meta, f)
    payload = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    payload.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    save_params(f"{prefix}-{epoch:04d}.params", payload)


def load_checkpoint(prefix: str, epoch: int
                    ) -> Tuple[Optional[dict], Dict[str, NDArray],
                               Dict[str, NDArray]]:
    """Load a checkpoint; returns (symbol_meta, arg_params, aux_params)."""
    sym_meta = None
    sym_file = f"{prefix}-symbol.json"
    if os.path.exists(sym_file):
        with open(sym_file) as f:
            sym_meta = json.load(f)
    fname = f"{prefix}-{epoch:04d}.params"
    if not os.path.exists(fname):
        raise MXNetError(f"checkpoint {fname} does not exist")
    loaded = load_params(fname)
    arg_params: Dict[str, NDArray] = {}
    aux_params: Dict[str, NDArray] = {}
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return sym_meta, arg_params, aux_params
