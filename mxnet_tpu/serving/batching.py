"""Dynamic micro-batching: bucket policy, bounded queue, load shedding.

The queueing model is the classic serving triad (see PAPERS.md: the
Gemma-on-TPU serving comparison — the wins come from batching and from
not recompiling):

* requests enter a BOUNDED queue; a full queue sheds the newcomer
  immediately (fail fast beats queue collapse),
* the batcher flushes a batch when a bucket fills OR the oldest request
  has waited ``MXNET_SERVING_BATCH_TIMEOUT_MS``,
* a request whose deadline passed while queued is shed at dequeue time
  (its client already gave up; running it would tax everyone behind it).

Shed requests fail with :class:`OverloadError` — a structured error the
HTTP front end maps to 429 + Retry-After, never a crash.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError, getenv, register_env
from .. import metrics as _metrics
from .. import tracing as _tracing

__all__ = ["BucketPolicy", "DynamicBatcher", "OverloadError", "Request",
           "SlotScheduler"]

register_env("MXNET_SERVING_MAX_BATCH", 32,
             "Largest micro-batch the serving batcher assembles (also the "
             "top batch bucket when no explicit bucket list is given).")
register_env("MXNET_SERVING_BATCH_TIMEOUT_MS", 5,
             "Micro-batching window: a queued request is batched with "
             "arrivals for at most this long before the batch flushes "
             "partially full. 0 flushes immediately (batch-1 unless "
             "requests are already queued).")
register_env("MXNET_SERVING_QUEUE_LIMIT", 256,
             "Bound on queued serving requests: past it, new requests are "
             "shed immediately with a structured OverloadError (429 on "
             "the HTTP front end) instead of growing the queue without "
             "bound.")
register_env("MXNET_SERVING_DEADLINE_MS", 0,
             "Default per-request serving deadline: a request still queued "
             "after this long is shed rather than served late. 0 (default) "
             "disables; per-request deadline_ms overrides.")


class OverloadError(MXNetError):
    """A request was shed by the serving layer (NOT a server fault).

    ``reason`` is ``"queue_full"`` (shed at submit: the bounded queue is
    at ``MXNET_SERVING_QUEUE_LIMIT``), ``"deadline"`` (shed at dequeue:
    the request's deadline passed while it waited), ``"draining"``
    (shed at submit: the process received SIGTERM and is finishing
    resident work before exiting — retry against another replica), or
    ``"restarting"`` (every worker replica is mid-restart; retry after
    the backoff).  ``retry_after_ms`` is a backoff hint derived from
    the current queue depth.
    """

    def __init__(self, reason: str, queue_depth: int = 0,
                 retry_after_ms: float = 0.0) -> None:
        self.reason = reason
        self.queue_depth = queue_depth
        self.retry_after_ms = retry_after_ms
        super().__init__(
            f"request shed ({reason}); queue_depth={queue_depth} "
            f"retry_after_ms={retry_after_ms:.0f}")

    def to_json(self) -> Dict[str, Any]:
        return {"error": "overloaded", "reason": self.reason,
                "queue_depth": self.queue_depth,
                "retry_after_ms": round(self.retry_after_ms, 1)}


def _pow2_buckets(max_batch: int) -> Tuple[int, ...]:
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(out)


class BucketPolicy:
    """Pad-to-bucket shape policy: bounds the compiled-executable count.

    Every batch the server runs has a shape drawn from the finite grid
    ``batch_buckets x length_buckets`` — a mixed-shape request stream
    compiles at most ``len(batch_buckets) * len(length_buckets)``
    executables (all warmable at startup) instead of one per distinct
    traffic shape.

    * ``batch_buckets`` — allowed batch sizes, e.g. ``(1, 2, 4, 8)``;
      a batch of n real requests pads (by repeating its first sample —
      never zeros, so no NaN-path surprises) up to the smallest bucket
      >= n.  Padded rows are sliced off the outputs: EXACT.
    * ``pad_axis``/``length_buckets`` — opt-in variable-length support:
      each sample's ``pad_axis`` dim (on the FIRST model input) rounds
      up to a length bucket, padded with ``pad_value``.  Only sound for
      models insensitive to trailing padding (masked attention, padded
      vocab ids, ...) — which is why it is off by default.  Samples
      longer than the top bucket are REJECTED (an unbounded shape would
      reopen the compile hole the policy exists to close).
    """

    def __init__(self, max_batch: Optional[int] = None,
                 batch_buckets: Optional[Sequence[int]] = None,
                 pad_axis: Optional[int] = None,
                 length_buckets: Optional[Sequence[int]] = None,
                 pad_value: float = 0.0) -> None:
        if batch_buckets is None:
            if max_batch is None:
                max_batch = int(getenv("MXNET_SERVING_MAX_BATCH", 32))
            batch_buckets = _pow2_buckets(int(max_batch))
        self.batch_buckets = tuple(sorted({int(b) for b in batch_buckets}))
        if not self.batch_buckets or self.batch_buckets[0] < 1:
            raise MXNetError(f"bad batch_buckets {batch_buckets!r}")
        self.max_batch = self.batch_buckets[-1]
        if (length_buckets is None) != (pad_axis is None):
            raise MXNetError("pad_axis and length_buckets go together")
        self.pad_axis = pad_axis
        self.length_buckets = (tuple(sorted({int(b) for b in
                                             length_buckets}))
                               if length_buckets is not None else None)
        self.pad_value = pad_value

    def n_buckets(self) -> int:
        return len(self.batch_buckets) * (len(self.length_buckets)
                                          if self.length_buckets else 1)

    def round_batch(self, n: int) -> int:
        """Smallest batch bucket >= n (n must not exceed max_batch)."""
        for b in self.batch_buckets:
            if b >= n:
                return b
        raise MXNetError(f"batch {n} exceeds top bucket {self.max_batch}")

    def _round_length(self, length: int) -> int:
        for b in self.length_buckets:
            if b >= length:
                return b
        raise MXNetError(
            f"sample length {length} exceeds the top length bucket "
            f"{self.length_buckets[-1]}; longer requests must be "
            f"rejected, or the executable count becomes unbounded")

    def bucket_key(self, sample: Sequence[_np.ndarray]
                   ) -> Tuple[Tuple[Tuple[int, ...], str], ...]:
        """The padded (shape, dtype) per input — batches only ever mix
        requests with the same key."""
        key = []
        for i, a in enumerate(sample):
            shape = list(a.shape)
            if i == 0 and self.pad_axis is not None:
                shape[self.pad_axis] = self._round_length(
                    shape[self.pad_axis])
            key.append((tuple(shape), str(a.dtype)))
        return tuple(key)

    def _pad_sample(self, a: _np.ndarray,
                    shape: Tuple[int, ...]) -> _np.ndarray:
        if tuple(a.shape) == shape:
            return a
        pad = [(0, t - s) for s, t in zip(a.shape, shape)]
        return _np.pad(a, pad, constant_values=self.pad_value)

    def assemble(self, samples: List[Sequence[_np.ndarray]],
                 key: Tuple[Tuple[Tuple[int, ...], str], ...]
                 ) -> Tuple[List[_np.ndarray], int]:
        """Stack ``samples`` (all sharing ``key``) into bucket-padded
        batch arrays; returns ``(arrays, padded_batch_size)``.  Padding
        rows repeat the first sample."""
        n = len(samples)
        nb = self.round_batch(n)
        out = []
        for i, (shape, dtype) in enumerate(key):
            rows = [self._pad_sample(_np.asarray(s[i]), shape)
                    for s in samples]
            rows.extend([rows[0]] * (nb - n))
            out.append(_np.stack(rows, axis=0).astype(dtype, copy=False))
        return out, nb

    def warmup_signatures(self, sample_signature: Sequence[
            Tuple[Tuple[int, ...], Any]]) -> List[List[Tuple[
                Tuple[int, ...], Any]]]:
        """Every batched input signature the policy can produce, for
        startup pre-compilation.  ``sample_signature`` is per-input
        (shape_without_batch, dtype)."""
        lengths = (self.length_buckets if self.length_buckets is not None
                   else [None])
        sigs = []
        for nb in self.batch_buckets:
            for lb in lengths:
                sig = []
                for i, (shape, dtype) in enumerate(sample_signature):
                    shape = list(shape)
                    if i == 0 and lb is not None:
                        shape[self.pad_axis] = lb
                    sig.append(((nb,) + tuple(shape), dtype))
                sigs.append(sig)
        return sigs


# ---------------------------------------------------------------------------
# Request + queue
# ---------------------------------------------------------------------------

# serving metric families (eager, like the core families in metrics.py)
QUEUE_DEPTH = _metrics.gauge(
    "mxnet_serving_queue_depth",
    "Requests currently waiting in the serving batcher queue.")
QUEUE_WAIT_SECONDS = _metrics.histogram(
    "mxnet_serving_queue_wait_seconds",
    "Per-request wait from submit to batch assembly.")
BATCH_SIZE = _metrics.histogram(
    "mxnet_serving_batch_size",
    "Real (pre-padding) request count per assembled serving batch.",
    buckets=_metrics.exponential_buckets(1, 2, 11))
SHED_TOTAL = _metrics.counter(
    "mxnet_serving_shed_total",
    "Requests shed by the serving layer, by reason (queue_full at "
    "submit; deadline at dequeue).", labels=("reason",))
REQUESTS_TOTAL = _metrics.counter(
    "mxnet_serving_requests_total",
    "Serving requests by terminal status (ok / shed / error).",
    labels=("status",))
INFER_SECONDS = _metrics.histogram(
    "mxnet_serving_inference_seconds",
    "Wall time of one batched model execution (padded batch).")
BUCKET_COMPILES = _metrics.counter(
    "mxnet_serving_bucket_compiles_total",
    "First-time executions per padded batch signature — each is one "
    "compiled executable; bounded by the bucket grid.",
    labels=("bucket",))


class Request:
    """One queued inference request: the sample (tuple of per-input
    arrays WITHOUT the batch dim), its future, and timing metadata."""

    __slots__ = ("sample", "key", "future", "enqueue_t", "deadline_t",
                 "trace")

    def __init__(self, sample: Sequence[_np.ndarray], key: Any,
                 future: Any, deadline_t: Optional[float]) -> None:
        self.sample = sample
        self.key = key
        self.future = future
        self.enqueue_t = time.monotonic()
        self.deadline_t = deadline_t
        # trace context captured at submit: the worker thread that
        # eventually executes this request attaches it so its spans
        # parent under the submitting request's trace
        self.trace = _tracing.capture()


class DynamicBatcher:
    """Bounded queue + micro-batch assembly (one consumer thread)."""

    def __init__(self, policy: BucketPolicy,
                 timeout_ms: Optional[float] = None,
                 queue_limit: Optional[int] = None) -> None:
        self.policy = policy
        if timeout_ms is None:
            timeout_ms = float(getenv("MXNET_SERVING_BATCH_TIMEOUT_MS", 5))
        if queue_limit is None:
            queue_limit = int(getenv("MXNET_SERVING_QUEUE_LIMIT", 256))
        self.timeout_s = max(0.0, timeout_ms / 1e3)
        self.queue_limit = queue_limit
        self._q: List[Request] = []
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._closed = False
        self._draining = False

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    @property
    def draining(self) -> bool:
        return self._draining

    def start_drain(self) -> None:
        """Stop ADMITTING: new submits shed with a structured
        ``OverloadError(reason="draining")`` while already-queued
        requests keep flowing to the workers (graceful drain)."""
        with self._lock:
            self._draining = True

    def requeue(self, reqs: Sequence[Request]) -> None:
        """Front-insert requests a dying worker abandoned mid-batch.
        No queue_full shed — they were already accepted — and completed
        futures are skipped (a partially-distributed batch re-executes
        only its unresolved requests: the future is the exactly-once
        boundary)."""
        live = [r for r in reqs if not r.future.done()]
        if not live:
            return
        with self._lock:
            if self._closed:
                for r in live:
                    try:
                        r.future.set_exception(MXNetError(
                            "serving batcher closed with the request "
                            "still queued"))
                    except Exception:   # noqa: BLE001 - done() race
                        continue
                    REQUESTS_TOTAL.labels(status="error").inc()
                return
            self._q[:0] = live
            QUEUE_DEPTH.set(len(self._q))
            self._nonempty.notify_all()

    def reopen(self) -> None:
        """Clear the closed/draining flags (the manual breaker-reset
        path re-admits traffic through the same batcher)."""
        with self._lock:
            self._closed = False
            self._draining = False

    def submit(self, req: Request) -> None:
        """Enqueue or shed-immediately (OverloadError set on the future
        AND raised — in-process callers see it synchronously)."""
        with self._lock:
            if self._closed:
                raise MXNetError("serving batcher is closed")
            if self._draining:
                err = OverloadError("draining", queue_depth=len(self._q),
                                    retry_after_ms=1e3)
                SHED_TOTAL.labels(reason="draining").inc()
                REQUESTS_TOTAL.labels(status="shed").inc()
                req.future.set_exception(err)
                raise err
            if len(self._q) >= self.queue_limit:
                # abandoned requests (future already cancelled/done)
                # must not hold queue_full sheds high: purge before
                # deciding to shed the live newcomer
                self._q[:] = [r for r in self._q if not r.future.done()]
                QUEUE_DEPTH.set(len(self._q))
            if len(self._q) >= self.queue_limit:
                depth = len(self._q)
                err = OverloadError(
                    "queue_full", queue_depth=depth,
                    retry_after_ms=1e3 * self.timeout_s * max(
                        1, depth // max(1, self.policy.max_batch)))
                SHED_TOTAL.labels(reason="queue_full").inc()
                REQUESTS_TOTAL.labels(status="shed").inc()
                req.future.set_exception(err)
                raise err
            self._q.append(req)
            QUEUE_DEPTH.set(len(self._q))
            self._nonempty.notify()

    def close(self, error: Optional[Exception] = None) -> None:
        """Stop accepting work and wake the consumers; queued requests
        fail with a server-stopped error (or ``error`` — the breaker
        trip passes its structured degradation error through)."""
        exc = error if error is not None else MXNetError(
            "serving batcher closed with the request still queued")
        with self._lock:
            self._closed = True
            for r in self._q:
                try:
                    r.future.set_exception(exc)
                except Exception:   # noqa: BLE001 - done() race
                    continue
                REQUESTS_TOTAL.labels(status="error").inc()
            self._q.clear()
            QUEUE_DEPTH.set(0)
            self._nonempty.notify_all()

    def _shed_expired(self, now: float) -> None:
        keep = []
        for r in self._q:
            if r.future.done():
                # cancelled by the caller while queued (e.g. a partial
                # multi-instance shed): free the slot, run nothing
                continue
            if r.deadline_t is not None and now > r.deadline_t:
                err = OverloadError("deadline", queue_depth=len(self._q),
                                    retry_after_ms=1e3 * self.timeout_s)
                try:
                    r.future.set_exception(err)
                except Exception:   # noqa: BLE001 - cancelled in the
                    continue        # done()->here window: just drop it
                SHED_TOTAL.labels(reason="deadline").inc()
                REQUESTS_TOTAL.labels(status="shed").inc()
            else:
                keep.append(r)
        self._q[:] = keep
        QUEUE_DEPTH.set(len(self._q))

    def next_batch(self, on_take: Optional[Callable[[List[Request]],
                                                    Any]] = None
                   ) -> Optional[List[Request]]:
        """Block until a batch is ready (bucket full, or the oldest
        request aged past the batching window); None once closed and
        drained.  Called by the server's worker threads.  ``on_take``
        runs UNDER the queue lock on the taken batch, so the caller's
        in-flight bookkeeping has no queued-nor-inflight gap for a
        drain poll to mistake for idleness."""
        with self._lock:
            while True:
                self._shed_expired(time.monotonic())
                if self._q:
                    # a FULL bucket anywhere flushes immediately — a
                    # rare-shape head request must not hold a full
                    # common-shape bucket hostage for its whole window
                    counts: Dict[Any, int] = {}
                    full_key = None
                    for r in self._q:
                        n = counts.get(r.key, 0) + 1
                        counts[r.key] = n
                        if n >= self.policy.max_batch:
                            full_key = r.key
                            break
                    head = self._q[0]
                    key = full_key if full_key is not None else head.key
                    same = [r for r in self._q if r.key == key]
                    age = time.monotonic() - head.enqueue_t
                    if (full_key is not None
                            or age >= self.timeout_s or self._closed):
                        take = same[:self.policy.max_batch]
                        taken = set(map(id, take))
                        self._q[:] = [r for r in self._q
                                      if id(r) not in taken]
                        QUEUE_DEPTH.set(len(self._q))
                        now = time.monotonic()
                        pc = time.perf_counter()
                        for r in take:
                            wait = now - r.enqueue_t
                            QUEUE_WAIT_SECONDS.observe(
                                wait,
                                exemplar=r.trace.trace_id
                                if r.trace is not None else None)
                            # retroactive span: submit -> batch take
                            _tracing.record_span(
                                "queue.wait", pc - wait, pc,
                                ctx=r.trace)
                        BATCH_SIZE.observe(len(take))
                        if on_take is not None:
                            on_take(take)
                        return take
                    self._nonempty.wait(self.timeout_s - age)
                    continue
                if self._closed:
                    return None
                # empty queue: nothing to age out — block until submit()
                # or close() notifies (no idle busy-poll)
                self._nonempty.wait()


# ---------------------------------------------------------------------------
# Two-queue scheduler for the generation engine (iteration-level
# continuous batching)
# ---------------------------------------------------------------------------

class SlotScheduler:
    """Prefill queue + decode slot table — the iteration-level
    scheduler behind :class:`~mxnet_tpu.serving.generation.
    GenerationEngine`.

    Two queues, two service disciplines:

    * **prefill** — a BOUNDED FIFO of not-yet-admitted requests with
      the one-shot path's exact shed semantics: a full queue sheds the
      newcomer at submit (``queue_full``); a request whose deadline
      passed while waiting for a slot is shed at admission time
      (``deadline``) — "no slot freed within the deadline" is the
      generation-side overload signal.
    * **decode** — the slot table itself: admitted requests occupy a
      slot until retirement (EOS / max-tokens / error) frees it.  The
      engine drains admissions BETWEEN decode iterations, so new
      requests join mid-flight without perturbing resident sequences.

    Requests are duck-typed: they carry ``deadline_t`` (monotonic or
    None), ``enqueue_t``, and ``fail(exc)`` / ``is_cancelled()`` (the
    generation request routes these to its token stream).
    """

    def __init__(self, max_slots: int,
                 queue_limit: Optional[int] = None) -> None:
        if queue_limit is None:
            queue_limit = int(getenv("MXNET_SERVING_QUEUE_LIMIT", 256))
        self.max_slots = int(max_slots)
        self.queue_limit = int(queue_limit)
        self._q: List[Any] = []
        self._active: Dict[int, Any] = {}       # slot -> request
        # popped for admission but not yet slot-resident (prefill in
        # flight): counted so a drain poll never sees a false idle
        self._mid_admission = 0
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._closed = False

    # -- prefill queue ------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def submit(self, req: Any, front: bool = False,
               force: bool = False) -> None:
        """Enqueue for admission, or shed immediately (OverloadError
        failed onto the request AND raised, mirroring
        :meth:`DynamicBatcher.submit`).  ``force`` bypasses the
        queue_full shed and ``front`` queue-jumps — the recovery path:
        a resurrected sequence was already accepted and already waited
        its turn once."""
        with self._lock:
            if self._closed:
                raise MXNetError("generation scheduler is closed")
            if not force and len(self._q) >= self.queue_limit:
                # abandoned (cancelled-while-queued) entries must not
                # hold queue_full sheds high
                self._q[:] = [r for r in self._q
                              if not r.is_cancelled()]
            if not force and len(self._q) >= self.queue_limit:
                depth = len(self._q)
                err = OverloadError("queue_full", queue_depth=depth,
                                    retry_after_ms=100.0 * max(1, depth))
                SHED_TOTAL.labels(reason="queue_full").inc()
                REQUESTS_TOTAL.labels(status="shed").inc()
                req.fail(err)
                raise err
            if front:
                self._q.insert(0, req)
            else:
                self._q.append(req)
            _metrics.GEN_QUEUE_DEPTH.set(len(self._q))
            self._work.notify_all()

    def discard(self, req: Any) -> bool:
        """Evict a still-queued request NOW (consumer cancelled): the
        queue budget frees immediately instead of at the next admission
        pass.  Returns True when the request was found queued."""
        with self._lock:
            try:
                self._q.remove(req)
            except ValueError:
                return False
            _metrics.GEN_QUEUE_DEPTH.set(len(self._q))
        REQUESTS_TOTAL.labels(status="cancelled").inc()
        return True

    def drain_queue(self) -> List[Any]:
        """Pop every queued request WITHOUT failing it (worker-death
        evacuation: the supervisor requeues them elsewhere).  Also
        clears the mid-admission count — the engine hands those
        requests over separately."""
        with self._lock:
            out, self._q[:] = list(self._q), []
            self._mid_admission = 0
            _metrics.GEN_QUEUE_DEPTH.set(0)
            return out

    def pop_admissions(self, free_slots: int,
                       now: Optional[float] = None) -> List[Any]:
        """Up to ``free_slots`` admissible requests, FIFO; expired or
        cancelled entries are shed/dropped in passing (the deadline
        check at the admission boundary IS the "no slot freed in time"
        shed)."""
        if now is None:
            now = time.monotonic()
        out: List[Any] = []
        with self._lock:
            keep: List[Any] = []
            for r in self._q:
                if r.is_cancelled():
                    continue
                if r.deadline_t is not None and now > r.deadline_t:
                    err = OverloadError("deadline",
                                        queue_depth=len(self._q),
                                        retry_after_ms=100.0)
                    SHED_TOTAL.labels(reason="deadline").inc()
                    REQUESTS_TOTAL.labels(status="shed").inc()
                    r.fail(err)
                    continue
                if len(out) < free_slots:
                    out.append(r)
                    tr = getattr(r, "trace", None)
                    if tr is not None:
                        # submit -> admission pop = the slot wait
                        pc = time.perf_counter()
                        _tracing.record_span(
                            "queue.wait", pc - (now - r.enqueue_t),
                            pc, ctx=tr)
                else:
                    keep.append(r)
            self._q[:] = keep
            self._mid_admission += len(out)
            _metrics.GEN_QUEUE_DEPTH.set(len(self._q))
        return out

    def admission_done(self) -> None:
        """One popped request landed (activated or failed): it is no
        longer mid-admission."""
        with self._lock:
            self._mid_admission = max(0, self._mid_admission - 1)

    def busy(self) -> bool:
        """Anything queued, slot-resident, or mid-admission — the
        drain-idleness check (a request being prefilled is in neither
        queue nor slot table, but it is NOT done)."""
        with self._lock:
            return bool(self._q or self._active or self._mid_admission)

    # -- decode slot table --------------------------------------------------
    def activate(self, slot: int, req: Any) -> None:
        with self._lock:
            self._active[int(slot)] = req

    def release(self, slot: int) -> Any:
        with self._lock:
            return self._active.pop(int(slot), None)

    def active(self) -> Dict[int, Any]:
        with self._lock:
            return dict(self._active)

    def n_active(self) -> int:
        with self._lock:
            return len(self._active)

    # -- engine-loop blocking ----------------------------------------------
    def wait_for_work(self, timeout: float) -> bool:
        """Block until there is anything to do (queued request, active
        slot, or close); returns False once closed AND drained."""
        with self._lock:
            if not self._q and not self._active and not self._closed:
                self._work.wait(timeout)
            return not (self._closed and not self._q
                        and not self._active)

    def close(self) -> None:
        """Stop admissions; queued requests fail with a shutdown error.
        Active slots are the engine's to fail (it owns the streams)."""
        with self._lock:
            self._closed = True
            for r in self._q:
                r.fail(MXNetError(
                    "generation scheduler closed with the request "
                    "still queued (shutdown)"))
                REQUESTS_TOTAL.labels(status="error").inc()
            self._q.clear()
            _metrics.GEN_QUEUE_DEPTH.set(0)
            self._work.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed
