"""ServedModel — one ``predict(arrays) -> arrays`` surface over every
way a model reaches the server.

Backends:

* **export artifact** (``HybridBlock.export`` / ``Module.export`` output:
  ``prefix-symbol.json`` + ``prefix-NNNN.params``): the StableHLO
  program is deserialized once and called directly on raw arrays — the
  ``c_predict_api`` analog, no gluon graph in the hot path.  An artifact
  exported with ``dynamic_batch=True`` serves every batch bucket from
  ONE serialized program (shape-polymorphic leading dim); a static
  artifact pins the policy to its exported batch size.
* **live block** (a (Hybrid)Block or Module): hybridized and driven in
  predict mode — per-bucket executables appear through the normal jit
  cache.  The path for models that never went through export (tests,
  notebooks, zoo models).

Both backends share per-bucket compile accounting: the first execution
of each padded batch signature increments
``mxnet_serving_bucket_compiles_total{bucket=...}`` — with a
:class:`~mxnet_tpu.serving.batching.BucketPolicy` in front, that counter
is bounded by the bucket grid, and :meth:`ServedModel.warmup` moves all
of it to startup.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError
from .batching import BUCKET_COMPILES, BucketPolicy, INFER_SECONDS

__all__ = ["ServedModel", "load_served"]


def _sig_str(shapes: Sequence[Tuple[int, ...]]) -> str:
    return ";".join("x".join(map(str, s)) for s in shapes)


class ServedModel:
    """A loaded inference model: ``predict`` over numpy batch arrays.

    Build with :meth:`from_export`, :meth:`from_block`,
    :meth:`from_module`, or the path-sniffing :func:`load_served`.
    """

    def __init__(self, fn: Any, input_signature: List[Tuple[Tuple[int, ...],
                                                            Any]],
                 fixed_batch: Optional[int], name: str) -> None:
        self._fn = fn
        # per-input (shape_without_batch, dtype) — what a single request
        # sample must look like
        self.input_signature = input_signature
        # static exports serve exactly their traced batch size
        self.fixed_batch = fixed_batch
        self.name = name
        # guarded: the worker thread adds while /healthz threads read
        self._seen_lock = threading.Lock()
        self._seen_buckets: set = set()

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_export(symbol_file: str,
                    param_file: Optional[str] = None) -> "ServedModel":
        """Load an ``export()`` artifact for serving (the predict-API
        path: StableHLO called directly, no gluon objects per request)."""
        import base64

        import jax
        import jax.numpy as jnp
        from jax import export as jax_export

        with open(symbol_file) as f:
            meta = json.load(f)
        if meta.get("framework") != "mxnet_tpu" or "stablehlo" not in meta:
            raise MXNetError(
                f"{symbol_file} is not an mxnet_tpu export (re-export "
                "with HybridBlock.export)")
        if param_file is None:
            param_file = _guess_param_file(symbol_file)
        exp = jax_export.deserialize(
            bytearray(base64.b64decode(meta["stablehlo"])))
        order = meta["param_order"]
        params: List[Any] = []
        if order:
            if param_file is None:
                raise MXNetError(
                    "this export has parameters — pass the "
                    "prefix-NNNN.params file (or keep it next to the "
                    "symbol json)")
            from ..ndarray_io import load_params
            loaded = load_params(param_file)
            missing = [k for k in order if k not in loaded]
            if missing:
                raise MXNetError(
                    f"{param_file} is missing exported params: {missing}")
            params = [jnp.asarray(loaded[k]._data) for k in order]
        key = jnp.zeros((2,), jnp.uint32)   # inference: dropout is off
        dynamic = bool(meta.get("dynamic_batch"))
        sig = [(tuple(i["shape"][1:]), _np.dtype(i["dtype"]))
               for i in meta["inputs"]]
        fixed = None if dynamic else int(meta["inputs"][0]["shape"][0])

        def fn(arrays: Sequence[_np.ndarray]) -> List[_np.ndarray]:
            jarrs = [jnp.asarray(a) for a in arrays]
            leaves = exp.call(key, params, *jarrs)
            return [_np.asarray(o) for o in leaves]

        name = os.path.basename(symbol_file).replace("-symbol.json", "")
        return ServedModel(fn, sig, fixed, name or "export")

    @staticmethod
    def from_block(block: Any,
                   input_signature: Optional[Sequence[Tuple[
                       Tuple[int, ...], Any]]] = None) -> "ServedModel":
        """Serve a live (Hybrid)Block.  ``input_signature`` is per-input
        (shape_without_batch, dtype); defaults to the block's last
        hybridized call signature (run it once first)."""
        from .. import autograd
        from ..ndarray.ndarray import NDArray

        if hasattr(block, "hybridize") and not getattr(block, "_active",
                                                       False):
            block.hybridize()
        if input_signature is None:
            last = getattr(block, "_last_sig", None)
            if last is None:
                raise MXNetError(
                    "from_block needs the input signature: run the block "
                    "once, or pass input_signature=[(sample_shape, "
                    "dtype), ...] (shapes WITHOUT the batch dim)")
            input_signature = [(tuple(s[1:]), d) for s, d in last]

        def fn(arrays: Sequence[_np.ndarray]) -> List[_np.ndarray]:
            import jax
            nds = [NDArray(a) for a in arrays]
            with autograd.predict_mode():
                out = block(*nds)
            leaves, _ = jax.tree_util.tree_flatten(
                out, is_leaf=lambda o: isinstance(o, NDArray))
            return [o.asnumpy() for o in leaves]

        sig = [(tuple(s), _np.dtype(d)) for s, d in input_signature]
        return ServedModel(fn, sig, None, type(block).__name__)

    @staticmethod
    def from_module(module: Any) -> "ServedModel":
        """Serve a bound Module's network (inference half of the classic
        workflow)."""
        if not getattr(module, "params_initialized", False):
            raise MXNetError("module must be bound + initialized before "
                             "serving")
        sig = [(tuple(d.shape[1:]) if hasattr(d, "shape")
                else tuple(d[1][1:]),
                getattr(d, "dtype", _np.float32))
               for d in module._data_shapes]
        return ServedModel.from_block(module.symbol, sig)

    # -- execution ----------------------------------------------------------
    def predict(self, arrays: Sequence[_np.ndarray]) -> List[_np.ndarray]:
        """Run one padded batch; returns per-output numpy arrays (axis 0
        = padded batch).  Tracks first-seen batch signatures as bucket
        compiles and times the execution."""
        shapes = tuple(tuple(a.shape) for a in arrays)
        with self._seen_lock:
            new = shapes not in self._seen_buckets
            if new:
                self._seen_buckets.add(shapes)
        if new:
            BUCKET_COMPILES.labels(bucket=_sig_str(shapes)).inc()
        t0 = time.perf_counter()
        out = self._fn(arrays)
        INFER_SECONDS.observe(time.perf_counter() - t0)
        return out

    def warmup(self, policy: BucketPolicy) -> int:
        """Pre-compile every bucket signature the policy can emit (zeros
        input); returns how many signatures were warmed.  After this, a
        request stream confined to the bucket grid never compiles."""
        n = 0
        for sig in policy.warmup_signatures(self.input_signature):
            if self.fixed_batch is not None \
                    and sig[0][0][0] != self.fixed_batch:
                raise MXNetError(
                    f"static export serves only batch={self.fixed_batch}; "
                    f"configure BucketPolicy(batch_buckets="
                    f"[{self.fixed_batch}]) (or re-export with "
                    "dynamic_batch=True)")
            self.predict([_np.zeros(s, d) for s, d in sig])
            n += 1
        return n

    def default_policy(self, **kw: Any) -> BucketPolicy:
        """A policy consistent with this model (static exports pin the
        batch bucket to the exported batch)."""
        if self.fixed_batch is not None and "batch_buckets" not in kw:
            kw["batch_buckets"] = [self.fixed_batch]
        return BucketPolicy(**kw)

    def describe(self) -> Dict[str, Any]:
        with self._seen_lock:
            seen = list(self._seen_buckets)
        return {
            "name": self.name,
            "inputs": [{"sample_shape": list(s), "dtype": str(d)}
                       for s, d in self.input_signature],
            "fixed_batch": self.fixed_batch,
            "buckets_compiled": sorted(_sig_str(s) for s in seen),
        }


def _guess_param_file(symbol_file: str) -> Optional[str]:
    """Newest ``prefix-NNNN.params`` next to ``prefix-symbol.json``."""
    if not symbol_file.endswith("-symbol.json"):
        return None
    prefix = symbol_file[:-len("-symbol.json")]
    cands = sorted(
        f for f in (os.listdir(os.path.dirname(prefix) or ".") or [])
        if f.startswith(os.path.basename(prefix) + "-")
        and f.endswith(".params"))
    if not cands:
        return None
    return os.path.join(os.path.dirname(prefix) or ".", cands[-1])


def load_served(model: Any, param_file: Optional[str] = None,
                **kw: Any) -> ServedModel:
    """Sniff ``model`` into a :class:`ServedModel`: an export prefix or
    ``-symbol.json`` path, a Module, or a (Hybrid)Block."""
    if isinstance(model, str):
        sym = model if model.endswith("-symbol.json") \
            else f"{model}-symbol.json"
        return ServedModel.from_export(sym, param_file)
    if hasattr(model, "params_initialized"):        # Module duck-type
        return ServedModel.from_module(model)
    if hasattr(model, "collect_params"):            # gluon Block
        return ServedModel.from_block(model, **kw)
    raise MXNetError(f"cannot serve a {type(model).__name__}")
