"""ServedModel — one ``predict(arrays) -> arrays`` surface over every
way a model reaches the server.

Backends:

* **export artifact** (``HybridBlock.export`` / ``Module.export`` output:
  ``prefix-symbol.json`` + ``prefix-NNNN.params``): the StableHLO
  program is deserialized once and called directly on raw arrays — the
  ``c_predict_api`` analog, no gluon graph in the hot path.  An artifact
  exported with ``dynamic_batch=True`` serves every batch bucket from
  ONE serialized program (shape-polymorphic leading dim); a static
  artifact pins the policy to its exported batch size.
* **live block** (a (Hybrid)Block or Module): hybridized and driven in
  predict mode — per-bucket executables appear through the normal jit
  cache.  The path for models that never went through export (tests,
  notebooks, zoo models).

Both backends share per-bucket compile accounting: the first execution
of each padded batch signature increments
``mxnet_serving_bucket_compiles_total{bucket=...}`` — with a
:class:`~mxnet_tpu.serving.batching.BucketPolicy` in front, that counter
is bounded by the bucket grid, and :meth:`ServedModel.warmup` moves all
of it to startup.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError
from .batching import BUCKET_COMPILES, BucketPolicy, INFER_SECONDS

__all__ = ["ServedModel", "DecodeModel", "load_served"]


def _sig_str(shapes: Sequence[Tuple[int, ...]]) -> str:
    return ";".join("x".join(map(str, s)) for s in shapes)


class ServedModel:
    """A loaded inference model: ``predict`` over numpy batch arrays.

    Build with :meth:`from_export`, :meth:`from_block`,
    :meth:`from_module`, or the path-sniffing :func:`load_served`.
    """

    def __init__(self, fn: Any, input_signature: List[Tuple[Tuple[int, ...],
                                                            Any]],
                 fixed_batch: Optional[int], name: str) -> None:
        self._fn = fn
        # per-input (shape_without_batch, dtype) — what a single request
        # sample must look like
        self.input_signature = input_signature
        # static exports serve exactly their traced batch size
        self.fixed_batch = fixed_batch
        self.name = name
        # guarded: the worker thread adds while /healthz threads read
        self._seen_lock = threading.Lock()
        self._seen_buckets: set = set()

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_export(symbol_file: str,
                    param_file: Optional[str] = None) -> "ServedModel":
        """Load an ``export()`` artifact for serving (the predict-API
        path: StableHLO called directly, no gluon objects per request).

        Artifacts carrying digests (``export()`` emits them) are
        **checksum-verified before deserialization**: a truncated or
        bit-flipped program/params file raises a structured error
        naming the artifact and the expected/actual digests, instead
        of an opaque deserializer crash (or, worse, a model that loads
        and serves garbage).  Per-bucket executables go through the
        persistent compile cache (pinned — a live server's grid is
        never evicted), so a restarted replica re-warms from disk with
        zero XLA compiles."""
        import base64

        import jax
        import jax.numpy as jnp
        from jax import export as jax_export
        from .. import compile_cache as _cc
        from .._durable import sha256_bytes, sha256_file

        with open(symbol_file) as f:
            meta = json.load(f)
        if meta.get("framework") != "mxnet_tpu" or "stablehlo" not in meta:
            raise MXNetError(
                f"{symbol_file} is not an mxnet_tpu export (re-export "
                "with HybridBlock.export)")
        if param_file is None:
            param_file = _guess_param_file(symbol_file)
        program = base64.b64decode(meta["stablehlo"])
        want = meta.get("stablehlo_sha256")
        if want is not None:
            got = sha256_bytes(program)
            if got != want:
                raise MXNetError(
                    f"export artifact {symbol_file} failed its program "
                    f"checksum (stablehlo_sha256 {want[:12]}…, file "
                    f"digests to {got[:12]}…) — the artifact is "
                    "truncated or garbled; re-export or restore it "
                    "before serving")
        order = meta["param_order"]
        params: List[Any] = []
        if order:
            if param_file is None:
                raise MXNetError(
                    "this export has parameters — pass the "
                    "prefix-NNNN.params file (or keep it next to the "
                    "symbol json)")
            want = meta.get("params_sha256")
            if want is not None:
                got = sha256_file(param_file)
                if got != want:
                    raise MXNetError(
                        f"export artifact {param_file} failed its "
                        f"checksum (params_sha256 {want[:12]}…, file "
                        f"digests to {got[:12]}…) — the weights are "
                        "truncated or garbled (or not the file this "
                        "symbol json was exported with); re-export or "
                        "restore them before serving")
            from ..ndarray_io import load_params
            loaded = load_params(param_file)
            missing = [k for k in order if k not in loaded]
            if missing:
                raise MXNetError(
                    f"{param_file} is missing exported params: {missing}")
            params = [jnp.asarray(loaded[k]._data) for k in order]
        exp = jax_export.deserialize(bytearray(program))
        key = jnp.zeros((2,), jnp.uint32)   # inference: dropout is off
        dynamic = bool(meta.get("dynamic_batch"))
        sig = [(tuple(i["shape"][1:]), _np.dtype(i["dtype"]))
               for i in meta["inputs"]]
        fixed = None if dynamic else int(meta["inputs"][0]["shape"][0])

        # params ride as ARGUMENTS (not closure constants): the lowered
        # program — and so the persistent-cache key and the serialized
        # executable — is weight-independent, shared across re-exports
        # of the same architecture
        aot = _cc.persistently_cached(
            jax.jit(lambda ps, *xs: exp.call(key, list(ps), *xs)),
            surface="serving.export", pin=True)
        params_t = tuple(params)

        def fn(arrays: Sequence[_np.ndarray]) -> List[_np.ndarray]:
            jarrs = [jnp.asarray(a) for a in arrays]
            leaves = aot(params_t, *jarrs)
            return [_np.asarray(o) for o in leaves]

        name = os.path.basename(symbol_file).replace("-symbol.json", "")
        return ServedModel(fn, sig, fixed, name or "export")

    @staticmethod
    def from_block(block: Any,
                   input_signature: Optional[Sequence[Tuple[
                       Tuple[int, ...], Any]]] = None) -> "ServedModel":
        """Serve a live (Hybrid)Block.  ``input_signature`` is per-input
        (shape_without_batch, dtype); defaults to the block's last
        hybridized call signature (run it once first)."""
        from .. import autograd
        from ..ndarray.ndarray import NDArray

        if hasattr(block, "hybridize") and not getattr(block, "_active",
                                                       False):
            block.hybridize()
        if input_signature is None:
            last = getattr(block, "_last_sig", None)
            if last is None:
                raise MXNetError(
                    "from_block needs the input signature: run the block "
                    "once, or pass input_signature=[(sample_shape, "
                    "dtype), ...] (shapes WITHOUT the batch dim)")
            input_signature = [(tuple(s[1:]), d) for s, d in last]

        def fn(arrays: Sequence[_np.ndarray]) -> List[_np.ndarray]:
            import jax
            nds = [NDArray(a) for a in arrays]
            with autograd.predict_mode():
                out = block(*nds)
            leaves, _ = jax.tree_util.tree_flatten(
                out, is_leaf=lambda o: isinstance(o, NDArray))
            return [o.asnumpy() for o in leaves]

        sig = [(tuple(s), _np.dtype(d)) for s, d in input_signature]
        return ServedModel(fn, sig, None, type(block).__name__)

    @staticmethod
    def from_module(module: Any) -> "ServedModel":
        """Serve a bound Module's network (inference half of the classic
        workflow)."""
        if not getattr(module, "params_initialized", False):
            raise MXNetError("module must be bound + initialized before "
                             "serving")
        sig = [(tuple(d.shape[1:]) if hasattr(d, "shape")
                else tuple(d[1][1:]),
                getattr(d, "dtype", _np.float32))
               for d in module._data_shapes]
        return ServedModel.from_block(module.symbol, sig)

    # -- execution ----------------------------------------------------------
    def predict(self, arrays: Sequence[_np.ndarray]) -> List[_np.ndarray]:
        """Run one padded batch; returns per-output numpy arrays (axis 0
        = padded batch).  Tracks first-seen batch signatures as bucket
        compiles and times the execution."""
        shapes = tuple(tuple(a.shape) for a in arrays)
        with self._seen_lock:
            new = shapes not in self._seen_buckets
            if new:
                self._seen_buckets.add(shapes)
        if new:
            BUCKET_COMPILES.labels(bucket=_sig_str(shapes)).inc()
        t0 = time.perf_counter()
        out = self._fn(arrays)
        INFER_SECONDS.observe(time.perf_counter() - t0)
        return out

    def warmup(self, policy: BucketPolicy) -> int:
        """Pre-compile every bucket signature the policy can emit (zeros
        input); returns how many signatures were warmed.  After this, a
        request stream confined to the bucket grid never compiles."""
        n = 0
        for sig in policy.warmup_signatures(self.input_signature):
            if self.fixed_batch is not None \
                    and sig[0][0][0] != self.fixed_batch:
                raise MXNetError(
                    f"static export serves only batch={self.fixed_batch}; "
                    f"configure BucketPolicy(batch_buckets="
                    f"[{self.fixed_batch}]) (or re-export with "
                    "dynamic_batch=True)")
            self.predict([_np.zeros(s, d) for s, d in sig])
            n += 1
        return n

    def default_policy(self, **kw: Any) -> BucketPolicy:
        """A policy consistent with this model (static exports pin the
        batch bucket to the exported batch)."""
        if self.fixed_batch is not None and "batch_buckets" not in kw:
            kw["batch_buckets"] = [self.fixed_batch]
        return BucketPolicy(**kw)

    def describe(self) -> Dict[str, Any]:
        with self._seen_lock:
            seen = list(self._seen_buckets)
        return {
            "name": self.name,
            "inputs": [{"sample_shape": list(s), "dtype": str(d)}
                       for s, d in self.input_signature],
            "fixed_batch": self.fixed_batch,
            "buckets_compiled": sorted(_sig_str(s) for s in seen),
        }


# ---------------------------------------------------------------------------
# DecodeModel — the stateful autoregressive path (continuous batching)
# ---------------------------------------------------------------------------

def _slot_block_step(p, x, ck, cv, pos, nh: int, ga):
    """One decode token for EVERY slot: ``x`` (S, 1, C), caches
    (S, L, nh, d), ``pos`` (S,) int32 — the per-slot-position variant
    of ``model_zoo.generation._block_step`` (which shares one scalar
    position across the batch; continuous batching cannot)."""
    import math as _math
    import jax
    import jax.numpy as jnp

    gelu_approx, eps = ga
    S, _, C = x.shape
    d = C // nh
    L = ck.shape[1]
    h = _pure_ln(x, p["ln1_g"], p["ln1_b"], eps)
    qkv = h @ p["qkv_w"].T + p["qkv_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    qh = q.reshape(S, 1, nh, d)
    rows = jnp.arange(S)
    # per-slot scatter: slot i writes its k/v at ITS position pos[i]
    ck = ck.at[rows, pos].set(k.reshape(S, nh, d))
    cv = cv.at[rows, pos].set(v.reshape(S, nh, d))
    scores = jnp.einsum("sqhd,skhd->shqk", qh, ck) / _math.sqrt(d)
    # slot i sees cache positions 0..pos[i] (its prompt + its decoded
    # tokens); pad garbage beyond pos[i] stays invisible until the loop
    # overwrites it position by position
    visible = jnp.arange(L)[None, :] <= pos[:, None]          # (S, L)
    scores = jnp.where(visible[:, None, None, :], scores,
                       jnp.float32(-jnp.inf).astype(scores.dtype))
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("shqk,skhd->sqhd", probs, cv).reshape(S, 1, C)
    x = x + (out @ p["out_w"].T + p["out_b"])
    h = _pure_ln(x, p["ln2_g"], p["ln2_b"], eps)
    ffn = jax.nn.gelu(h @ p["f1_w"].T + p["f1_b"],
                      approximate=gelu_approx)
    return x + (ffn @ p["f2_w"].T + p["f2_b"]), ck, cv


def _pure_ln(x, g, b, eps):
    import jax.numpy as jnp
    from jax import lax
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * lax.rsqrt(var + eps) * g + b


class DecodeModel:
    """The decode-capable serving path: a stateful
    ``(params, kv_cache, positions) -> next tokens`` step over slot
    rows, compiled ONCE per KV capacity bucket, plus a per-prompt-bucket
    prefill — the two programs the continuous-batching
    :class:`~mxnet_tpu.serving.generation.GenerationEngine` runs
    resident.

    Built from a live :class:`~mxnet_tpu.gluon.model_zoo.gpt.GPTModel`
    (the zoo's decoder-only family): parameters are extracted once into
    a pure pytree (``model_zoo.generation._collect``) and the decode
    math mirrors the zoo's KV-cache step, extended to per-slot
    positions.  Compile accounting rides the SAME per-bucket counter as
    the one-shot path (``mxnet_serving_bucket_compiles_total``, labels
    ``decode:SxL`` / ``prefill:Lp``), so warmup moves every compile to
    startup and the smoke gate can pin "0 after warmup".
    """

    def __init__(self, params: Any, num_heads: int, ga: Tuple[Any, Any],
                 max_length: int, name: str) -> None:
        import jax

        self.params = params
        self.num_heads = int(num_heads)
        self.ga = (bool(ga[0]), float(ga[1]))
        self.max_length = int(max_length)
        self.name = name
        self.vocab_size, self.units = params["embed"].shape
        self.head_dim = self.units // self.num_heads
        self.n_layers = len(params["blocks"])
        self.dtype = params["blocks"][0]["qkv_w"].dtype
        self._seen_lock = threading.Lock()
        self._seen: set = set()
        nh, ga_s = self.num_heads, self.ga

        def _prefill(params, toks, t0):
            # toks (Lp,) int32 (pad tokens after t0), t0 traced scalar;
            # returns (last-real-token logits (V,), ks/vs lists of
            # (Lp, nh, d)) — garbage pad KV past t0 is masked by the
            # decode position mask until overwritten
            from jax import lax
            from ..gluon.model_zoo.generation import _block_prefill
            Lp = toks.shape[0]
            x = params["embed"][toks][None] + params["pos"][None, :Lp]
            ks, vs = [], []
            for p in params["blocks"]:
                x, ck, cv = _block_prefill(p, x, nh, Lp, ga_s)
                ks.append(ck[0])
                vs.append(cv[0])
            x = _pure_ln(x, params["lnf_g"], params["lnf_b"], ga_s[1])
            h = lax.dynamic_slice_in_dim(x[0], t0 - 1, 1, axis=0)[0]
            return h @ params["embed"].T, ks, vs

        def _step(params, ks, vs, toks, pos):
            # toks (S,) int32 last emitted per slot, pos (S,) int32
            # write positions; free slots ride along with pos=0 and
            # their outputs are ignored on the host
            import jax.numpy as jnp
            x = (params["embed"][toks][:, None, :]
                 + params["pos"][pos][:, None, :])
            new_ks, new_vs = [], []
            for p, ck, cv in zip(params["blocks"], ks, vs):
                x, ck, cv = _slot_block_step(p, x, ck, cv, pos, nh, ga_s)
                new_ks.append(ck)
                new_vs.append(cv)
            x = _pure_ln(x, params["lnf_g"], params["lnf_b"], ga_s[1])
            logits = x[:, 0, :] @ params["embed"].T
            # greedy argmax ON DEVICE: the host reads back (S,) int32
            # per iteration, not (S, V) logits
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), \
                new_ks, new_vs

        # both programs persist through the compile cache (pinned: a
        # live server's decode grid is never evicted) so a restarted
        # replica re-warms its whole bucket grid with zero XLA compiles
        from .. import compile_cache as _cc
        self._prefill_fn = _cc.persistently_cached(
            jax.jit(_prefill), surface="serving.decode", pin=True)
        # the KV buffers are DONATED: XLA updates the resident cache in
        # place instead of allocating a fresh (S, L, h, d) per layer
        # every token
        self._step_fn = _cc.persistently_cached(
            jax.jit(_step, donate_argnums=(1, 2)),
            surface="serving.decode", pin=True)

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_block(block: Any) -> "DecodeModel":
        """Build from a live zoo ``GPTModel`` (weights as currently
        initialized/loaded; MoE decode is not supported yet — same
        restriction as ``model_zoo.generation``)."""
        from ..gluon.model_zoo.generation import _collect
        if not hasattr(block, "blocks") or not hasattr(block,
                                                       "word_embed"):
            raise MXNetError(
                f"DecodeModel serves decoder-only zoo LMs (GPTModel); "
                f"got {type(block).__name__}")
        params = _collect(block)
        ga = (params.pop("gelu_approx"), params.pop("ln_eps"))
        nh = next(iter(block.blocks._children.values()))._num_heads
        return DecodeModel(params, nh, ga, block._max_length,
                           type(block).__name__)

    # -- execution ----------------------------------------------------------
    def _account(self, tag: str) -> None:
        with self._seen_lock:
            new = tag not in self._seen
            if new:
                self._seen.add(tag)
        if new:
            BUCKET_COMPILES.labels(bucket=tag).inc()

    def prefill(self, tokens: _np.ndarray, bucket_len: int
                ) -> Tuple[_np.ndarray, List[Any], List[Any]]:
        """Run the prompt pass padded to ``bucket_len``; returns
        (last-token logits (V,) numpy, per-layer ks/vs device arrays
        (bucket_len, nh, d))."""
        import jax.numpy as jnp
        toks = _np.asarray(tokens, _np.int32).reshape(-1)
        t0 = toks.shape[0]
        if t0 < 1:
            raise MXNetError("empty prompt")
        if bucket_len < t0:
            raise MXNetError(
                f"prompt length {t0} exceeds its bucket {bucket_len}")
        padded = _np.zeros((bucket_len,), _np.int32)
        padded[:t0] = toks
        self._account(f"prefill:{bucket_len}")
        t = time.perf_counter()
        logits, ks, vs = self._prefill_fn(
            self.params, jnp.asarray(padded), _np.int32(t0))
        out = _np.asarray(logits)
        from .. import metrics as _metrics
        _metrics.GEN_STEP_SECONDS.labels(phase="prefill").observe(
            time.perf_counter() - t)
        return out, ks, vs

    def step(self, cache: Any, tokens: _np.ndarray,
             positions: _np.ndarray) -> _np.ndarray:
        """One resident decode iteration over every slot: consumes the
        cache's buffers (donated), installs the updated ones, returns
        the (S,) int32 greedy next-token vector."""
        import jax.numpy as jnp
        S = cache.max_slots
        self._account(f"decode:{S}x{cache.bucket}")
        t = time.perf_counter()
        toks, new_ks, new_vs = self._step_fn(
            self.params, cache._k, cache._v,
            jnp.asarray(_np.asarray(tokens, _np.int32)),
            jnp.asarray(_np.asarray(positions, _np.int32)))
        cache.replace(new_ks, new_vs)
        out = _np.asarray(toks)
        from .. import metrics as _metrics
        _metrics.GEN_STEP_SECONDS.labels(phase="decode").observe(
            time.perf_counter() - t)
        return out

    def warmup(self, cache: Any, prompt_buckets: Sequence[int]) -> int:
        """Pre-compile the full program grid: one prefill per prompt
        bucket + one decode step per KV capacity bucket (run on the
        cache's own buffer shapes).  After this, traffic confined to
        the grids never compiles."""
        n = 0
        for pb in prompt_buckets:
            self.prefill(_np.zeros((1,), _np.int32), int(pb))
            n += 1
        S = cache.max_slots
        toks = _np.zeros((S,), _np.int32)
        pos = _np.zeros((S,), _np.int32)
        for b in cache.grid:
            # walk the bucket grid directly (not via grow(): warmup
            # must not count as live migrations)
            cache.bucket = int(b)
            cache._alloc_buffers(cache.bucket)
            self.step(cache, toks, pos)
            n += 1
        # hand the cache back at rest on the smallest bucket
        cache.bucket = cache.grid[0]
        cache._alloc_buffers(cache.bucket)
        return n

    def describe(self) -> Dict[str, Any]:
        with self._seen_lock:
            seen = sorted(self._seen)
        return {
            "name": self.name,
            "kind": "decode",
            "vocab_size": int(self.vocab_size),
            "units": int(self.units),
            "layers": self.n_layers,
            "heads": self.num_heads,
            "max_length": self.max_length,
            "dtype": str(self.dtype),
            "programs_compiled": seen,
        }


def _guess_param_file(symbol_file: str) -> Optional[str]:
    """Newest ``prefix-NNNN.params`` next to ``prefix-symbol.json``."""
    if not symbol_file.endswith("-symbol.json"):
        return None
    prefix = symbol_file[:-len("-symbol.json")]
    cands = sorted(
        f for f in (os.listdir(os.path.dirname(prefix) or ".") or [])
        if f.startswith(os.path.basename(prefix) + "-")
        and f.endswith(".params"))
    if not cands:
        return None
    return os.path.join(os.path.dirname(prefix) or ".", cands[-1])


def load_served(model: Any, param_file: Optional[str] = None,
                **kw: Any) -> ServedModel:
    """Sniff ``model`` into a :class:`ServedModel`: an export prefix or
    ``-symbol.json`` path, a Module, or a (Hybrid)Block."""
    if isinstance(model, str):
        sym = model if model.endswith("-symbol.json") \
            else f"{model}-symbol.json"
        return ServedModel.from_export(sym, param_file)
    if hasattr(model, "params_initialized"):        # Module duck-type
        return ServedModel.from_module(model)
    if hasattr(model, "collect_params"):            # gluon Block
        return ServedModel.from_block(model, **kw)
    raise MXNetError(f"cannot serve a {type(model).__name__}")
