"""ServedModel — one ``predict(arrays) -> arrays`` surface over every
way a model reaches the server.

Backends:

* **export artifact** (``HybridBlock.export`` / ``Module.export`` output:
  ``prefix-symbol.json`` + ``prefix-NNNN.params``): the StableHLO
  program is deserialized once and called directly on raw arrays — the
  ``c_predict_api`` analog, no gluon graph in the hot path.  An artifact
  exported with ``dynamic_batch=True`` serves every batch bucket from
  ONE serialized program (shape-polymorphic leading dim); a static
  artifact pins the policy to its exported batch size.
* **live block** (a (Hybrid)Block or Module): hybridized and driven in
  predict mode — per-bucket executables appear through the normal jit
  cache.  The path for models that never went through export (tests,
  notebooks, zoo models).

Both backends share per-bucket compile accounting: the first execution
of each padded batch signature increments
``mxnet_serving_bucket_compiles_total{bucket=...}`` — with a
:class:`~mxnet_tpu.serving.batching.BucketPolicy` in front, that counter
is bounded by the bucket grid, and :meth:`ServedModel.warmup` moves all
of it to startup.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError
from .batching import BUCKET_COMPILES, BucketPolicy, INFER_SECONDS

__all__ = ["ServedModel", "DecodeModel", "load_served"]


def _sig_str(shapes: Sequence[Tuple[int, ...]]) -> str:
    return ";".join("x".join(map(str, s)) for s in shapes)


class ServedModel:
    """A loaded inference model: ``predict`` over numpy batch arrays.

    Build with :meth:`from_export`, :meth:`from_block`,
    :meth:`from_module`, or the path-sniffing :func:`load_served`.
    """

    def __init__(self, fn: Any, input_signature: List[Tuple[Tuple[int, ...],
                                                            Any]],
                 fixed_batch: Optional[int], name: str) -> None:
        self._fn = fn
        # per-input (shape_without_batch, dtype) — what a single request
        # sample must look like
        self.input_signature = input_signature
        # static exports serve exactly their traced batch size
        self.fixed_batch = fixed_batch
        self.name = name
        # guarded: the worker thread adds while /healthz threads read
        self._seen_lock = threading.Lock()
        self._seen_buckets: set = set()

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_export(symbol_file: str,
                    param_file: Optional[str] = None) -> "ServedModel":
        """Load an ``export()`` artifact for serving (the predict-API
        path: StableHLO called directly, no gluon objects per request).

        Artifacts carrying digests (``export()`` emits them) are
        **checksum-verified before deserialization**: a truncated or
        bit-flipped program/params file raises a structured error
        naming the artifact and the expected/actual digests, instead
        of an opaque deserializer crash (or, worse, a model that loads
        and serves garbage).  Per-bucket executables go through the
        persistent compile cache (pinned — a live server's grid is
        never evicted), so a restarted replica re-warms from disk with
        zero XLA compiles."""
        import base64

        import jax
        import jax.numpy as jnp
        from jax import export as jax_export
        from .. import compile_cache as _cc
        from .._durable import sha256_bytes, sha256_file

        with open(symbol_file) as f:
            meta = json.load(f)
        if meta.get("framework") != "mxnet_tpu" or "stablehlo" not in meta:
            raise MXNetError(
                f"{symbol_file} is not an mxnet_tpu export (re-export "
                "with HybridBlock.export)")
        if param_file is None:
            param_file = _guess_param_file(symbol_file)
        program = base64.b64decode(meta["stablehlo"])
        want = meta.get("stablehlo_sha256")
        if want is not None:
            got = sha256_bytes(program)
            if got != want:
                raise MXNetError(
                    f"export artifact {symbol_file} failed its program "
                    f"checksum (stablehlo_sha256 {want[:12]}…, file "
                    f"digests to {got[:12]}…) — the artifact is "
                    "truncated or garbled; re-export or restore it "
                    "before serving")
        order = meta["param_order"]
        params: List[Any] = []
        if order:
            if param_file is None:
                raise MXNetError(
                    "this export has parameters — pass the "
                    "prefix-NNNN.params file (or keep it next to the "
                    "symbol json)")
            want = meta.get("params_sha256")
            if want is not None:
                got = sha256_file(param_file)
                if got != want:
                    raise MXNetError(
                        f"export artifact {param_file} failed its "
                        f"checksum (params_sha256 {want[:12]}…, file "
                        f"digests to {got[:12]}…) — the weights are "
                        "truncated or garbled (or not the file this "
                        "symbol json was exported with); re-export or "
                        "restore them before serving")
            from ..ndarray_io import load_params
            loaded = load_params(param_file)
            missing = [k for k in order if k not in loaded]
            if missing:
                raise MXNetError(
                    f"{param_file} is missing exported params: {missing}")
            params = [jnp.asarray(loaded[k]._data) for k in order]
        exp = jax_export.deserialize(bytearray(program))
        key = jnp.zeros((2,), jnp.uint32)   # inference: dropout is off
        dynamic = bool(meta.get("dynamic_batch"))
        sig = [(tuple(i["shape"][1:]), _np.dtype(i["dtype"]))
               for i in meta["inputs"]]
        fixed = None if dynamic else int(meta["inputs"][0]["shape"][0])

        # params ride as ARGUMENTS (not closure constants): the lowered
        # program — and so the persistent-cache key and the serialized
        # executable — is weight-independent, shared across re-exports
        # of the same architecture
        aot = _cc.persistently_cached(
            jax.jit(lambda ps, *xs: exp.call(key, list(ps), *xs)),
            surface="serving.export", pin=True)
        params_t = tuple(params)

        def fn(arrays: Sequence[_np.ndarray]) -> List[_np.ndarray]:
            jarrs = [jnp.asarray(a) for a in arrays]
            leaves = aot(params_t, *jarrs)
            return [_np.asarray(o) for o in leaves]

        name = os.path.basename(symbol_file).replace("-symbol.json", "")
        return ServedModel(fn, sig, fixed, name or "export")

    @staticmethod
    def from_block(block: Any,
                   input_signature: Optional[Sequence[Tuple[
                       Tuple[int, ...], Any]]] = None) -> "ServedModel":
        """Serve a live (Hybrid)Block.  ``input_signature`` is per-input
        (shape_without_batch, dtype); defaults to the block's last
        hybridized call signature (run it once first)."""
        from .. import autograd
        from ..ndarray.ndarray import NDArray

        if hasattr(block, "hybridize") and not getattr(block, "_active",
                                                       False):
            block.hybridize()
        if input_signature is None:
            last = getattr(block, "_last_sig", None)
            if last is None:
                raise MXNetError(
                    "from_block needs the input signature: run the block "
                    "once, or pass input_signature=[(sample_shape, "
                    "dtype), ...] (shapes WITHOUT the batch dim)")
            input_signature = [(tuple(s[1:]), d) for s, d in last]

        def fn(arrays: Sequence[_np.ndarray]) -> List[_np.ndarray]:
            import jax
            nds = [NDArray(a) for a in arrays]
            with autograd.predict_mode():
                out = block(*nds)
            leaves, _ = jax.tree_util.tree_flatten(
                out, is_leaf=lambda o: isinstance(o, NDArray))
            return [o.asnumpy() for o in leaves]

        sig = [(tuple(s), _np.dtype(d)) for s, d in input_signature]
        return ServedModel(fn, sig, None, type(block).__name__)

    @staticmethod
    def from_module(module: Any) -> "ServedModel":
        """Serve a bound Module's network (inference half of the classic
        workflow)."""
        if not getattr(module, "params_initialized", False):
            raise MXNetError("module must be bound + initialized before "
                             "serving")
        sig = [(tuple(d.shape[1:]) if hasattr(d, "shape")
                else tuple(d[1][1:]),
                getattr(d, "dtype", _np.float32))
               for d in module._data_shapes]
        return ServedModel.from_block(module.symbol, sig)

    # -- execution ----------------------------------------------------------
    def predict(self, arrays: Sequence[_np.ndarray]) -> List[_np.ndarray]:
        """Run one padded batch; returns per-output numpy arrays (axis 0
        = padded batch).  Tracks first-seen batch signatures as bucket
        compiles and times the execution."""
        shapes = tuple(tuple(a.shape) for a in arrays)
        with self._seen_lock:
            new = shapes not in self._seen_buckets
            if new:
                self._seen_buckets.add(shapes)
        if new:
            BUCKET_COMPILES.labels(bucket=_sig_str(shapes)).inc()
        t0 = time.perf_counter()
        out = self._fn(arrays)
        from .. import tracing as _tracing
        INFER_SECONDS.observe(time.perf_counter() - t0,
                              exemplar=_tracing.current_trace_id())
        return out

    def warmup(self, policy: BucketPolicy) -> int:
        """Pre-compile every bucket signature the policy can emit (zeros
        input); returns how many signatures were warmed.  After this, a
        request stream confined to the bucket grid never compiles."""
        n = 0
        for sig in policy.warmup_signatures(self.input_signature):
            if self.fixed_batch is not None \
                    and sig[0][0][0] != self.fixed_batch:
                raise MXNetError(
                    f"static export serves only batch={self.fixed_batch}; "
                    f"configure BucketPolicy(batch_buckets="
                    f"[{self.fixed_batch}]) (or re-export with "
                    "dynamic_batch=True)")
            self.predict([_np.zeros(s, d) for s, d in sig])
            n += 1
        return n

    def default_policy(self, **kw: Any) -> BucketPolicy:
        """A policy consistent with this model (static exports pin the
        batch bucket to the exported batch)."""
        if self.fixed_batch is not None and "batch_buckets" not in kw:
            kw["batch_buckets"] = [self.fixed_batch]
        return BucketPolicy(**kw)

    def describe(self) -> Dict[str, Any]:
        with self._seen_lock:
            seen = list(self._seen_buckets)
        return {
            "name": self.name,
            "inputs": [{"sample_shape": list(s), "dtype": str(d)}
                       for s, d in self.input_signature],
            "fixed_batch": self.fixed_batch,
            "buckets_compiled": sorted(_sig_str(s) for s in seen),
        }


# ---------------------------------------------------------------------------
# DecodeModel — the stateful autoregressive path (continuous batching)
# ---------------------------------------------------------------------------

# decode-method codes: the sampler rides INSIDE the compiled step, so
# the method travels as a traced (S,) int32 operand, never a Python
# constant (a constant would recompile the step per method mix)
METHOD_CODES = {"greedy": 0, "sample": 1, "top_k": 2, "top_p": 3}


def _sample_tokens(logits, seeds, ctrs, temps, topks, topps, methods):
    """Fused per-slot token selection over (S, V) logits — the
    on-device sampler.  Per slot: temperature scaling, then the
    method's filter (top-k kth-largest threshold / top-p nucleus
    threshold), then a categorical draw under the slot's counter-PRNG
    key ``fold_in(PRNGKey(seed), counter)``; greedy slots take the raw
    argmax.  Every parameter is a traced operand, so one executable
    serves every per-request method/parameter mix, and the math
    mirrors ``model_zoo.generation._select`` exactly — the zoo stays
    the host-side parity oracle (pinned in tests)."""
    import jax
    import jax.numpy as jnp

    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    neg = jnp.float32(-jnp.inf).astype(scaled.dtype)
    asc = jnp.sort(scaled, axis=-1)
    # top-k: the kth-largest value is asc[V - k] (k pre-clamped to
    # [1, V] at submit, clipped again here so free slots riding along
    # with k=0 stay finite)
    kidx = jnp.clip(V - topks, 0, V - 1)
    kth_k = jnp.take_along_axis(asc, kidx[:, None], axis=-1)
    # top-p: smallest probability-sorted prefix reaching mass top_p
    # (the most probable token is always kept)
    desc = asc[:, ::-1]
    probs = jax.nn.softmax(desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < topps[:, None]
    kth_p = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1,
                    keepdims=True)
    m = methods[:, None]
    filt = jnp.where((m == 2) & (scaled < kth_k), neg, scaled)
    filt = jnp.where((m == 3) & (filt < kth_p), neg, filt)

    def draw(seed, ctr, row):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), ctr)
        return jax.random.categorical(key, row, axis=-1)

    sampled = jax.vmap(draw)(seeds, ctrs, filt).astype(jnp.int32)
    return jnp.where(methods == 0, greedy, sampled)


def _slot_block_step(p, x, ck, cv, pos, nh: int, ga):
    """One decode token for EVERY slot: ``x`` (S, 1, C), caches
    (S, L, nh, d), ``pos`` (S,) int32 — the per-slot-position variant
    of ``model_zoo.generation._block_step`` (which shares one scalar
    position across the batch; continuous batching cannot)."""
    import math as _math
    import jax
    import jax.numpy as jnp

    gelu_approx, eps = ga
    S, _, C = x.shape
    d = C // nh
    L = ck.shape[1]
    h = _pure_ln(x, p["ln1_g"], p["ln1_b"], eps)
    qkv = h @ p["qkv_w"].T + p["qkv_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    qh = q.reshape(S, 1, nh, d)
    rows = jnp.arange(S)
    # per-slot scatter: slot i writes its k/v at ITS position pos[i]
    ck = ck.at[rows, pos].set(k.reshape(S, nh, d))
    cv = cv.at[rows, pos].set(v.reshape(S, nh, d))
    scores = jnp.einsum("sqhd,skhd->shqk", qh, ck) / _math.sqrt(d)
    # slot i sees cache positions 0..pos[i] (its prompt + its decoded
    # tokens); pad garbage beyond pos[i] stays invisible until the loop
    # overwrites it position by position
    visible = jnp.arange(L)[None, :] <= pos[:, None]          # (S, L)
    scores = jnp.where(visible[:, None, None, :], scores,
                       jnp.float32(-jnp.inf).astype(scores.dtype))
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("shqk,skhd->sqhd", probs, cv).reshape(S, 1, C)
    x = x + (out @ p["out_w"].T + p["out_b"])
    h = _pure_ln(x, p["ln2_g"], p["ln2_b"], eps)
    ffn = jax.nn.gelu(h @ p["f1_w"].T + p["f1_b"],
                      approximate=gelu_approx)
    return x + (ffn @ p["f2_w"].T + p["f2_b"]), ck, cv


def _pure_ln(x, g, b, eps):
    import jax.numpy as jnp
    from jax import lax
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * lax.rsqrt(var + eps) * g + b


def _block_suffix(p, x, pk, pv, q, nh: int, ga):
    """Causal pass over a prompt SUFFIX against resident prefix KV:
    ``x`` (1, Sb, C) embeds suffix tokens at absolute positions
    ``q..q+Sb``, ``pk``/``pv`` (Pb, nh, d) hold the shared prefix's
    rows (valid through traced ``q``; pad garbage past it is masked).
    Returns (x_out, suffix ck/cv (Sb, nh, d)) — the prefix rows are
    already in the cache, only the suffix rows are new."""
    import math as _math
    import jax
    import jax.numpy as jnp

    gelu_approx, eps = ga
    _, T, C = x.shape
    d = C // nh
    Pb = pk.shape[0]
    h = _pure_ln(x, p["ln1_g"], p["ln1_b"], eps)
    qkv = h @ p["qkv_w"].T + p["qkv_b"]
    qq, kk, vv = jnp.split(qkv, 3, axis=-1)
    qh = qq.reshape(T, nh, d)
    kh = kk.reshape(T, nh, d)
    vh = vv.reshape(T, nh, d)
    k_all = jnp.concatenate([pk, kh], axis=0)       # (Pb + T, nh, d)
    v_all = jnp.concatenate([pv, vh], axis=0)
    scores = jnp.einsum("qhd,khd->hqk", qh, k_all) / _math.sqrt(d)
    cols = jnp.arange(Pb + T)
    # suffix position i (absolute q+i) sees: real prefix rows (< q)
    # and suffix rows up to itself (causal); prefix pad garbage in
    # q..Pb stays invisible
    vis = (cols[None, :] < q) | (
        (cols[None, :] >= Pb)
        & (cols[None, :] - Pb <= jnp.arange(T)[:, None]))
    scores = jnp.where(vis[None, :, :], scores,
                       jnp.float32(-jnp.inf).astype(scores.dtype))
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("hqk,khd->qhd", probs, v_all).reshape(1, T, C)
    x = x + (out @ p["out_w"].T + p["out_b"])
    h = _pure_ln(x, p["ln2_g"], p["ln2_b"], eps)
    ffn = jax.nn.gelu(h @ p["f1_w"].T + p["f1_b"],
                      approximate=gelu_approx)
    return x + (ffn @ p["f2_w"].T + p["f2_b"]), kh, vh


class DecodeModel:
    """The decode-capable serving path: a stateful
    ``(params, kv_cache, positions) -> next tokens`` step over slot
    rows, compiled ONCE per KV capacity bucket, plus a per-prompt-bucket
    prefill — the two programs the continuous-batching
    :class:`~mxnet_tpu.serving.generation.GenerationEngine` runs
    resident.

    Built from a live :class:`~mxnet_tpu.gluon.model_zoo.gpt.GPTModel`
    (the zoo's decoder-only family): parameters are extracted once into
    a pure pytree (``model_zoo.generation._collect``) and the decode
    math mirrors the zoo's KV-cache step, extended to per-slot
    positions.  Compile accounting rides the SAME per-bucket counter as
    the one-shot path (``mxnet_serving_bucket_compiles_total``, labels
    ``decode:SxL`` / ``prefill:Lp``), so warmup moves every compile to
    startup and the smoke gate can pin "0 after warmup".
    """

    def __init__(self, params: Any, num_heads: int, ga: Tuple[Any, Any],
                 max_length: int, name: str) -> None:
        import jax

        self.params = params
        self.num_heads = int(num_heads)
        self.ga = (bool(ga[0]), float(ga[1]))
        self.max_length = int(max_length)
        self.name = name
        self.vocab_size, self.units = params["embed"].shape
        self.head_dim = self.units // self.num_heads
        self.n_layers = len(params["blocks"])
        self.dtype = params["blocks"][0]["qkv_w"].dtype
        self._seen_lock = threading.Lock()
        self._seen: set = set()
        nh, ga_s = self.num_heads, self.ga

        def _prefill(params, toks, t0):
            # toks (Lp,) int32 (pad tokens after t0), t0 traced scalar;
            # returns (last-real-token logits (V,), ks/vs lists of
            # (Lp, nh, d)) — garbage pad KV past t0 is masked by the
            # decode position mask until overwritten
            from jax import lax
            from ..gluon.model_zoo.generation import _block_prefill
            Lp = toks.shape[0]
            x = params["embed"][toks][None] + params["pos"][None, :Lp]
            ks, vs = [], []
            for p in params["blocks"]:
                x, ck, cv = _block_prefill(p, x, nh, Lp, ga_s)
                ks.append(ck[0])
                vs.append(cv[0])
            x = _pure_ln(x, params["lnf_g"], params["lnf_b"], ga_s[1])
            h = lax.dynamic_slice_in_dim(x[0], t0 - 1, 1, axis=0)[0]
            return h @ params["embed"].T, ks, vs

        def _step(params, ks, vs, toks, pos, seeds, bases, temps,
                  topks, topps, methods):
            # toks (S,) int32 last emitted per slot, pos (S,) int32
            # write positions; free slots ride along with pos=0 and
            # their outputs are ignored on the host.  The sampling
            # vectors (seed/base/temperature/top-k/top-p/method, all
            # (S,)) are traced operands: per-request parameter changes
            # never recompile the step — and they change only at
            # admission, so the engine reuses their device mirrors
            # across iterations.  The key COUNTER is derived
            # in-program (ctr = pos - base: base is the slot's
            # original prompt length minus its stream offset, minus
            # one) so no per-token host vector rides the hot loop
            x = (params["embed"][toks][:, None, :]
                 + params["pos"][pos][:, None, :])
            new_ks, new_vs = [], []
            for p, ck, cv in zip(params["blocks"], ks, vs):
                x, ck, cv = _slot_block_step(p, x, ck, cv, pos, nh, ga_s)
                new_ks.append(ck)
                new_vs.append(cv)
            x = _pure_ln(x, params["lnf_g"], params["lnf_b"], ga_s[1])
            logits = x[:, 0, :] @ params["embed"].T

            # token selection ON DEVICE (greedy argmax or the fused
            # temperature/top-k/top-p sampler under per-slot counter
            # keys): the host reads back (S,) int32 per iteration,
            # never (S, V) logits.  The sampler rides behind a
            # runtime lax.cond: an all-greedy iteration (the default
            # traffic) executes only the argmax branch, so sampling
            # support costs nothing until a slot actually samples —
            # and it stays ONE executable, so greedy tokens are
            # bit-identical whichever branch the batch composition
            # selects (argmax is comparison-only, no FP reassociation)
            def _mixed(lg):
                return _sample_tokens(lg, seeds, pos - bases, temps,
                                      topks, topps, methods)

            def _greedy(lg):
                import jax.numpy as jnp
                return jnp.argmax(lg, axis=-1).astype(jnp.int32)

            from jax import lax
            import jax.numpy as jnp
            next_tok = lax.cond(jnp.any(methods != 0), _mixed,
                                _greedy, logits)
            return next_tok, new_ks, new_vs

        def _verify(params, ks, vs, toks, pos, seeds, bases, temps,
                    topks, topps, methods):
            # speculative verification: toks (S, K1) int32 — column 0
            # is each slot's last emitted token, columns 1.. the draft
            # proposals; pos (S,) the write position of column 0.  The
            # program is K1 UNROLLED repetitions of the single-token
            # step (same ``_slot_block_step`` math, same shapes per
            # sub-step, same lax.cond'd sampler), each scattering its
            # K/V at pos+j and sampling under counter pos+j-base — so
            # the token this pass computes at any position is
            # BIT-IDENTICAL to what the sequential one-token step
            # would have computed there (the byte-identical-streams
            # contract CI pins).  Inputs past the accepted prefix feed
            # garbage forward; the host discards those columns and
            # rolls their KV rows back (PagedKVCache.truncate)
            from jax import lax
            import jax.numpy as jnp
            K1 = toks.shape[1]
            outs = []
            for j in range(K1):
                x = (params["embed"][toks[:, j]][:, None, :]
                     + params["pos"][pos + j][:, None, :])
                new_ks, new_vs = [], []
                for p, ck, cv in zip(params["blocks"], ks, vs):
                    x, ck, cv = _slot_block_step(p, x, ck, cv, pos + j,
                                                 nh, ga_s)
                    new_ks.append(ck)
                    new_vs.append(cv)
                ks, vs = new_ks, new_vs
                x = _pure_ln(x, params["lnf_g"], params["lnf_b"],
                             ga_s[1])
                logits = x[:, 0, :] @ params["embed"].T

                def _mixed(lg, _j=j):
                    return _sample_tokens(lg, seeds, (pos + _j) - bases,
                                          temps, topks, topps, methods)

                def _greedy(lg):
                    return jnp.argmax(lg, axis=-1).astype(jnp.int32)

                outs.append(lax.cond(jnp.any(methods != 0), _mixed,
                                     _greedy, logits))
            return jnp.stack(outs, axis=1), ks, vs

        def _prefill_sfx(params, pre_ks, pre_vs, toks, q, t0):
            # suffix pass for shared-prefix admissions: pre_ks/pre_vs
            # are the resident prefix rows (Pb, nh, d) per layer, toks
            # (Sb,) the pad-bucketed suffix, q the traced real prefix
            # length, t0 the traced real suffix length.  Returns the
            # last-real-suffix-token logits + the SUFFIX KV rows only
            from jax import lax
            Sb = toks.shape[0]
            x = (params["embed"][toks][None]
                 + lax.dynamic_slice_in_dim(params["pos"], q, Sb,
                                            axis=0)[None])
            ks_o, vs_o = [], []
            for p, pk, pv in zip(params["blocks"], pre_ks, pre_vs):
                x, ck, cv = _block_suffix(p, x, pk, pv, q, nh, ga_s)
                ks_o.append(ck)
                vs_o.append(cv)
            x = _pure_ln(x, params["lnf_g"], params["lnf_b"], ga_s[1])
            h = lax.dynamic_slice_in_dim(x[0], t0 - 1, 1, axis=0)[0]
            return h @ params["embed"].T, ks_o, vs_o

        def _select_one(logits, seed, ctr, temp, topk, topp, method):
            # the first-token selector (prefill logits -> token): the
            # SAME fused sampler on one row, so host-emitted first
            # tokens and step-emitted tokens share one code path and
            # one key-stream discipline
            return _sample_tokens(
                logits[None], seed[None], ctr[None], temp[None],
                topk[None], topp[None], method[None])[0]

        # all programs persist through the compile cache (pinned: a
        # live server's decode grid is never evicted) so a restarted
        # replica re-warms its whole bucket grid with zero XLA compiles
        from .. import compile_cache as _cc
        self._prefill_fn = _cc.persistently_cached(
            jax.jit(_prefill), surface="serving.decode", pin=True)
        self._prefill_sfx_fn = _cc.persistently_cached(
            jax.jit(_prefill_sfx), surface="serving.decode", pin=True)
        self._select_fn = _cc.persistently_cached(
            jax.jit(_select_one), surface="serving.decode", pin=True)
        # the KV buffers are DONATED: XLA updates the resident cache in
        # place instead of allocating a fresh (S, L, h, d) per layer
        # every token
        self._step_fn = _cc.persistently_cached(
            jax.jit(_step, donate_argnums=(1, 2)),
            surface="serving.decode", pin=True)
        # same donation contract as _step: verify scatters k+1 rows
        # into the resident buffers in place; rejected rows are
        # invisible (visibility mask <= pos) until overwritten
        self._verify_fn = _cc.persistently_cached(
            jax.jit(_verify, donate_argnums=(1, 2)),
            surface="serving.decode", pin=True)

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_block(block: Any) -> "DecodeModel":
        """Build from a live zoo ``GPTModel`` (weights as currently
        initialized/loaded; MoE decode is not supported yet — same
        restriction as ``model_zoo.generation``)."""
        from ..gluon.model_zoo.generation import _collect
        if not hasattr(block, "blocks") or not hasattr(block,
                                                       "word_embed"):
            raise MXNetError(
                f"DecodeModel serves decoder-only zoo LMs (GPTModel); "
                f"got {type(block).__name__}")
        params = _collect(block)
        ga = (params.pop("gelu_approx"), params.pop("ln_eps"))
        nh = next(iter(block.blocks._children.values()))._num_heads
        return DecodeModel(params, nh, ga, block._max_length,
                           type(block).__name__)

    # -- execution ----------------------------------------------------------
    def _account(self, tag: str) -> None:
        with self._seen_lock:
            new = tag not in self._seen
            if new:
                self._seen.add(tag)
        if new:
            BUCKET_COMPILES.labels(bucket=tag).inc()

    def prefill(self, tokens: _np.ndarray, bucket_len: int
                ) -> Tuple[_np.ndarray, List[Any], List[Any]]:
        """Run the prompt pass padded to ``bucket_len``; returns
        (last-token logits (V,) numpy, per-layer ks/vs device arrays
        (bucket_len, nh, d))."""
        import jax.numpy as jnp
        toks = _np.asarray(tokens, _np.int32).reshape(-1)
        t0 = toks.shape[0]
        if t0 < 1:
            raise MXNetError("empty prompt")
        if bucket_len < t0:
            raise MXNetError(
                f"prompt length {t0} exceeds its bucket {bucket_len}")
        padded = _np.zeros((bucket_len,), _np.int32)
        padded[:t0] = toks
        self._account(f"prefill:{bucket_len}")
        t = time.perf_counter()
        logits, ks, vs = self._prefill_fn(
            self.params, jnp.asarray(padded), _np.int32(t0))
        out = _np.asarray(logits)
        from .. import metrics as _metrics
        from .. import tracing as _tracing
        _metrics.GEN_STEP_SECONDS.labels(phase="prefill").observe(
            time.perf_counter() - t,
            exemplar=_tracing.current_trace_id())
        return out, ks, vs

    def greedy_sampling(self, n_slots: int) -> Tuple[_np.ndarray, ...]:
        """All-greedy per-slot sampling vectors (seed, counter base,
        temperature, top_k, top_p, method) — the default when no slot
        asked for sampling."""
        return (_np.zeros((n_slots,), _np.int32),
                _np.zeros((n_slots,), _np.int32),
                _np.ones((n_slots,), _np.float32),
                _np.ones((n_slots,), _np.int32),
                _np.ones((n_slots,), _np.float32),
                _np.zeros((n_slots,), _np.int32))

    def device_sampling(self, sampling: Sequence[_np.ndarray]
                        ) -> Tuple[Any, ...]:
        """Device mirrors of the per-slot sampling vectors, dtype
        canonicalized.  The engine caches the result across
        iterations (the lanes change only at admission/retirement),
        keeping the per-iteration host->device traffic at exactly the
        pre-sampling two arrays (tokens + positions)."""
        import jax.numpy as jnp
        seeds, bases, temps, topks, topps, methods = sampling
        return (jnp.asarray(_np.asarray(seeds, _np.int32)),
                jnp.asarray(_np.asarray(bases, _np.int32)),
                jnp.asarray(_np.asarray(temps, _np.float32)),
                jnp.asarray(_np.asarray(topks, _np.int32)),
                jnp.asarray(_np.asarray(topps, _np.float32)),
                jnp.asarray(_np.asarray(methods, _np.int32)))

    def step(self, cache: Any, tokens: _np.ndarray,
             positions: _np.ndarray,
             sampling: Optional[Sequence[Any]] = None
             ) -> _np.ndarray:
        """One resident decode iteration over every slot: consumes the
        cache's buffers (donated), installs the updated ones, returns
        the (S,) int32 next-token vector (greedy or sampled per slot —
        ``sampling`` is the (seeds, counter bases, temperatures,
        top_ks, top_ps, methods) vectors, host or device
        (:meth:`device_sampling`); None means all-greedy)."""
        import jax
        import jax.numpy as jnp
        S = cache.max_slots
        if sampling is None:
            sampling = self.greedy_sampling(S)
        if not isinstance(sampling[0], jax.Array):
            # host vectors: one-shot callers; the engine hands in its
            # cached device mirrors instead
            sampling = self.device_sampling(sampling)
        seeds, bases, temps, topks, topps, methods = sampling
        self._account(f"decode:{S}x{cache.bucket}")
        t = time.perf_counter()
        toks, new_ks, new_vs = self._step_fn(
            self.params, cache._k, cache._v,
            jnp.asarray(_np.asarray(tokens, _np.int32)),
            jnp.asarray(_np.asarray(positions, _np.int32)),
            seeds, bases, temps, topks, topps, methods)
        cache.replace(new_ks, new_vs)
        out = _np.asarray(toks)
        from .. import metrics as _metrics
        from .. import tracing as _tracing
        _metrics.GEN_STEP_SECONDS.labels(phase="decode").observe(
            time.perf_counter() - t,
            exemplar=_tracing.current_trace_id())
        return out

    def verify(self, cache: Any, tokens: _np.ndarray,
               positions: _np.ndarray,
               sampling: Optional[Sequence[Any]] = None
               ) -> _np.ndarray:
        """One speculative verification pass over every slot:
        ``tokens`` is (S, k+1) int32 — column 0 each slot's last
        emitted token, columns 1.. the k draft proposals — and the
        return is the (S, k+1) int32 target tokens for those
        positions, each bit-identical to what ``step`` would have
        produced sequentially (same kernel math, same counter-PRNG
        lanes).  The cache's buffers gain k+1 rows per slot starting
        at ``positions``; the caller owns acceptance and rolls back
        rejected rows via ``cache.truncate``.  One compiled program
        per (S, bucket, k+1) triple, persistently cached like the
        decode grid."""
        import jax
        import jax.numpy as jnp
        S = cache.max_slots
        toks = _np.asarray(tokens, _np.int32)
        if toks.ndim != 2 or toks.shape[0] != S or toks.shape[1] < 2:
            raise MXNetError(
                f"verify wants an (S, k+1) token matrix with k >= 1; "
                f"got shape {toks.shape} for {S} slots")
        if sampling is None:
            sampling = self.greedy_sampling(S)
        if not isinstance(sampling[0], jax.Array):
            sampling = self.device_sampling(sampling)
        seeds, bases, temps, topks, topps, methods = sampling
        self._account(f"verify:{S}x{cache.bucket}x{toks.shape[1]}")
        t = time.perf_counter()
        out_toks, new_ks, new_vs = self._verify_fn(
            self.params, cache._k, cache._v,
            jnp.asarray(toks),
            jnp.asarray(_np.asarray(positions, _np.int32)),
            seeds, bases, temps, topks, topps, methods)
        cache.replace(new_ks, new_vs)
        out = _np.asarray(out_toks)
        from .. import metrics as _metrics
        from .. import tracing as _tracing
        _metrics.GEN_STEP_SECONDS.labels(phase="verify").observe(
            time.perf_counter() - t,
            exemplar=_tracing.current_trace_id())
        return out

    def prefill_suffix(self, tokens: _np.ndarray, prefix_ks: List[Any],
                       prefix_vs: List[Any], q: int, bucket_len: int
                       ) -> Tuple[_np.ndarray, List[Any], List[Any]]:
        """Run the prompt pass over only the SUFFIX ``tokens`` (real
        positions ``q..q+len``) against resident prefix K/V rows —
        the shared-prefix admission path.  Returns (last-real-token
        logits (V,) numpy, per-layer suffix ks/vs (bucket_len, nh,
        d)).  One compiled program per (prefix bucket, suffix bucket)
        pair; ``q`` and the real suffix length are traced operands."""
        import jax.numpy as jnp
        toks = _np.asarray(tokens, _np.int32).reshape(-1)
        t0 = toks.shape[0]
        if t0 < 1:
            raise MXNetError("empty prompt suffix")
        if bucket_len < t0:
            raise MXNetError(
                f"suffix length {t0} exceeds its bucket {bucket_len}")
        padded = _np.zeros((bucket_len,), _np.int32)
        padded[:t0] = toks
        Pb = int(prefix_ks[0].shape[0])
        self._account(f"prefill_sfx:{Pb}x{bucket_len}")
        t = time.perf_counter()
        logits, ks, vs = self._prefill_sfx_fn(
            self.params, list(prefix_ks), list(prefix_vs),
            jnp.asarray(padded), _np.int32(q), _np.int32(t0))
        out = _np.asarray(logits)
        from .. import metrics as _metrics
        from .. import tracing as _tracing
        _metrics.GEN_STEP_SECONDS.labels(phase="prefill").observe(
            time.perf_counter() - t,
            exemplar=_tracing.current_trace_id())
        return out, ks, vs

    def select(self, logits: _np.ndarray, seed: int, counter: int,
               temperature: float, top_k: int, top_p: float,
               method: int) -> int:
        """First-token selection over prefill logits — the single-row
        twin of the in-step sampler (same fused code path, same
        ``fold_in(PRNGKey(seed), counter)`` key stream), so a
        sequence's token at index ``i`` is identical whether the
        prefill or the decode step emitted it (the resurrection
        replay-from-transcript contract extends to sampling)."""
        import jax.numpy as jnp
        # logits keep the model dtype: the step's sampler sees the
        # same representation, so the two paths stay bit-identical
        tok = self._select_fn(
            jnp.asarray(logits),
            _np.int32(seed), _np.int32(counter),
            _np.float32(temperature), _np.int32(top_k),
            _np.float32(top_p), _np.int32(method))
        return int(tok)

    def warmup(self, cache: Any, prompt_buckets: Sequence[int],
               suffix_pairs: bool = True) -> int:
        """Pre-compile the full program grid: one prefill per prompt
        bucket, one suffix prefill per (prefix bucket, suffix bucket)
        pair (the shared-prefix admission path; skipped when the
        prefix cache is disabled), the first-token selector, and one
        decode step per KV capacity bucket (run on the cache's own
        buffer shapes).  After this, traffic confined to the grids
        never compiles."""
        import jax
        n = 0
        for pb in prompt_buckets:
            self.prefill(_np.zeros((1,), _np.int32), int(pb))
            n += 1
        # one call warms the selector for every method (the method is
        # a traced operand — a single executable)
        self.select(_np.zeros((self.vocab_size,), self.dtype),
                    seed=0, counter=0, temperature=1.0, top_k=1,
                    top_p=1.0, method=0)
        n += 1
        if suffix_pairs:
            dev = jax.local_devices()[0]
            top = max(int(pb) for pb in prompt_buckets)
            rows = {int(pb): [jax.device_put(
                _np.zeros((int(pb), self.num_heads, self.head_dim),
                          self.dtype), dev)
                for _ in range(self.n_layers)]
                for pb in prompt_buckets}
            for Pb in prompt_buckets:
                for Sb in prompt_buckets:
                    if int(Pb) + int(Sb) > top:
                        # unreachable at runtime: entries store
                        # bucket-aligned prefixes (Pb == q) and the
                        # admission capacity rule bounds q + Sb by the
                        # top prompt bucket — compiling these pairs
                        # would only inflate warmup and the persistent
                        # cache
                        continue
                    self.prefill_suffix(
                        _np.zeros((1,), _np.int32), rows[int(Pb)],
                        rows[int(Pb)], q=1, bucket_len=int(Sb))
                    n += 1
        S = cache.max_slots
        toks = _np.zeros((S,), _np.int32)
        pos = _np.zeros((S,), _np.int32)
        for b in cache.grid:
            # walk the bucket grid directly (not via grow(): warmup
            # must not count as live migrations)
            cache.bucket = int(b)
            cache._alloc_buffers(cache.bucket)
            self.step(cache, toks, pos)
            n += 1
        # hand the cache back at rest on the smallest bucket
        cache.bucket = cache.grid[0]
        cache._alloc_buffers(cache.bucket)
        return n

    def describe(self) -> Dict[str, Any]:
        with self._seen_lock:
            seen = sorted(self._seen)
        return {
            "name": self.name,
            "kind": "decode",
            "vocab_size": int(self.vocab_size),
            "units": int(self.units),
            "layers": self.n_layers,
            "heads": self.num_heads,
            "max_length": self.max_length,
            "dtype": str(self.dtype),
            "programs_compiled": seen,
        }


def _guess_param_file(symbol_file: str) -> Optional[str]:
    """Newest ``prefix-NNNN.params`` next to ``prefix-symbol.json``."""
    if not symbol_file.endswith("-symbol.json"):
        return None
    prefix = symbol_file[:-len("-symbol.json")]
    cands = sorted(
        f for f in (os.listdir(os.path.dirname(prefix) or ".") or [])
        if f.startswith(os.path.basename(prefix) + "-")
        and f.endswith(".params"))
    if not cands:
        return None
    return os.path.join(os.path.dirname(prefix) or ".", cands[-1])


def load_served(model: Any, param_file: Optional[str] = None,
                **kw: Any) -> ServedModel:
    """Sniff ``model`` into a :class:`ServedModel`: an export prefix or
    ``-symbol.json`` path, a Module, or a (Hybrid)Block."""
    if isinstance(model, str):
        sym = model if model.endswith("-symbol.json") \
            else f"{model}-symbol.json"
        return ServedModel.from_export(sym, param_file)
    if hasattr(model, "params_initialized"):        # Module duck-type
        return ServedModel.from_module(model)
    if hasattr(model, "collect_params"):            # gluon Block
        return ServedModel.from_block(model, **kw)
    raise MXNetError(f"cannot serve a {type(model).__name__}")
