"""ModelServer / GenerationServer — batcher + model + replicated workers.

The concurrency shape mirrors the device reality: a worker drains the
queue and executes batches (a single accelerator runs one program at a
time; a second in-flight batch would only queue inside the runtime),
while any number of producer threads — the HTTP front end's
per-connection threads, in-process callers — submit requests and wait on
futures.  Backpressure is therefore explicit and bounded: the queue
limit and the deadline are the only places a request can wait.

Since ISSUE 7 the worker is no longer a single point of failure.  Both
servers host ``MXNET_SERVING_REPLICAS`` worker replicas behind a
router, and worker death is a *routine, bounded* event:

* a dead ``ModelServer`` worker's in-flight batch **requeues** to the
  surviving workers (unresolved futures only — the future is the
  exactly-once boundary for one-shot inference);
* a dead ``GenerationServer`` worker's engine is **evacuated**: queued
  requests requeue, and slot-resident sequences are **resurrected** on
  a healthy replica by re-prefilling ``prompt + tokens already
  emitted`` — greedy decode is deterministic, so the recovered stream
  is token-identical to a fault-free run, and the
  :class:`~mxnet_tpu.serving.generation.TokenStream` index dedupe makes
  the join exactly-once on the wire;
* the :class:`~mxnet_tpu.serving.replica.ReplicaSupervisor` restarts
  the dead replica with jittered backoff behind a per-replica circuit
  breaker; when every replica exhausts its budget the server degrades
  EXPLICITLY (structured :class:`DegradedError`, readiness 503,
  liveness 200) instead of crash-looping;
* SIGTERM triggers a **graceful drain**
  (:func:`serve_until_preempted`): admissions shed with 429, resident
  work finishes within ``MXNET_SERVING_DRAIN_DEADLINE_S``, readiness
  drops out of rotation first, and the process exits 0.
"""
from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as _np

from ..base import MXNetError, getenv
from .. import faults as _faults
from .. import metrics as _metrics
from .. import tracing as _tracing
from .batching import (BucketPolicy, DynamicBatcher, OverloadError,
                       REQUESTS_TOTAL, Request)
from .generation import GenRequest, make_recovery_request
from .model import ServedModel
from .replica import ReplicaSupervisor

__all__ = ["ModelServer", "GenerationServer", "DegradedError",
           "serve_until_preempted"]

_LOG = logging.getLogger("mxnet_tpu.serving")


def _compile_cache_stats() -> Dict[str, Any]:
    """Persistent compile-cache stats for /v1/model ({} when the cache
    is disabled) — operators see at a glance whether a restarted
    replica's warmup came from disk."""
    from .. import compile_cache as _cc
    try:
        return _cc.cache_stats()
    except Exception:   # noqa: BLE001 - introspection must never fail
        return {}


class DegradedError(MXNetError):
    """The server cannot take requests (circuit breaker open, every
    worker replica dead, or stopped) — the HTTP front end maps this to
    503, distinct from caller errors."""


class ModelServer:
    """Serve a :class:`~mxnet_tpu.serving.model.ServedModel` behind a
    dynamic micro-batching queue.

    In-process API::

        server = ModelServer(load_served("model"), warmup=True)
        server.start()
        y = server.infer(x_np)               # blocking, one sample
        fut = server.infer_async(x_np)       # concurrent.futures.Future
        server.stop()

    ``infer`` raises :class:`OverloadError` when the request is shed
    (bounded queue / deadline / draining) — callers back off; the
    server never crashes or grows its queue without bound.
    ``replicas`` worker threads (default ``MXNET_SERVING_REPLICAS``)
    drain the shared queue; a dead worker's batch requeues to the
    survivors while the supervisor restarts it.
    """

    def __init__(self, model: ServedModel,
                 policy: Optional[BucketPolicy] = None,
                 timeout_ms: Optional[float] = None,
                 queue_limit: Optional[int] = None,
                 warmup: bool = False,
                 replicas: Optional[int] = None,
                 max_restarts: Optional[int] = None,
                 restart_backoff_ms: Optional[float] = None) -> None:
        self.model = model
        self.policy = policy if policy is not None \
            else model.default_policy()
        if model.fixed_batch is not None and \
                tuple(self.policy.batch_buckets) != (model.fixed_batch,):
            raise MXNetError(
                f"static export serves only batch={model.fixed_batch}; "
                f"the policy's batch_buckets must be "
                f"[{model.fixed_batch}]")
        self.batcher = DynamicBatcher(self.policy, timeout_ms=timeout_ms,
                                      queue_limit=queue_limit)
        self._default_deadline_s = \
            float(getenv("MXNET_SERVING_DEADLINE_MS", 0)) / 1e3
        if replicas is None:
            replicas = int(getenv("MXNET_SERVING_REPLICAS", 1))
        self.replicas = max(1, int(replicas))
        self._workers: Dict[int, threading.Thread] = {}
        self._started = False
        self._stopping = False
        self._degraded = False
        # per-worker batch currently executing: a dying worker's batch
        # requeues to the survivors; stop() fails whatever remains
        self._inflight: Dict[int, List[Request]] = {}
        self._lock = threading.Lock()
        self.supervisor = ReplicaSupervisor(
            "oneshot", self.replicas, self._spawn_worker,
            self._on_degraded, self._worker_alive,
            max_restarts=max_restarts, backoff_ms=restart_backoff_ms)
        # warmup runs BEFORE start()/ready(): a prewarming server never
        # flips /healthz ready with an un-compiled bucket grid.  With
        # the persistent compile cache populated, this is a disk reload
        # (seconds), not a compile storm — warmup_seconds in /v1/model
        # is the number that proves it
        self.warmed = 0
        self.warmup_seconds = 0.0
        if warmup:
            t0 = time.perf_counter()
            self.warmed = model.warmup(self.policy)
            self.warmup_seconds = time.perf_counter() - t0

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ModelServer":
        if self._started:
            return self
        if self.batcher._closed:
            raise MXNetError(
                "ModelServer cannot restart after stop(): the batcher is "
                "closed (build a fresh ModelServer)")
        self._started = True
        for wid in range(self.replicas):
            self._spawn_worker(wid)
        return self

    def _spawn_worker(self, wid: int) -> None:
        t = threading.Thread(target=self._run, args=(wid,),
                             name=f"mxnet-serving-worker-{wid}",
                             daemon=True)
        with self._lock:
            self._workers[wid] = t
        t.start()

    def _worker_alive(self, wid: int) -> bool:
        t = self._workers.get(wid)
        return bool(t is not None and t.is_alive())

    def stop(self, timeout: float = 10.0) -> None:
        if not self._started:
            return
        self._stopping = True
        self.supervisor.stop()
        self.batcher.close()
        deadline = time.monotonic() + timeout
        for t in list(self._workers.values()):
            t.join(max(0.0, deadline - time.monotonic()))
        # strand nothing: a batch still executing when the join timed
        # out (or whose worker died) holds futures no one will ever
        # complete — fail them with a structured shutdown error so HTTP
        # clients and in-process callers unblock deterministically
        self._fail_inflight(MXNetError(
            "ModelServer stopped with the request still in flight "
            "(shutdown)"))
        self._started = False

    def _fail_inflight(self, exc: Exception) -> None:
        with self._lock:
            batches = list(self._inflight.values())
            self._inflight.clear()
        for batch in batches:
            for r in batch:
                if not r.future.done():
                    try:
                        r.future.set_exception(exc)
                    except Exception:   # noqa: BLE001 - done() race
                        continue
                    REQUESTS_TOTAL.labels(status="error").inc()

    # -- health split -------------------------------------------------------
    @property
    def draining(self) -> bool:
        return self.batcher.draining

    @property
    def degraded(self) -> bool:
        return self._degraded

    def ready(self) -> bool:
        """Readiness: in rotation for NEW traffic — started, breaker
        closed, not draining, and at least one worker serving or coming
        back.  The load balancer keys on this."""
        return bool(self._started and not self._degraded
                    and not self.draining
                    and self.supervisor.in_rotation() > 0)

    def healthy(self) -> bool:
        """Back-compat alias for :meth:`ready` (pre-replica callers)."""
        return self.ready()

    def __enter__(self) -> "ModelServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- drain --------------------------------------------------------------
    def start_drain(self) -> None:
        """Stop admissions (new submits shed 429 ``draining``); queued
        and in-flight work keeps executing."""
        _metrics.SERVING_DRAINING.set(1)
        self.batcher.start_drain()

    def await_drained(self, timeout: float = 1.0) -> bool:
        """Poll until no request is queued or in flight (or timeout)."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                idle = not any(self._inflight.values())
            if idle and len(self.batcher) == 0:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)

    def drain(self, deadline_s: Optional[float] = None,
              stop_timeout: float = 10.0) -> bool:
        """Graceful shutdown: stop admissions, finish resident work
        within ``deadline_s`` (default
        ``MXNET_SERVING_DRAIN_DEADLINE_S``), then stop.  Returns True
        when everything finished inside the budget."""
        if deadline_s is None:
            deadline_s = float(
                getenv("MXNET_SERVING_DRAIN_DEADLINE_S", 30))
        self.start_drain()
        drained = self.await_drained(float(deadline_s))
        self.stop(timeout=stop_timeout)
        return drained

    # -- breaker ------------------------------------------------------------
    def _on_degraded(self, exc: BaseException) -> None:
        """Every replica exhausted its restart budget: explicit
        degraded mode — fail everything held, refuse new work."""
        self._degraded = True
        err = MXNetError(
            f"ModelServer worker thread died repeatedly "
            f"({self.supervisor.max_restarts} restarts per replica "
            f"spent); circuit breaker tripped — the server is degraded "
            f"(last error: {exc!r}); reset_breaker() or restart")
        self._fail_inflight(err)
        self.batcher.close(error=err)
        _LOG.error(
            "serving worker crash-loop: breaker tripped after %d "
            "restarts/replica — /healthz now reports degraded (503); "
            "reset_breaker() re-admits traffic (last error: %r)",
            self.supervisor.max_restarts, exc)

    def reset_breaker(self) -> None:
        """Operator acknowledgement that the crash cause is gone:
        refill every restart budget, reopen the queue, and respawn dead
        workers — traffic re-admits immediately."""
        if not self._started:
            raise MXNetError("reset_breaker() on a stopped server — "
                             "build and start a fresh one")
        self.supervisor.reset()
        self._degraded = False
        self.batcher.reopen()
        for wid in range(self.replicas):
            if not self._worker_alive(wid):
                self._spawn_worker(wid)

    # -- request API --------------------------------------------------------
    def infer_async(self, *sample: _np.ndarray,
                    deadline_ms: Optional[float] = None) -> Future:
        """Submit one sample (per-input arrays WITHOUT the batch dim);
        returns a Future of the per-output arrays (list, or the single
        array for single-output models)."""
        if not self._started:
            raise MXNetError("ModelServer.start() first")
        if self._degraded:
            # a tripped breaker would park this future forever — fail
            # the submit instead so clients back off / fail over
            raise DegradedError(
                "ModelServer worker replicas are crash-looping and the "
                "circuit breaker is open; the server is degraded "
                "(healthz reports 503) — reset_breaker() or restart it")
        arrays = [_np.asarray(a) for a in sample]
        sig = self.model.input_signature
        if len(arrays) != len(sig):
            raise MXNetError(
                f"model {self.model.name} takes {len(sig)} inputs, "
                f"got {len(arrays)}")
        for i, (a, (shape, dtype)) in enumerate(zip(arrays, sig)):
            got = tuple(a.shape)
            if i == 0 and self.policy.pad_axis is not None:
                # only the bucketed axis may vary — every other dim must
                # match, or each distinct wrong shape would become a
                # fresh bucket key (an unbounded-compile hole) or be
                # silently zero-padded into wrong answers
                ax = self.policy.pad_axis
                if len(got) != len(shape) or any(
                        g != s for j, (g, s) in enumerate(zip(got, shape))
                        if j != ax):
                    raise MXNetError(
                        f"sample shape {got} != model input {shape} "
                        f"(batch dim excluded; only axis {ax} is "
                        "length-bucketed)")
            elif got != tuple(shape):
                raise MXNetError(
                    f"sample shape {got} != model input {tuple(shape)} "
                    "(batch dim excluded); enable length bucketing "
                    "(pad_axis/length_buckets) for variable-shape "
                    "requests")
        key = self.policy.bucket_key(arrays)
        if deadline_ms is None and self._default_deadline_s > 0:
            deadline_ms = self._default_deadline_s * 1e3
        deadline_t = (time.monotonic() + deadline_ms / 1e3
                      if deadline_ms else None)
        fut: Future = Future()
        self.batcher.submit(Request(arrays, key, fut, deadline_t))
        return fut

    def infer(self, *sample: _np.ndarray,
              deadline_ms: Optional[float] = None,
              timeout: float = 60.0) -> Any:
        """Blocking single-sample inference (the in-process API)."""
        return self.infer_async(*sample,
                                deadline_ms=deadline_ms).result(timeout)

    # -- worker -------------------------------------------------------------
    def _run(self, wid: int) -> None:
        def take(batch: List[Request]) -> None:
            # runs under the batcher lock: no queued-nor-inflight gap
            # for a drain poll to mistake for idleness
            with self._lock:
                self._inflight[wid] = batch

        try:
            while True:
                batch = self.batcher.next_batch(on_take=take)
                if batch is None:
                    return
                # the worker-death chaos site: an injected error here
                # (NOT per-request handling) kills this worker thread
                _faults.maybe_fault("serving.worker", worker=wid,
                                    batch=len(batch))
                try:
                    self._execute(batch)
                except Exception:   # noqa: BLE001 - the worker must
                    # outlive any per-batch surprise (a dead worker is a
                    # wedged replica); per-request faults were
                    # already set
                    pass
                # cleared only on survival: a BaseException must leave
                # the batch visible to the death handler below
                with self._lock:
                    self._inflight.pop(wid, None)
        except BaseException as e:   # noqa: BLE001 - worker death is a
            # replica-level event: requeue its batch to the survivors
            # and let the supervisor restart it; re-raising inside a
            # worker thread would only reach threading.excepthook
            self._on_worker_death(wid, e)

    def _on_worker_death(self, wid: int, exc: BaseException) -> None:
        if self._stopping or self.batcher._closed:
            # shutdown races a death: keep the old deterministic
            # behavior — fail this worker's batch so no caller blocks
            self._fail_inflight(MXNetError(
                f"ModelServer worker thread died: {exc!r}; the server "
                "is stopping"))
            return
        with self._lock:
            batch = self._inflight.pop(wid, None)
        if batch:
            # the future is the exactly-once boundary: only unresolved
            # requests re-execute
            self.batcher.requeue(batch)
        _LOG.error(
            "serving worker %d died: %r — batch requeued to surviving "
            "replicas; supervisor restarting with backoff", wid, exc)
        self.supervisor.notify_death(wid, exc)

    def _execute(self, batch: List[Request]) -> None:
        try:
            # the execute span is its own (head-sampled) trace — a
            # batch serves many requests, so it LINKS each request's
            # trace id instead of parenting under any one of them
            with _tracing.span("serving.execute",
                               batch=len(batch)) as xsp:
                for _r in batch:
                    _tr = getattr(_r, "trace", None)
                    if _tr is not None:
                        xsp.add_link(_tr.trace_id)
                _faults.maybe_fault("serving.execute", batch=len(batch))
                arrays, _nb = self.policy.assemble(
                    [r.sample for r in batch], batch[0].key)
                # per-batch execute deadline: the training hang
                # watchdog reused for serving
                # (MXNET_HEALTH_STEP_DEADLINE_S) — a wedged model
                # execute dumps all-thread stacks instead of silently
                # eating the queue's deadline budget
                from .. import health as _health
                with _health.watch_section("serving.execute",
                                           batch=len(batch)):
                    outs = self.model.predict(arrays)
        except Exception as e:   # noqa: BLE001 - worker must survive
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)
                    REQUESTS_TOTAL.labels(status="error").inc()
            return
        for i, r in enumerate(batch):
            if r.future.done():
                # cancelled (or shed) while queued/executing: a result
                # set now would raise InvalidStateError
                continue
            rows = [o[i] for o in outs]
            if self.policy.pad_axis is not None:
                # slice length padding back off axis pad_axis of each
                # output that still carries the padded extent
                rows = self._strip_length(rows, r)
            try:
                r.future.set_result(rows[0] if len(rows) == 1 else rows)
            except Exception:   # noqa: BLE001 - cancelled in the
                continue        # done()->here window; keep distributing
            REQUESTS_TOTAL.labels(status="ok").inc()

    def _strip_length(self, rows: List[_np.ndarray],
                      req: Request) -> List[_np.ndarray]:
        """Heuristic by necessity: outputs carry no axis metadata, so an
        output is taken to keep the length axis when it has at least the
        sample's rank AND the padded extent at pad_axis.  Requiring the
        full rank keeps reduced outputs (a pooled logits vector whose
        size merely equals a bucket length) untouched."""
        real = req.sample[0].shape[self.policy.pad_axis]
        padded = req.key[0][0][self.policy.pad_axis]
        if real == padded:
            return rows
        sample_ndim = req.sample[0].ndim
        out = []
        for o in rows:
            ax = self.policy.pad_axis
            if o.ndim >= sample_ndim and o.ndim > ax \
                    and o.shape[ax] == padded:
                sl = [slice(None)] * o.ndim
                sl[ax] = slice(0, real)
                o = o[tuple(sl)]
            out.append(o)
        return out

    # -- introspection ------------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        from ..ndarray.register import exec_cache_stats
        return {
            "model": self.model.describe(),
            "policy": {
                "batch_buckets": list(self.policy.batch_buckets),
                "pad_axis": self.policy.pad_axis,
                "length_buckets": (list(self.policy.length_buckets)
                                   if self.policy.length_buckets else None),
                "n_buckets": self.policy.n_buckets(),
            },
            "queue": {"depth": len(self.batcher),
                      "limit": self.batcher.queue_limit,
                      "batch_timeout_ms": self.batcher.timeout_s * 1e3},
            "warmed_buckets": self.warmed,
            "warmup_seconds": round(self.warmup_seconds, 6),
            "compile_cache": _compile_cache_stats(),
            "worker_alive": self.ready(),
            "resilience": {
                "replicas": self.replicas,
                "workers_alive": sum(
                    1 for wid in range(self.replicas)
                    if self._worker_alive(wid)),
                "draining": self.draining,
                "supervisor": self.supervisor.describe(),
            },
            "exec_cache": exec_cache_stats(),
        }


class _GenReplica:
    """One generation worker replica: its engine, its thread.
    ``dead`` flips the moment the death handler starts so the router
    stops feeding an engine that is being evacuated."""

    __slots__ = ("idx", "engine", "thread", "dead")

    def __init__(self, idx: int, engine: Any) -> None:
        self.idx = idx
        self.engine = engine
        self.thread: Optional[threading.Thread] = None
        self.dead = False


class GenerationServer:
    """Host :class:`~mxnet_tpu.serving.generation.GenerationEngine`
    replicas on worker threads — the continuous-batching sibling of
    :class:`ModelServer`.

    The same concurrency shape per replica: ONE worker owns its engine
    (it runs the resident decode loop, one iteration at a time, each
    iteration watchdog-armed inside the engine), while any number of
    producer threads submit prompts and drain their
    :class:`~mxnet_tpu.serving.generation.TokenStream`.  A router picks
    the least-loaded healthy replica per request; worker death
    evacuates the replica's engine and resurrects its sequences on the
    survivors (exactly-once, token-identical — see the module doc).

    ::

        server = GenerationServer(engine, warmup=True).start()
        stream = server.generate(prompt_ids, max_new_tokens=64)
        for tok in stream: ...
        server.stop()

    Pass ``engine_factory=`` (and optionally ``replicas=``, default
    ``MXNET_SERVING_REPLICAS``) to host N independent engines; dead
    replicas are then rebuilt from the factory on restart.  Passing a
    single ``engine`` keeps the pre-replica behavior (one replica,
    restart reuses the evacuated engine).
    """

    def __init__(self, engine: Any = None, warmup: bool = False,
                 engine_factory: Optional[Callable[[], Any]] = None,
                 replicas: Optional[int] = None,
                 max_restarts: Optional[int] = None,
                 restart_backoff_ms: Optional[float] = None) -> None:
        if (engine is None) == (engine_factory is None):
            raise MXNetError(
                "GenerationServer takes an engine OR an engine_factory")
        self._factory = engine_factory
        self._warmup = bool(warmup)
        if engine is not None:
            engines = [engine]
        else:
            if replicas is None:
                replicas = int(getenv("MXNET_SERVING_REPLICAS", 1))
            engines = [engine_factory() for _ in range(max(1,
                                                           int(replicas)))]
        self.replicas = len(engines)
        self._replicas = [
            _GenReplica(i, eng) for i, eng in enumerate(engines)]
        self._started = False
        self._degraded = False
        self._draining = False
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # accepted requests waiting for a replica to come back (every
        # replica dead/restarting): flushed on restart, failed on
        # degrade/stop — never silently dropped
        self._pending: List[GenRequest] = []
        self.supervisor = ReplicaSupervisor(
            "generation", self.replicas, self._spawn_replica,
            self._on_degraded, self._replica_alive,
            max_restarts=max_restarts, backoff_ms=restart_backoff_ms)
        # prewarm BEFORE any replica thread exists or ready() can flip:
        # a restarted replica re-populates its whole program grid from
        # the persistent compile cache here, and /v1/model reports how
        # long that took (warmup_seconds)
        self.warmup_seconds = 0.0
        t0 = time.perf_counter()
        for rep in self._replicas:
            rep.engine.recovery_sink = self._recover
            if warmup:
                rep.engine.warmup()
        if warmup:
            self.warmup_seconds = time.perf_counter() - t0

    # -- compat surface ------------------------------------------------------
    @property
    def engine(self) -> Any:
        """The first replica's engine (pre-replica API compat)."""
        return self._replicas[0].engine

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "GenerationServer":
        if self._started:
            return self
        if self.engine.scheduler.closed:
            raise MXNetError(
                "GenerationServer cannot restart after stop(): build a "
                "fresh engine")
        self._started = True
        for rep in self._replicas:
            self._spawn_thread(rep)
        return self

    def _spawn_thread(self, rep: _GenReplica) -> None:
        t = threading.Thread(
            target=self._run, args=(rep,),
            name=f"mxnet-generation-worker-{rep.idx}", daemon=True)
        rep.thread = t
        t.start()

    def _replica_alive(self, rid: int) -> bool:
        rep = self._replicas[rid]
        # the death handler runs ON the dying thread, so is_alive() is
        # still True mid-evacuation — the dead flag closes that window
        return bool(not rep.dead and rep.thread is not None
                    and rep.thread.is_alive())

    def stop(self, timeout: float = 10.0) -> None:
        if not self._started:
            return
        self._stop.set()
        self.supervisor.stop()
        # close the admission queues: sheds queued requests with a
        # structured shutdown error and wakes parked workers
        for rep in self._replicas:
            rep.engine.scheduler.close()
        deadline = time.monotonic() + timeout
        for rep in self._replicas:
            if rep.thread is not None:
                rep.thread.join(max(0.0, deadline - time.monotonic()))
        # whether the workers exited cleanly or not, no stream may be
        # left to block forever
        for rep in self._replicas:
            rep.engine.close()
        err = MXNetError("generation server stopped with the request "
                         "still pending (shutdown)")
        with self._lock:
            pending, self._pending = self._pending, []
        for req in pending:
            req.fail(err)
        self._started = False

    # -- health split -------------------------------------------------------
    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def degraded(self) -> bool:
        return self._degraded

    def ready(self) -> bool:
        """Readiness: in rotation for NEW prompts — started, breaker
        closed, not draining, at least one replica serving or coming
        back."""
        return bool(self._started and not self._degraded
                    and not self._draining
                    and self.supervisor.in_rotation() > 0)

    def healthy(self) -> bool:
        """Back-compat alias for :meth:`ready`."""
        return self.ready()

    def __enter__(self) -> "GenerationServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- drain --------------------------------------------------------------
    def start_drain(self) -> None:
        """Stop admitting NEW prompts (429 ``draining``); queued and
        slot-resident sequences decode to completion."""
        _metrics.SERVING_DRAINING.set(1)
        self._draining = True

    def await_drained(self, timeout: float = 1.0) -> bool:
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                pending = bool(self._pending)
            idle = not pending and not any(
                rep.engine.scheduler.busy() for rep in self._replicas)
            if idle:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)

    def drain(self, deadline_s: Optional[float] = None,
              stop_timeout: float = 10.0) -> bool:
        """Stop admissions, finish every accepted sequence within
        ``deadline_s`` (default ``MXNET_SERVING_DRAIN_DEADLINE_S``),
        then stop.  Returns True when everything finished in budget
        (leftovers fail with the structured shutdown error)."""
        if deadline_s is None:
            deadline_s = float(
                getenv("MXNET_SERVING_DRAIN_DEADLINE_S", 30))
        self.start_drain()
        drained = self.await_drained(float(deadline_s))
        self.stop(timeout=stop_timeout)
        return drained

    # -- request API --------------------------------------------------------
    def generate(self, tokens: Any, max_new_tokens: int = 64,
                 eos_token: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 method: Optional[str] = None,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 seed: Optional[int] = None,
                 speculative: Optional[bool] = None) -> Any:
        """Submit one prompt; returns its ``TokenStream``.  Sampling
        parameters pass through to the engine (on-device sampling,
        deterministic by ``seed`` — including across worker-death
        resurrection), as does ``speculative`` (None = the engine's
        MXNET_GEN_SPEC_MODE default; the flag rides recovery, so a
        resurrected sequence keeps its draft config and its bytes).
        Sheds with ``OverloadError`` (queue full / no
        slot within deadline / draining / every replica mid-restart)
        and refuses with :class:`DegradedError` when the breaker is
        open — the same 429-vs-503 split as the one-shot path."""
        if not self._started:
            raise MXNetError("GenerationServer.start() first")
        if self._degraded:
            raise DegradedError(
                "generation worker replicas are crash-looping and the "
                "circuit breaker is open; the server is degraded "
                "(healthz reports 503) — reset_breaker() or restart it")
        if self._draining:
            from .batching import SHED_TOTAL
            SHED_TOTAL.labels(reason="draining").inc()
            REQUESTS_TOTAL.labels(status="shed").inc()
            raise OverloadError("draining", retry_after_ms=1e3)
        reps = sorted(
            (rep for rep in self._replicas
             if self._replica_alive(rep.idx)
             and not rep.engine.scheduler.closed),
            key=lambda rep: (len(rep.engine.scheduler)
                             + rep.engine.scheduler.n_active()))
        if not reps:
            if self.supervisor.any_pending():
                # transient: every replica is mid-restart — structured
                # backpressure, not a fake acceptance that could die
                raise OverloadError(
                    "restarting",
                    retry_after_ms=self.supervisor.backoff_ms)
            raise DegradedError(
                "no generation worker replica is alive; the server is "
                "degraded (healthz reports 503) — restart it")
        last: Optional[OverloadError] = None
        for rep in reps:
            try:
                return rep.engine.submit(
                    tokens, max_new_tokens=max_new_tokens,
                    eos_token=eos_token, deadline_ms=deadline_ms,
                    method=method, temperature=temperature,
                    top_k=top_k, top_p=top_p, seed=seed,
                    speculative=speculative)
            except OverloadError as e:
                last = e                 # replica full: try the next
        raise last if last is not None else MXNetError(
            "no replica accepted the request")

    # -- worker -------------------------------------------------------------
    def _run(self, rep: _GenReplica) -> None:
        try:
            while not self._stop.is_set():
                if not rep.engine.scheduler.wait_for_work(0.5):
                    return               # closed and fully drained
                if len(rep.engine.scheduler) \
                        or rep.engine.scheduler.n_active():
                    # the worker-death chaos site, hit only on passes
                    # with work so seeded after=N plans count decode
                    # activity, not idle parks
                    _faults.maybe_fault("serving.worker",
                                        replica=rep.idx)
                rep.engine.run_iteration()
        except BaseException as e:   # noqa: BLE001 - worker death is a
            # replica-level event: evacuate + resurrect elsewhere
            self._on_worker_death(rep, e)

    def _on_worker_death(self, rep: _GenReplica, exc: BaseException) -> None:
        if self._stop.is_set():
            try:
                rep.engine.close()
            except Exception:   # noqa: BLE001 - already dying
                pass
            return
        _LOG.error(
            "generation worker %d died: %r — evacuating its sequences "
            "to surviving replicas; supervisor restarting with backoff",
            rep.idx, exc)
        rep.dead = True          # router must not feed a dying engine
        try:
            queued, resident = rep.engine.evacuate()
        except Exception:   # noqa: BLE001 - the engine is too broken
            # even to evacuate: strand nothing — close() fails every
            # stream it still holds so waiters unblock deterministically
            queued, resident = [], []
            try:
                rep.engine.close()
            except Exception:   # noqa: BLE001 - already beyond help
                pass
        for req in queued:
            _metrics.SERVING_RECOVERIES_TOTAL.labels(site="queue").inc()
            self._route(req, exclude=rep)
        self._recover(resident, exc, "worker", exclude=rep)
        self.supervisor.notify_death(rep.idx, exc)

    def _recover(self, victims: Sequence[GenRequest],
                 exc: BaseException, site: str,
                 exclude: Optional[_GenReplica] = None) -> None:
        """Resurrect slot-resident sequences from their stream
        transcripts (exactly-once: deterministic greedy re-prefill +
        the TokenStream index dedupe).  Each sequence carries a
        recovery budget (the supervisor's restart budget, reused): a
        deterministically-poisoned sequence that crashes every decode
        step it joins must eventually FAIL with the underlying error,
        not resurrect forever while churning its slot-mates."""
        for req in victims:
            if req.recoveries >= self.supervisor.max_restarts:
                req.fail(MXNetError(
                    f"sequence recovered {req.recoveries} times and "
                    f"failed again ({exc!r}); recovery budget spent — "
                    "failing it instead of resurrecting forever"))
                REQUESTS_TOTAL.labels(status="error").inc()
                continue
            try:
                # the resurrection stays inside the original request's
                # trace: attach its captured context so the recovery
                # span (and the re-prefill that follows on the new
                # replica) share the request's trace id
                with _tracing.attach(req.trace), _tracing.child_span(
                        "serving.recover", site=site,
                        request_id=req.request_id,
                        recovered_tokens=len(req.stream.tokens)):
                    r = make_recovery_request(req)
            except MXNetError as e:
                req.fail(e)
                REQUESTS_TOTAL.labels(status="error").inc()
                continue
            _metrics.SERVING_RECOVERIES_TOTAL.labels(site=site).inc()
            _metrics.SERVING_RECOVERED_TOKENS.inc(len(req.stream.tokens))
            self._route(r, exclude=exclude)

    def _route(self, req: GenRequest,
               exclude: Optional[_GenReplica] = None) -> None:
        """Hand an already-accepted request to a healthy replica, or
        park it for the next restart — never shed, never drop."""
        reps = sorted(
            (rep for rep in self._replicas
             if rep is not exclude and self._replica_alive(rep.idx)
             and not rep.engine.scheduler.closed),
            key=lambda rep: (len(rep.engine.scheduler)
                             + rep.engine.scheduler.n_active()))
        for rep in reps:
            try:
                rep.engine.submit_request(req, front=True)
                return
            except MXNetError:
                continue                 # closed in a race: next
        with self._lock:
            if not self._degraded and not self._stop.is_set():
                self._pending.append(req)
                return
        req.fail(DegradedError(
            "sequence lost its worker and no replica is available "
            "(server degraded/stopping)"))
        REQUESTS_TOTAL.labels(status="error").inc()

    def _spawn_replica(self, rid: int) -> None:
        """Supervisor callback (after backoff): bring replica ``rid``
        back — fresh engine from the factory when there is one, the
        evacuated engine otherwise — and flush parked requests into
        it."""
        if self._stop.is_set() or self._degraded:
            return
        rep = self._replicas[rid]
        late_q: List[GenRequest] = []
        late_r: List[GenRequest] = []
        if self._factory is not None:
            eng = self._factory()
            if self._warmup:
                eng.warmup()
            # a generate() that read the replica as alive just before
            # the dead flag flipped may have queued into the old engine
            # AFTER its evacuation — sweep once more before orphaning it
            try:
                late_q, late_r = rep.engine.evacuate()
            except Exception:   # noqa: BLE001 - poisoned old engine
                pass
        else:
            eng = rep.engine             # evacuated + buffers reset
        eng.recovery_sink = self._recover
        with self._lock:
            if self._stop.is_set() or self._degraded:
                return
            rep.engine = eng
            rep.dead = False
            pending, self._pending = self._pending, []
        self._spawn_thread(rep)
        for req in late_q + pending:
            try:
                rep.engine.submit_request(req, front=True)
            except MXNetError as e:
                req.fail(e)
                REQUESTS_TOTAL.labels(status="error").inc()
        if late_r:
            self._recover(late_r, MXNetError(
                "worker died while the sequence was being admitted"),
                "worker")

    # -- breaker ------------------------------------------------------------
    def _on_degraded(self, exc: BaseException) -> None:
        self._degraded = True
        err = DegradedError(
            f"generation worker replicas died repeatedly "
            f"({self.supervisor.max_restarts} restarts per replica "
            f"spent); circuit breaker tripped — the server is degraded "
            f"(last error: {exc!r}); reset_breaker() or restart")
        with self._lock:
            pending, self._pending = self._pending, []
        for req in pending:
            req.fail(err)
            REQUESTS_TOTAL.labels(status="error").inc()
        for rep in self._replicas:
            try:
                queued, resident = rep.engine.evacuate()
            except Exception:   # noqa: BLE001 - poisoned engine
                continue
            for req in queued + resident:
                req.fail(err)
                REQUESTS_TOTAL.labels(status="error").inc()
        _LOG.error(
            "generation worker crash-loop: breaker tripped after %d "
            "restarts/replica — /healthz now reports degraded (503); "
            "reset_breaker() re-admits traffic (last error: %r)",
            self.supervisor.max_restarts, exc)

    def reset_breaker(self) -> None:
        """Refill every restart budget and bring dead replicas back —
        the operator's re-admit-traffic lever."""
        if not self._started:
            raise MXNetError("reset_breaker() on a stopped server — "
                             "build and start a fresh one")
        self.supervisor.reset()
        self._degraded = False
        for rep in self._replicas:
            if not self._replica_alive(rep.idx):
                self._spawn_replica(rep.idx)

    # -- introspection ------------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        d = self.engine.describe()
        if self.replicas > 1:
            d["slots"] = {
                "max": sum(rep.engine.max_slots
                           for rep in self._replicas),
                "active": sum(rep.engine.scheduler.n_active()
                              for rep in self._replicas),
                "free": sum(len(rep.engine.cache.free_slots())
                            for rep in self._replicas),
            }
            d["queue"] = {
                "depth": sum(len(rep.engine.scheduler)
                             for rep in self._replicas),
                "limit": sum(rep.engine.scheduler.queue_limit
                             for rep in self._replicas),
            }
        d["worker_alive"] = self.ready()
        d["warmup_seconds"] = round(self.warmup_seconds, 6)
        d["compile_cache"] = _compile_cache_stats()
        d["resilience"] = {
            "replicas": self.replicas,
            "workers_alive": sum(
                1 for rep in self._replicas
                if self._replica_alive(rep.idx)),
            "draining": self._draining,
            "pending_recoveries": len(self._pending),
            "supervisor": self.supervisor.describe(),
        }
        return d


def serve_until_preempted(httpd: Any, *servers: Any,
                          deadline_s: Optional[float] = None,
                          poll_s: float = 0.2) -> bool:
    """Run the HTTP front end until SIGTERM/SIGINT, then drain
    gracefully — the zero-downtime rolling-restart contract:

    1. the first signal (via :class:`~mxnet_tpu.preemption.
       PreemptionGuard`) stops admissions: readiness flips 503 so the
       balancer routes away, new requests shed 429 ``draining`` —
       never a connection reset;
    2. resident sequences/batches finish within ``deadline_s``
       (default ``MXNET_SERVING_DRAIN_DEADLINE_S``) while liveness
       stays 200;
    3. the HTTP listener closes, the servers stop, and the caller
       exits 0 (a second signal escalates through the guard — a wedged
       drain is still killable).

    Returns True when every accepted request finished inside the
    budget (leftovers failed with structured shutdown errors).
    """
    from ..preemption import PreemptionGuard

    if deadline_s is None:
        deadline_s = float(getenv("MXNET_SERVING_DRAIN_DEADLINE_S", 30))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    drained = True
    with PreemptionGuard() as guard:
        while not guard.wait(poll_s):
            pass
        _LOG.warning(
            "%s received: draining — admissions shed (429), readiness "
            "503, finishing resident work within %.0fs",
            guard.signal_name or "signal", deadline_s)
        for s in servers:
            s.start_drain()
        deadline = time.monotonic() + float(deadline_s)
        drained = False
        while time.monotonic() < deadline:
            if all(s.await_drained(0.2) for s in servers):
                drained = True
                break
        httpd.shutdown()
        for s in servers:
            s.stop()
    _LOG.warning("drain %s; exiting",
                 "complete" if drained else
                 "deadline exceeded (leftovers failed structurally)")
    return drained
