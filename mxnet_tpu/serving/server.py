"""ModelServer — batcher + model + one inference worker thread.

The concurrency shape mirrors the device reality: ONE worker drains the
queue and executes batches (a single accelerator runs one program at a
time; a second in-flight batch would only queue inside the runtime),
while any number of producer threads — the HTTP front end's
per-connection threads, in-process callers — submit requests and wait on
futures.  Backpressure is therefore explicit and bounded: the queue
limit and the deadline are the only places a request can wait.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

import numpy as _np

from ..base import MXNetError, getenv
from .. import faults as _faults
from .batching import (BucketPolicy, DynamicBatcher, OverloadError,
                       REQUESTS_TOTAL, Request)
from .model import ServedModel

__all__ = ["ModelServer", "GenerationServer", "DegradedError"]


class DegradedError(MXNetError):
    """The server cannot take requests (worker dead or stopped) — the
    HTTP front end maps this to 503, distinct from caller errors."""


class ModelServer:
    """Serve a :class:`~mxnet_tpu.serving.model.ServedModel` behind a
    dynamic micro-batching queue.

    In-process API::

        server = ModelServer(load_served("model"), warmup=True)
        server.start()
        y = server.infer(x_np)               # blocking, one sample
        fut = server.infer_async(x_np)       # concurrent.futures.Future
        server.stop()

    ``infer`` raises :class:`OverloadError` when the request is shed
    (bounded queue / deadline) — callers back off; the server never
    crashes or grows its queue without bound.
    """

    def __init__(self, model: ServedModel,
                 policy: Optional[BucketPolicy] = None,
                 timeout_ms: Optional[float] = None,
                 queue_limit: Optional[int] = None,
                 warmup: bool = False) -> None:
        self.model = model
        self.policy = policy if policy is not None \
            else model.default_policy()
        if model.fixed_batch is not None and \
                tuple(self.policy.batch_buckets) != (model.fixed_batch,):
            raise MXNetError(
                f"static export serves only batch={model.fixed_batch}; "
                f"the policy's batch_buckets must be "
                f"[{model.fixed_batch}]")
        self.batcher = DynamicBatcher(self.policy, timeout_ms=timeout_ms,
                                      queue_limit=queue_limit)
        self._default_deadline_s = \
            float(getenv("MXNET_SERVING_DEADLINE_MS", 0)) / 1e3
        self._thread: Optional[threading.Thread] = None
        self._started = False
        self._worker_died = False
        # the batch currently executing (worker-owned): stop() fails
        # these futures after the join so no caller blocks forever on a
        # result that will never come
        self._inflight: List[Request] = []
        self.warmed = 0
        if warmup:
            self.warmed = model.warmup(self.policy)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ModelServer":
        if self._started:
            return self
        if self.batcher._closed:
            raise MXNetError(
                "ModelServer cannot restart after stop(): the batcher is "
                "closed (build a fresh ModelServer)")
        self._started = True
        self._thread = threading.Thread(target=self._run,
                                        name="mxnet-serving-worker",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        if not self._started:
            return
        self.batcher.close()
        if self._thread is not None:
            self._thread.join(timeout)
        # strand nothing: a batch still executing when the join timed
        # out (or whose worker died) holds futures no one will ever
        # complete — fail them with a structured shutdown error so HTTP
        # clients and in-process callers unblock deterministically
        self._fail_inflight(MXNetError(
            "ModelServer stopped with the request still in flight "
            "(shutdown)"))
        self._started = False

    def _fail_inflight(self, exc: Exception) -> None:
        for r in list(self._inflight):
            if not r.future.done():
                try:
                    r.future.set_exception(exc)
                except Exception:   # noqa: BLE001 - done() race
                    continue
                REQUESTS_TOTAL.labels(status="error").inc()
        self._inflight = []

    def healthy(self) -> bool:
        """Ready to serve: started AND the worker thread is alive.  A
        dead worker or a stopped/never-started server reports False, so
        /healthz goes non-200 the moment requests would stall or fail —
        not only in the died-mid-run case."""
        return bool(self._started and not self._worker_died
                    and self._thread is not None
                    and self._thread.is_alive())

    def __enter__(self) -> "ModelServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- request API --------------------------------------------------------
    def infer_async(self, *sample: _np.ndarray,
                    deadline_ms: Optional[float] = None) -> Future:
        """Submit one sample (per-input arrays WITHOUT the batch dim);
        returns a Future of the per-output arrays (list, or the single
        array for single-output models)."""
        if not self._started:
            raise MXNetError("ModelServer.start() first")
        if not self.healthy():
            # a dead worker would park this future forever — fail the
            # submit instead so clients back off / failover
            raise DegradedError(
                "ModelServer worker thread has died; the server is "
                "degraded (healthz reports 503) — restart it")
        arrays = [_np.asarray(a) for a in sample]
        sig = self.model.input_signature
        if len(arrays) != len(sig):
            raise MXNetError(
                f"model {self.model.name} takes {len(sig)} inputs, "
                f"got {len(arrays)}")
        for i, (a, (shape, dtype)) in enumerate(zip(arrays, sig)):
            got = tuple(a.shape)
            if i == 0 and self.policy.pad_axis is not None:
                # only the bucketed axis may vary — every other dim must
                # match, or each distinct wrong shape would become a
                # fresh bucket key (an unbounded-compile hole) or be
                # silently zero-padded into wrong answers
                ax = self.policy.pad_axis
                if len(got) != len(shape) or any(
                        g != s for j, (g, s) in enumerate(zip(got, shape))
                        if j != ax):
                    raise MXNetError(
                        f"sample shape {got} != model input {shape} "
                        f"(batch dim excluded; only axis {ax} is "
                        "length-bucketed)")
            elif got != tuple(shape):
                raise MXNetError(
                    f"sample shape {got} != model input {tuple(shape)} "
                    "(batch dim excluded); enable length bucketing "
                    "(pad_axis/length_buckets) for variable-shape "
                    "requests")
        key = self.policy.bucket_key(arrays)
        if deadline_ms is None and self._default_deadline_s > 0:
            deadline_ms = self._default_deadline_s * 1e3
        deadline_t = (time.monotonic() + deadline_ms / 1e3
                      if deadline_ms else None)
        fut: Future = Future()
        self.batcher.submit(Request(arrays, key, fut, deadline_t))
        return fut

    def infer(self, *sample: _np.ndarray,
              deadline_ms: Optional[float] = None,
              timeout: float = 60.0) -> Any:
        """Blocking single-sample inference (the in-process API)."""
        return self.infer_async(*sample,
                                deadline_ms=deadline_ms).result(timeout)

    # -- worker -------------------------------------------------------------
    def _run(self) -> None:
        try:
            while True:
                batch = self.batcher.next_batch()
                if batch is None:
                    return
                self._inflight = batch
                try:
                    self._execute(batch)
                except Exception:   # noqa: BLE001 - the worker must
                    # outlive any per-batch surprise (a dead worker is a
                    # silently wedged server); per-request faults were
                    # already set
                    pass
                # cleared only on survival: a BaseException must leave
                # the batch visible to the death handler below
                self._inflight = []
        except BaseException as e:   # noqa: BLE001 - worker death is a
            # server-level event: mark degraded and unblock EVERY waiter
            # — the in-flight batch the dying worker held AND everything
            # still queued (close() fails those); re-raising inside a
            # worker thread would only reach threading.excepthook
            self._worker_died = True
            self._fail_inflight(MXNetError(
                f"ModelServer worker thread died: {e!r}; the server is "
                "degraded — restart it"))
            self.batcher.close()
            import logging
            logging.getLogger("mxnet_tpu.serving").error(
                "serving worker thread died: %r — /healthz now reports "
                "degraded (503); restart the server", e)

    def _execute(self, batch: List[Request]) -> None:
        try:
            _faults.maybe_fault("serving.execute", batch=len(batch))
            arrays, _nb = self.policy.assemble(
                [r.sample for r in batch], batch[0].key)
            # per-batch execute deadline: the training hang watchdog
            # reused for serving (MXNET_HEALTH_STEP_DEADLINE_S) — a
            # wedged model execute dumps all-thread stacks instead of
            # silently eating the queue's deadline budget
            from .. import health as _health
            with _health.watch_section("serving.execute",
                                       batch=len(batch)):
                outs = self.model.predict(arrays)
        except Exception as e:   # noqa: BLE001 - worker must survive
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)
                    REQUESTS_TOTAL.labels(status="error").inc()
            return
        for i, r in enumerate(batch):
            if r.future.done():
                # cancelled (or shed) while queued/executing: a result
                # set now would raise InvalidStateError
                continue
            rows = [o[i] for o in outs]
            if self.policy.pad_axis is not None:
                # slice length padding back off axis pad_axis of each
                # output that still carries the padded extent
                rows = self._strip_length(rows, r)
            try:
                r.future.set_result(rows[0] if len(rows) == 1 else rows)
            except Exception:   # noqa: BLE001 - cancelled in the
                continue        # done()->here window; keep distributing
            REQUESTS_TOTAL.labels(status="ok").inc()

    def _strip_length(self, rows: List[_np.ndarray],
                      req: Request) -> List[_np.ndarray]:
        """Heuristic by necessity: outputs carry no axis metadata, so an
        output is taken to keep the length axis when it has at least the
        sample's rank AND the padded extent at pad_axis.  Requiring the
        full rank keeps reduced outputs (a pooled logits vector whose
        size merely equals a bucket length) untouched."""
        real = req.sample[0].shape[self.policy.pad_axis]
        padded = req.key[0][0][self.policy.pad_axis]
        if real == padded:
            return rows
        sample_ndim = req.sample[0].ndim
        out = []
        for o in rows:
            ax = self.policy.pad_axis
            if o.ndim >= sample_ndim and o.ndim > ax \
                    and o.shape[ax] == padded:
                sl = [slice(None)] * o.ndim
                sl[ax] = slice(0, real)
                o = o[tuple(sl)]
            out.append(o)
        return out

    # -- introspection ------------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        from ..ndarray.register import exec_cache_stats
        return {
            "model": self.model.describe(),
            "policy": {
                "batch_buckets": list(self.policy.batch_buckets),
                "pad_axis": self.policy.pad_axis,
                "length_buckets": (list(self.policy.length_buckets)
                                   if self.policy.length_buckets else None),
                "n_buckets": self.policy.n_buckets(),
            },
            "queue": {"depth": len(self.batcher),
                      "limit": self.batcher.queue_limit,
                      "batch_timeout_ms": self.batcher.timeout_s * 1e3},
            "warmed_buckets": self.warmed,
            "worker_alive": self.healthy(),
            "exec_cache": exec_cache_stats(),
        }


class GenerationServer:
    """Host a :class:`~mxnet_tpu.serving.generation.GenerationEngine`
    on a worker thread — the continuous-batching sibling of
    :class:`ModelServer`.

    The same concurrency shape: ONE worker owns the device (it runs
    the resident decode loop, one iteration at a time, each iteration
    watchdog-armed inside the engine), while any number of producer
    threads submit prompts and drain their
    :class:`~mxnet_tpu.serving.generation.TokenStream`.  Unlike the
    one-shot worker, this one never blocks per-request: it parks only
    when NOTHING is queued or decoding, and a submit wakes it.

    ::

        server = GenerationServer(engine, warmup=True).start()
        stream = server.generate(prompt_ids, max_new_tokens=64)
        for tok in stream: ...
        server.stop()
    """

    def __init__(self, engine: Any, warmup: bool = False) -> None:
        self.engine = engine
        self._thread: Optional[threading.Thread] = None
        self._started = False
        self._worker_died = False
        self._stop = threading.Event()
        if warmup:
            engine.warmup()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "GenerationServer":
        if self._started:
            return self
        if self.engine.scheduler.closed:
            raise MXNetError(
                "GenerationServer cannot restart after stop(): build a "
                "fresh engine")
        self._started = True
        self._thread = threading.Thread(target=self._run,
                                        name="mxnet-generation-worker",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        if not self._started:
            return
        self._stop.set()
        # close the admission queue: sheds queued requests with a
        # structured shutdown error and wakes a parked worker
        self.engine.scheduler.close()
        if self._thread is not None:
            self._thread.join(timeout)
        # whether the worker exited cleanly or not, no stream may be
        # left to block forever
        self.engine.close()
        self._started = False

    def healthy(self) -> bool:
        return bool(self._started and not self._worker_died
                    and self._thread is not None
                    and self._thread.is_alive())

    def __enter__(self) -> "GenerationServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- request API --------------------------------------------------------
    def generate(self, tokens: Any, max_new_tokens: int = 64,
                 eos_token: Optional[int] = None,
                 deadline_ms: Optional[float] = None) -> Any:
        """Submit one prompt; returns its ``TokenStream``.  Sheds with
        ``OverloadError`` (queue full / no slot within deadline) and
        refuses with :class:`DegradedError` when the decode worker is
        dead — the same 429-vs-503 split as the one-shot path."""
        if not self._started:
            raise MXNetError("GenerationServer.start() first")
        if not self.healthy():
            raise DegradedError(
                "generation worker thread has died; the server is "
                "degraded (healthz reports 503) — restart it")
        return self.engine.submit(tokens, max_new_tokens=max_new_tokens,
                                  eos_token=eos_token,
                                  deadline_ms=deadline_ms)

    # -- worker -------------------------------------------------------------
    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                if not self.engine.scheduler.wait_for_work(0.5):
                    return               # closed and fully drained
                self.engine.run_iteration()
        except BaseException as e:   # noqa: BLE001 - worker death is a
            # server-level event: mark degraded, unblock every waiter
            self._worker_died = True
            try:
                self.engine.close()
            except Exception:   # noqa: BLE001 - already dying
                pass
            import logging
            logging.getLogger("mxnet_tpu.serving").error(
                "generation worker thread died: %r — /healthz now "
                "reports degraded (503); restart the server", e)

    # -- introspection ------------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        d = self.engine.describe()
        d["worker_alive"] = self.healthy()
        return d
