"""Slot-based, capacity-bucketed KV cache for continuous-batching decode.

The decode inner loop must be ONE compiled, shape-stable program that
stays resident across requests (the Julia->TPU full-compilation lesson,
PAPERS.md): every tensor the step touches therefore has a fixed shape.
This cache provides that shape discipline:

* **Slots** — the cache is a fixed ``(S, L, heads, d)`` buffer per
  layer, ``S = max_slots``.  A sequence owns one slot row for its whole
  lifetime; admission writes its prefilled keys/values into the row,
  retirement simply frees the slot id (no copy, no compaction — the
  row's stale contents are masked off by the per-slot position mask).
* **Capacity buckets** — ``L`` is drawn from a power-of-two-style grid
  (``MXNET_GEN_KV_BUCKETS``).  The decode step compiles once per
  bucket; when any live sequence needs a position ``>= L`` the whole
  cache pads up to the next bucket (`grow`), switching the engine to
  that bucket's pre-compiled step.  Steady-state traffic confined to
  the warmed grid therefore triggers ZERO XLA compiles.
* **Donation-friendly** — the engine replaces the layer buffers with
  the decode step's outputs each iteration, so XLA can update the
  cache in place (the buffers are donated to the compiled step).

Positions/occupancy are host-side numpy bookkeeping: the device only
ever sees the fixed-shape buffers plus an ``(S,)`` position vector.
"""
from __future__ import annotations

import collections
import hashlib
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError, getenv, register_env
from .. import metrics as _metrics

__all__ = ["PagedKVCache", "PrefixCache", "kv_bucket_grid",
           "round_up_bucket"]

register_env("MXNET_GEN_KV_BUCKETS", "128,256,512,1024",
             "KV-cache capacity bucket grid for the generation engine "
             "(comma list of padded sequence lengths). The resident "
             "decode step compiles once per bucket; a sequence whose "
             "prompt+new-tokens budget exceeds the top bucket is "
             "rejected at submit.")
register_env("MXNET_GEN_PREFIX_CACHE_SLOTS", 8,
             "Resident entries in the generation engine's shared-prefix "
             "KV cache: bucket-aligned prompt prefixes (e.g. a common "
             "system prompt) keep their K/V rows on the device and "
             "admissions COPY them into the slot instead of re-running "
             "prefill, collapsing TTFT for the dominant traffic class. "
             "LRU eviction past this bound; 0 disables prefix caching.")


def kv_bucket_grid(buckets: Optional[Sequence[int]] = None
                   ) -> Tuple[int, ...]:
    """The configured KV capacity grid, sorted ascending."""
    if buckets is None:
        raw = str(getenv("MXNET_GEN_KV_BUCKETS", "128,256,512,1024"))
        buckets = [int(b) for b in raw.split(",") if b.strip()]
    out = tuple(sorted({int(b) for b in buckets}))
    if not out or out[0] < 1:
        raise MXNetError(f"bad KV bucket grid {buckets!r}")
    return out


def round_up_bucket(n: int, grid: Sequence[int]) -> int:
    """Smallest grid bucket >= n (raises past the top — an unbounded
    length would reopen the compile hole the grid exists to close)."""
    for b in grid:
        if b >= n:
            return b
    raise MXNetError(
        f"required capacity {n} exceeds the top KV bucket {grid[-1]}; "
        "reject the request (or raise MXNET_GEN_KV_BUCKETS)")


class PagedKVCache:
    """Per-layer ``(max_slots, L, heads, head_dim)`` K/V buffers plus
    host-side slot bookkeeping.

    ``layers`` buffers live as jax arrays (device-resident); ``k(i)`` /
    ``v(i)`` hand them to the decode step and :meth:`replace` swaps in
    the step's outputs (donation-compatible).
    """

    def __init__(self, n_layers: int, n_heads: int, head_dim: int,
                 max_slots: int,
                 buckets: Optional[Sequence[int]] = None,
                 dtype: Any = None,
                 prefix_slots: Optional[int] = None,
                 prefix: Optional["PrefixCache"] = None) -> None:
        import jax.numpy as jnp
        self.grid = kv_bucket_grid(buckets)
        self.n_layers = int(n_layers)
        self.n_heads = int(n_heads)
        self.head_dim = int(head_dim)
        self.max_slots = int(max_slots)
        if self.max_slots < 1:
            raise MXNetError(f"max_slots must be >= 1, got {max_slots}")
        self.dtype = jnp.dtype(dtype) if dtype is not None \
            else jnp.float32
        self.bucket = self.grid[0]
        self._k: List[Any] = []
        self._v: List[Any] = []
        self._alloc_buffers(self.bucket)
        # host bookkeeping: next write position per slot (== tokens
        # resident in the row), -1 marks a free slot
        self.positions = _np.full((self.max_slots,), -1, _np.int64)
        # the pinned shared-prefix region: hot prompt-prefix K/V rows
        # resident beside the slot buffers, copied (never re-prefilled)
        # into slots at admission.  Pass ``prefix`` to SHARE one store
        # across engines (replicas on one device hit each other's
        # inserts — a resurrected sequence lands on a warm prefix)
        self.prefix = prefix if prefix is not None \
            else PrefixCache(prefix_slots)
        _metrics.GEN_KV_BUCKET_LEN.set(self.bucket)

    # -- buffers ------------------------------------------------------------
    def _alloc_buffers(self, L: int) -> None:
        import jax
        shape = (self.max_slots, L, self.n_heads, self.head_dim)
        # device_put COMMITS the buffers: a jitted call keys its cache
        # on input committed-ness, so fresh uncommitted zeros would
        # make the first post-reset admission recompile the row write
        # even at an identical shape.  HOST zeros, not jnp.zeros: an
        # eager jnp.zeros compiles a tiny program per shape — a pure
        # transfer keeps restart warmup (which walks every bucket
        # shape) at zero XLA compiles
        zeros = _np.zeros(shape, self.dtype)
        dev = jax.local_devices()[0]
        self._k = [jax.device_put(zeros, dev)
                   for _ in range(self.n_layers)]
        self._v = [jax.device_put(zeros, dev)
                   for _ in range(self.n_layers)]

    def k(self, layer: int) -> Any:
        return self._k[layer]

    def v(self, layer: int) -> Any:
        return self._v[layer]

    def layers(self) -> List[Tuple[Any, Any]]:
        return list(zip(self._k, self._v))

    def replace(self, new_k: Sequence[Any], new_v: Sequence[Any]) -> None:
        """Swap in the decode step's updated buffers (the old ones were
        donated to the compiled call)."""
        self._k = list(new_k)
        self._v = list(new_v)

    # -- slots --------------------------------------------------------------
    def free_slots(self) -> List[int]:
        return [i for i in range(self.max_slots)
                if self.positions[i] < 0]

    def occupancy(self) -> int:
        return int((self.positions >= 0).sum())

    def alloc(self) -> Optional[int]:
        for i in range(self.max_slots):
            if self.positions[i] < 0:
                self.positions[i] = 0
                return i
        return None

    def free(self, slot: int) -> None:
        self.positions[slot] = -1

    # -- admission write ----------------------------------------------------
    def write_prompt(self, slot: int, ks: Sequence[Any],
                     vs: Sequence[Any], t0: int,
                     start: int = 0) -> None:
        """Install prefilled rows into ``slot``: ``ks[l]``/``vs[l]``
        are ``(Lp, heads, d)`` (padded to a length bucket; the pad rows
        carry garbage KV that stays masked until the decode loop
        overwrites them position by position).  ``start`` places the
        rows at positions ``start..start+Lp`` — the shared-prefix
        admission writes the copied prefix at 0 and the suffix prefill
        at the prefix length (``start`` is a traced operand, so every
        offset shares one compiled write per shape pair).  ``t0`` is
        the slot's resident-token count after the write.  Grows the
        cache first if the rows exceed the current bucket."""
        Lp = int(ks[0].shape[0])
        if int(start) + Lp > self.bucket:
            self.grow(round_up_bucket(int(start) + Lp, self.grid))
        # ONE dispatch writes every layer's K and V row: per-call
        # dispatch overhead is what dominates a row copy on small
        # hosts, so 2L separate writes would bury the prefix cache's
        # TTFT win under launch latency (see _make_write_rows for why
        # the write is not donated)
        out = _write_rows_jit(self._k + self._v,
                              list(ks) + list(vs),
                              _np.int32(slot), _np.int32(start))
        self._k = out[:self.n_layers]
        self._v = out[self.n_layers:]
        self.positions[slot] = int(t0)

    # -- rollback -----------------------------------------------------------
    def truncate(self, slot: int, position: int) -> int:
        """Roll ``slot`` back so ``position`` is its next write index,
        discarding every row at ``position..`` — the speculative-decode
        rejection path.  No device work happens: rows past a slot's
        position are already invisible to the decode/verify attention
        mask, so rolling back is pure host bookkeeping and the next
        accepted token's write makes the row bit-identical to one that
        was never speculated into (CI pins this).  Only ever touches
        the slot's own rows — shared-prefix entries hold their own
        buffers (admission COPIES them in), so a rollback can never
        corrupt a refcounted prefix.  Returns the number of rows
        discarded."""
        if not 0 <= int(slot) < self.max_slots:
            raise MXNetError(
                f"truncate: slot {slot} out of range "
                f"(max_slots={self.max_slots})")
        cur = int(self.positions[slot])
        if cur < 0:
            raise MXNetError(f"truncate: slot {slot} is free")
        position = int(position)
        if position < 0 or position > cur:
            raise MXNetError(
                f"truncate: position {position} outside the slot's "
                f"resident range [0, {cur}] — rollback only ever "
                "rewinds (forward motion is the decode loop's job)")
        dropped = cur - position
        if dropped:
            self.positions[slot] = position
            _metrics.GEN_KV_ROLLBACKS_TOTAL.inc()
        return dropped

    # -- capacity -----------------------------------------------------------
    def needed_capacity(self) -> int:
        """Positions the next decode step will write: max live position
        + 1 (0 when idle)."""
        live = self.positions[self.positions >= 0]
        return int(live.max()) + 1 if live.size else 0

    def ensure_capacity(self, pos_needed: int) -> bool:
        """Grow to the bucket covering ``pos_needed`` write positions;
        returns True when a migration happened."""
        if pos_needed <= self.bucket:
            return False
        self.grow(round_up_bucket(pos_needed, self.grid))
        return True

    def grow(self, new_bucket: int) -> None:
        if new_bucket <= self.bucket:
            return
        self._k = [_grow_rows(k, new_bucket) for k in self._k]
        self._v = [_grow_rows(v, new_bucket) for v in self._v]
        self.bucket = new_bucket
        _metrics.GEN_KV_MIGRATIONS_TOTAL.inc()
        _metrics.GEN_KV_BUCKET_LEN.set(new_bucket)

    def warmup_writes(self, prompt_buckets: Sequence[int]) -> int:
        """Pre-compile every admission/migration executable: the
        prompt-row write per (capacity bucket x prompt bucket) pair,
        the grow pad per (bucket -> larger bucket) pair, and the
        prefix-row shrink per (prompt bucket -> smaller prompt bucket)
        pair — so steady-state traffic never compiles them."""
        import jax
        dev = jax.local_devices()[0]
        n = 0
        for i, L in enumerate(self.grid):
            self.bucket = int(L)
            self._alloc_buffers(self.bucket)
            for Lp in prompt_buckets:
                if Lp > L:
                    continue
                rows = [jax.device_put(
                    _np.zeros((int(Lp), self.n_heads, self.head_dim),
                              self.dtype), dev)
                    for _ in range(2 * self.n_layers)]
                # one fused write covers every layer's K and V; zeros
                # into zeros is a no-op in content
                out = _write_rows_jit(self._k + self._v, rows,
                                      _np.int32(0), _np.int32(0))
                self._k = out[:self.n_layers]
                self._v = out[self.n_layers:]
                n += 1
            for L2 in self.grid[i + 1:]:
                # live migrations may leap buckets (a long-prompt
                # admission), so warm every ordered pair
                _grow_rows(self._k[0], int(L2))
                n += 1
        if self.prefix.slots > 0:
            # prefix insertion slices a prefill's (Lp, h, d) rows down
            # to the bucket-aligned prefix length: warm each ordered
            # (larger -> smaller) prompt-bucket pair
            pbs = sorted(int(b) for b in prompt_buckets)
            for i, Lp in enumerate(pbs):
                rows = [jax.device_put(
                    _np.zeros((Lp, self.n_heads, self.head_dim),
                              self.dtype), dev)
                    for _ in range(2 * self.n_layers)]
                for Pb in pbs[:i]:
                    _shrink_rows(rows, Pb)
                    n += 1
        self.bucket = self.grid[0]
        self._alloc_buffers(self.bucket)
        return n

    def reset_buffers(self) -> None:
        """Reallocate the K/V buffers at the current bucket.  Needed
        after a decode-step FAILURE: the step consumed the old buffers
        by donation, so a raise after dispatch leaves ``_k``/``_v``
        pointing at deleted arrays — without this, every later
        admission would fail on them forever."""
        self._alloc_buffers(self.bucket)

    def reset_if_empty(self) -> None:
        """Shrink back to the smallest bucket once no sequence is live
        (only then: shrinking under live traffic would thrash)."""
        if self.occupancy() == 0 and self.bucket != self.grid[0]:
            self.bucket = self.grid[0]
            self._alloc_buffers(self.bucket)
            _metrics.GEN_KV_BUCKET_LEN.set(self.bucket)

    def describe(self) -> dict:
        return {
            "max_slots": self.max_slots,
            "bucket": self.bucket,
            "buckets": list(self.grid),
            "occupancy": self.occupancy(),
            "layers": self.n_layers,
            "heads": self.n_heads,
            "head_dim": self.head_dim,
            "dtype": str(self.dtype),
            "prefix_cache": self.prefix.describe(),
        }


# jitted helpers — one executable per (cache shape, prompt shape) pair,
# all drawn from the bucket grid (warmable, bounded).  Both persist
# through the compile cache (surface serving.kv, pinned) so a restarted
# replica's warmup re-loads the whole admission/migration grid from
# disk instead of recompiling it.

def _grow_rows(buf: Any, new_len: int) -> Any:
    fn = _grow_jits.get(int(new_len))
    if fn is None:
        import jax
        import jax.numpy as jnp
        from .. import compile_cache as _cc

        def grow(b, _L=int(new_len)):
            return jnp.pad(
                b, ((0, 0), (0, _L - b.shape[1]), (0, 0), (0, 0)))

        fn = _grow_jits[int(new_len)] = _cc.persistently_cached(
            jax.jit(grow), surface="serving.kv", pin=True)
    return fn(buf)


_grow_jits: dict = {}


def _make_write_rows():
    import jax
    from jax import lax
    from .. import compile_cache as _cc

    def write(bufs, rows, slot, start):
        # bufs: every layer's K then V buffer (S, L, h, d); rows: the
        # matching (Lp, h, d) rows; slot/start scalars: place each
        # row-set at [slot, start:start+Lp] in ONE executable (per-
        # dispatch overhead dominates a row copy, so one call per
        # layer per K/V would bury the admission in launch latency).
        # start is a traced operand (prefix copies write at 0, suffix
        # prefills at the prefix length) so every offset shares this
        # one executable per shape pair.  NOT donated: a donated
        # multi-buffer write deserialized from the persistent compile
        # cache mis-aliases on this jax/XLA version — a warm-restarted
        # replica then decodes corrupted KV rows and double-frees at
        # teardown (observed live; the in-process jit was fine).  The
        # un-donated form matches the pre-prefix-cache write's
        # semantics and keeps warm restarts at 0 compiles
        return [lax.dynamic_update_slice(
            b, r[None].astype(b.dtype),
            (slot, start, _np.int32(0), _np.int32(0)))
            for b, r in zip(bufs, rows)]
    return _cc.persistently_cached(
        jax.jit(write), surface="serving.kv",
        pin=True)


class _LazyWrite:
    """Defer the jax import to first use (the serving package must stay
    importable without touching the backend)."""

    def __init__(self) -> None:
        self._fn = None

    def __call__(self, bufs, rows, slot, start):
        if self._fn is None:
            self._fn = _make_write_rows()
        return self._fn(bufs, rows, slot, start)


_write_rows_jit = _LazyWrite()


def _shrink_rows(rows: List[Any], new_len: int) -> List[Any]:
    """Slice every (Lp, h, d) row-set in ``rows`` down to its first
    ``new_len`` rows in ONE executable — the prefix-insertion path (a
    prefill's K and V rows cut to the bucket-aligned prefix).  One
    executable per (Lp, new_len) pair, all drawn from the
    prompt-bucket grid (warmable, bounded)."""
    fn = _shrink_jits.get(int(new_len))
    if fn is None:
        import jax
        from .. import compile_cache as _cc

        def shrink(bs, _n=int(new_len)):
            return [b[:_n] for b in bs]

        fn = _shrink_jits[int(new_len)] = _cc.persistently_cached(
            jax.jit(shrink), surface="serving.kv", pin=True)
    return fn(list(rows))


_shrink_jits: dict = {}


# ---------------------------------------------------------------------------
# shared-prefix KV cache (the pinned region)
# ---------------------------------------------------------------------------

class _PrefixEntry:
    """One resident prefix: per-layer K/V rows (Pb, heads, d) on the
    device, the real prefix length ``q`` (rows past it are pad
    garbage, masked by slot positions like any admission), and — when
    the prefix IS a whole prompt — the prefill's last-token logits, so
    an identical-prompt admission emits its first token without any
    model call."""

    __slots__ = ("key", "ks", "vs", "q", "bucket", "logits", "refs")

    def __init__(self, key: str, ks: List[Any], vs: List[Any], q: int,
                 logits: Optional[_np.ndarray]) -> None:
        self.key = key
        self.ks = ks
        self.vs = vs
        self.q = int(q)
        self.bucket = int(ks[0].shape[0])
        self.logits = logits
        self.refs = 0


def prefix_key(tokens: _np.ndarray, q: int) -> str:
    """Content hash of the first ``q`` tokens (int32-canonical)."""
    raw = _np.ascontiguousarray(
        _np.asarray(tokens, _np.int32)[:q]).tobytes()
    return f"{q}:{hashlib.sha1(raw).hexdigest()}"


class PrefixCache:
    """Ref-counted, LRU-bounded store of hot prompt-prefix K/V rows.

    One store may be SHARED by engines serving the same
    :class:`~mxnet_tpu.serving.model.DecodeModel` (replicas on one
    device — ``tools/serve.py`` does this) so any replica's cold
    prefill warms them all; entries are model-specific, so never share
    a store across different models/weights.

    Engine threads probe/pin/insert concurrently under the shared
    store, so every method is lock-guarded; nothing under the lock
    touches the device (entries hold already-built arrays — eviction
    just drops the references).  ``refs`` counts admissions currently
    copying from the entry: eviction only ever removes unreferenced
    entries, so rows cannot vanish out from under an admission on a
    sibling engine."""

    def __init__(self, slots: Optional[int] = None) -> None:
        if slots is None:
            slots = int(getenv("MXNET_GEN_PREFIX_CACHE_SLOTS", 8))
        self.slots = max(0, int(slots))
        self._entries: "collections.OrderedDict[str, _PrefixEntry]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def lookup(self, key: str, pin: bool = False
               ) -> Optional[_PrefixEntry]:
        """The entry for ``key`` (refreshing recency), or None.
        ``pin=True`` bumps the refcount — pair with :meth:`unpin`."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            self._entries.move_to_end(key)
            if pin:
                e.refs += 1
            return e

    def unpin(self, key: str) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.refs > 0:
                e.refs -= 1

    def insert(self, key: str, ks: List[Any], vs: List[Any], q: int,
               logits: Optional[_np.ndarray] = None) -> bool:
        """Install a prefix (idempotent: an existing key only refreshes
        recency — concurrent admissions of the same prefix must not
        churn the rows).  Evicts LRU unreferenced entries past the
        ``slots`` bound; returns False when the cache is disabled or
        every resident entry is pinned."""
        if self.slots == 0:
            return False
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return True
            while len(self._entries) >= self.slots:
                victim = next((k for k, e in self._entries.items()
                               if e.refs == 0), None)
                if victim is None:
                    return False        # everything pinned: skip insert
                del self._entries[victim]
                _metrics.GEN_PREFIX_EVICTIONS_TOTAL.inc()
            self._entries[key] = _PrefixEntry(key, list(ks), list(vs),
                                              q, logits)
            _metrics.GEN_PREFIX_ROWS.set(
                sum(e.bucket for e in self._entries.values()))
            return True

    def attach_logits(self, key: str, logits: _np.ndarray) -> None:
        """Upgrade a resident entry with whole-prompt prefill logits
        (an entry first inserted from a LONGER prompt carries none;
        once some request's full prompt IS the prefix, its logits make
        every identical prompt admit with zero model calls)."""
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.logits is None:
                e.logits = logits

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
        _metrics.GEN_PREFIX_ROWS.set(0)

    def rows_resident(self) -> int:
        with self._lock:
            return sum(e.bucket for e in self._entries.values())

    def describe(self) -> dict:
        with self._lock:
            return {
                "slots": self.slots,
                "entries": len(self._entries),
                "rows": sum(e.bucket for e in self._entries.values()),
                "pinned": sum(1 for e in self._entries.values()
                              if e.refs > 0),
            }
