"""Slot-based, capacity-bucketed KV cache for continuous-batching decode.

The decode inner loop must be ONE compiled, shape-stable program that
stays resident across requests (the Julia->TPU full-compilation lesson,
PAPERS.md): every tensor the step touches therefore has a fixed shape.
This cache provides that shape discipline:

* **Slots** — the cache is a fixed ``(S, L, heads, d)`` buffer per
  layer, ``S = max_slots``.  A sequence owns one slot row for its whole
  lifetime; admission writes its prefilled keys/values into the row,
  retirement simply frees the slot id (no copy, no compaction — the
  row's stale contents are masked off by the per-slot position mask).
* **Capacity buckets** — ``L`` is drawn from a power-of-two-style grid
  (``MXNET_GEN_KV_BUCKETS``).  The decode step compiles once per
  bucket; when any live sequence needs a position ``>= L`` the whole
  cache pads up to the next bucket (`grow`), switching the engine to
  that bucket's pre-compiled step.  Steady-state traffic confined to
  the warmed grid therefore triggers ZERO XLA compiles.
* **Donation-friendly** — the engine replaces the layer buffers with
  the decode step's outputs each iteration, so XLA can update the
  cache in place (the buffers are donated to the compiled step).

Positions/occupancy are host-side numpy bookkeeping: the device only
ever sees the fixed-shape buffers plus an ``(S,)`` position vector.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError, getenv, register_env
from .. import metrics as _metrics

__all__ = ["PagedKVCache", "kv_bucket_grid", "round_up_bucket"]

register_env("MXNET_GEN_KV_BUCKETS", "128,256,512,1024",
             "KV-cache capacity bucket grid for the generation engine "
             "(comma list of padded sequence lengths). The resident "
             "decode step compiles once per bucket; a sequence whose "
             "prompt+new-tokens budget exceeds the top bucket is "
             "rejected at submit.")


def kv_bucket_grid(buckets: Optional[Sequence[int]] = None
                   ) -> Tuple[int, ...]:
    """The configured KV capacity grid, sorted ascending."""
    if buckets is None:
        raw = str(getenv("MXNET_GEN_KV_BUCKETS", "128,256,512,1024"))
        buckets = [int(b) for b in raw.split(",") if b.strip()]
    out = tuple(sorted({int(b) for b in buckets}))
    if not out or out[0] < 1:
        raise MXNetError(f"bad KV bucket grid {buckets!r}")
    return out


def round_up_bucket(n: int, grid: Sequence[int]) -> int:
    """Smallest grid bucket >= n (raises past the top — an unbounded
    length would reopen the compile hole the grid exists to close)."""
    for b in grid:
        if b >= n:
            return b
    raise MXNetError(
        f"required capacity {n} exceeds the top KV bucket {grid[-1]}; "
        "reject the request (or raise MXNET_GEN_KV_BUCKETS)")


class PagedKVCache:
    """Per-layer ``(max_slots, L, heads, head_dim)`` K/V buffers plus
    host-side slot bookkeeping.

    ``layers`` buffers live as jax arrays (device-resident); ``k(i)`` /
    ``v(i)`` hand them to the decode step and :meth:`replace` swaps in
    the step's outputs (donation-compatible).
    """

    def __init__(self, n_layers: int, n_heads: int, head_dim: int,
                 max_slots: int,
                 buckets: Optional[Sequence[int]] = None,
                 dtype: Any = None) -> None:
        import jax.numpy as jnp
        self.grid = kv_bucket_grid(buckets)
        self.n_layers = int(n_layers)
        self.n_heads = int(n_heads)
        self.head_dim = int(head_dim)
        self.max_slots = int(max_slots)
        if self.max_slots < 1:
            raise MXNetError(f"max_slots must be >= 1, got {max_slots}")
        self.dtype = jnp.dtype(dtype) if dtype is not None \
            else jnp.float32
        self.bucket = self.grid[0]
        self._k: List[Any] = []
        self._v: List[Any] = []
        self._alloc_buffers(self.bucket)
        # host bookkeeping: next write position per slot (== tokens
        # resident in the row), -1 marks a free slot
        self.positions = _np.full((self.max_slots,), -1, _np.int64)
        _metrics.GEN_KV_BUCKET_LEN.set(self.bucket)

    # -- buffers ------------------------------------------------------------
    def _alloc_buffers(self, L: int) -> None:
        import jax
        shape = (self.max_slots, L, self.n_heads, self.head_dim)
        # device_put COMMITS the buffers: a jitted call keys its cache
        # on input committed-ness, so fresh uncommitted zeros would
        # make the first post-reset admission recompile the row write
        # even at an identical shape.  HOST zeros, not jnp.zeros: an
        # eager jnp.zeros compiles a tiny program per shape — a pure
        # transfer keeps restart warmup (which walks every bucket
        # shape) at zero XLA compiles
        zeros = _np.zeros(shape, self.dtype)
        dev = jax.local_devices()[0]
        self._k = [jax.device_put(zeros, dev)
                   for _ in range(self.n_layers)]
        self._v = [jax.device_put(zeros, dev)
                   for _ in range(self.n_layers)]

    def k(self, layer: int) -> Any:
        return self._k[layer]

    def v(self, layer: int) -> Any:
        return self._v[layer]

    def layers(self) -> List[Tuple[Any, Any]]:
        return list(zip(self._k, self._v))

    def replace(self, new_k: Sequence[Any], new_v: Sequence[Any]) -> None:
        """Swap in the decode step's updated buffers (the old ones were
        donated to the compiled call)."""
        self._k = list(new_k)
        self._v = list(new_v)

    # -- slots --------------------------------------------------------------
    def free_slots(self) -> List[int]:
        return [i for i in range(self.max_slots)
                if self.positions[i] < 0]

    def occupancy(self) -> int:
        return int((self.positions >= 0).sum())

    def alloc(self) -> Optional[int]:
        for i in range(self.max_slots):
            if self.positions[i] < 0:
                self.positions[i] = 0
                return i
        return None

    def free(self, slot: int) -> None:
        self.positions[slot] = -1

    # -- admission write ----------------------------------------------------
    def write_prompt(self, slot: int, ks: Sequence[Any],
                     vs: Sequence[Any], t0: int) -> None:
        """Install a prefilled prompt into ``slot``: ``ks[l]``/``vs[l]``
        are ``(Lp, heads, d)`` (prompt padded to a length bucket; the
        pad rows carry garbage KV that stays masked until the decode
        loop overwrites them position by position).  Grows the cache
        first if the padded prompt exceeds the current bucket."""
        Lp = int(ks[0].shape[0])
        if Lp > self.bucket:
            self.grow(round_up_bucket(Lp, self.grid))
        slot_j = _np.int32(slot)
        for li in range(self.n_layers):
            self._k[li] = _write_row_jit(self._k[li], ks[li], slot_j)
            self._v[li] = _write_row_jit(self._v[li], vs[li], slot_j)
        self.positions[slot] = int(t0)

    # -- capacity -----------------------------------------------------------
    def needed_capacity(self) -> int:
        """Positions the next decode step will write: max live position
        + 1 (0 when idle)."""
        live = self.positions[self.positions >= 0]
        return int(live.max()) + 1 if live.size else 0

    def ensure_capacity(self, pos_needed: int) -> bool:
        """Grow to the bucket covering ``pos_needed`` write positions;
        returns True when a migration happened."""
        if pos_needed <= self.bucket:
            return False
        self.grow(round_up_bucket(pos_needed, self.grid))
        return True

    def grow(self, new_bucket: int) -> None:
        if new_bucket <= self.bucket:
            return
        self._k = [_grow_rows(k, new_bucket) for k in self._k]
        self._v = [_grow_rows(v, new_bucket) for v in self._v]
        self.bucket = new_bucket
        _metrics.GEN_KV_MIGRATIONS_TOTAL.inc()
        _metrics.GEN_KV_BUCKET_LEN.set(new_bucket)

    def warmup_writes(self, prompt_buckets: Sequence[int]) -> int:
        """Pre-compile every admission/migration executable: the
        prompt-row write per (capacity bucket x prompt bucket) pair and
        the grow pad per (bucket -> larger bucket) pair — so
        steady-state traffic never compiles them."""
        import jax
        dev = jax.local_devices()[0]
        n = 0
        for i, L in enumerate(self.grid):
            self.bucket = int(L)
            self._alloc_buffers(self.bucket)
            for Lp in prompt_buckets:
                if Lp > L:
                    continue
                row = jax.device_put(
                    _np.zeros((int(Lp), self.n_heads, self.head_dim),
                              self.dtype), dev)
                # one write warms the executable for every layer (they
                # share shapes); zeros into zeros is a no-op in content
                self._k[0] = _write_row_jit(self._k[0], row,
                                            _np.int32(0))
                n += 1
            for L2 in self.grid[i + 1:]:
                # live migrations may leap buckets (a long-prompt
                # admission), so warm every ordered pair
                _grow_rows(self._k[0], int(L2))
                n += 1
        self.bucket = self.grid[0]
        self._alloc_buffers(self.bucket)
        return n

    def reset_buffers(self) -> None:
        """Reallocate the K/V buffers at the current bucket.  Needed
        after a decode-step FAILURE: the step consumed the old buffers
        by donation, so a raise after dispatch leaves ``_k``/``_v``
        pointing at deleted arrays — without this, every later
        admission would fail on them forever."""
        self._alloc_buffers(self.bucket)

    def reset_if_empty(self) -> None:
        """Shrink back to the smallest bucket once no sequence is live
        (only then: shrinking under live traffic would thrash)."""
        if self.occupancy() == 0 and self.bucket != self.grid[0]:
            self.bucket = self.grid[0]
            self._alloc_buffers(self.bucket)
            _metrics.GEN_KV_BUCKET_LEN.set(self.bucket)

    def describe(self) -> dict:
        return {
            "max_slots": self.max_slots,
            "bucket": self.bucket,
            "buckets": list(self.grid),
            "occupancy": self.occupancy(),
            "layers": self.n_layers,
            "heads": self.n_heads,
            "head_dim": self.head_dim,
            "dtype": str(self.dtype),
        }


# jitted helpers — one executable per (cache shape, prompt shape) pair,
# all drawn from the bucket grid (warmable, bounded).  Both persist
# through the compile cache (surface serving.kv, pinned) so a restarted
# replica's warmup re-loads the whole admission/migration grid from
# disk instead of recompiling it.

def _grow_rows(buf: Any, new_len: int) -> Any:
    fn = _grow_jits.get(int(new_len))
    if fn is None:
        import jax
        import jax.numpy as jnp
        from .. import compile_cache as _cc

        def grow(b, _L=int(new_len)):
            return jnp.pad(
                b, ((0, 0), (0, _L - b.shape[1]), (0, 0), (0, 0)))

        fn = _grow_jits[int(new_len)] = _cc.persistently_cached(
            jax.jit(grow), surface="serving.kv", pin=True)
    return fn(buf)


_grow_jits: dict = {}


def _make_write_row():
    import jax
    from jax import lax
    from .. import compile_cache as _cc

    def write(buf, row, slot):
        # buf (S, L, h, d), row (Lp, h, d), slot scalar: place the
        # prompt KV at [slot, 0:Lp] without materializing a copy chain
        return lax.dynamic_update_slice(
            buf, row[None].astype(buf.dtype),
            (slot, _np.int32(0), _np.int32(0), _np.int32(0)))
    return _cc.persistently_cached(jax.jit(write), surface="serving.kv",
                                   pin=True)


class _LazyWrite:
    """Defer the jax import to first use (the serving package must stay
    importable without touching the backend)."""

    def __init__(self) -> None:
        self._fn = None

    def __call__(self, buf, row, slot):
        if self._fn is None:
            self._fn = _make_write_row()
        return self._fn(buf, row, slot)


_write_row_jit = _LazyWrite()
