"""Speculative decoding: draft models that propose k tokens per slot
for one-pass target verification.

The GenerationEngine's plain decode loop emits exactly one token per
slot per iteration, so tokens/sec is hard-capped by target-model step
latency (the tokens/sec/chip economics PAPERS.md frames).  Speculation
breaks the cap: a cheap DRAFT proposes ``k`` tokens per slot, the
target scores all ``k+1`` positions in ONE bucket-compiled pass
(:meth:`DecodeModel.verify`), and the engine keeps the longest prefix
of proposals that MATCH the target's own tokens — every emitted token
is the target's, so output is byte-identical to the non-speculative
engine at the same seed (CI pins this for greedy and sampled traffic).

**Accept rule.**  Draft proposal ``d_j`` (for stream position ``p+j``)
is accepted iff it equals the token the target itself produces at that
position — greedy argmax, or the PR-12 counter-PRNG sample under the
slot's folded key ``fold_in(PRNGKey(seed), position - base)``.  Both
drafts below therefore run the SAME per-slot sampling lanes as the
target: a good draft reproduces the target's categorical draw exactly
(identical logits => identical token under an identical key), so
sampled traffic speculates as well as greedy.  Greedy is just the
``method=0`` special case where the key never matters.

**Rollback.**  Verification scatters K/V rows for all ``k+1``
positions; when only ``m <= k`` tokens survive, the engine rewinds the
slot with :meth:`PagedKVCache.truncate` — pure host bookkeeping (the
rows were never visible past the slot position) counted in
``mxnet_gen_kv_rollbacks_total``.

Two draft flavors:

* :class:`SelfSpeculativeDraft` — the target's own bottom ``n`` layers
  (plus its final norm and tied head) act as the draft.  Zero extra
  parameters, zero extra KV state: the chained draft steps READ the
  target cache's first ``n`` layer buffers (never donated, temporaries
  discarded), so rollback only ever concerns the verify pass's writes.
* :class:`IndependentDraft` — a separate small zoo GPT sharing the
  target's tokenizer, with its own :class:`PagedKVCache` mirroring the
  target's slot ids.  Each iteration runs ``k+1`` chained sub-steps
  (the extra one writes the row for the last proposal), so after
  truncating to the accepted boundary the draft cache position always
  equals the target's — no catch-up pass exists anywhere.
"""
from __future__ import annotations

import time
from typing import Any, List, Optional, Sequence

import numpy as _np

from ..base import MXNetError
from .. import metrics as _metrics
from .kv_cache import PagedKVCache, round_up_bucket
from .model import DecodeModel, _pure_ln, _sample_tokens, \
    _slot_block_step

__all__ = ["DraftModel", "SelfSpeculativeDraft", "IndependentDraft",
           "make_draft"]


def _chain_steps(params, ks, vs, toks, pos, seeds, bases, temps,
                 topks, topps, methods, n_sub, nh, ga_s):
    """``n_sub`` UNROLLED single-token steps feeding each output token
    back in as the next input — the draft-proposal chain.  Sub-step
    ``j`` scatters K/V at ``pos + j`` and samples under counter
    ``pos + j - base``: exactly the key the target's verify pass uses
    for that position, so a draft whose logits match the target's
    proposes the target's own token (the accept rule's fixed point)."""
    from jax import lax
    import jax.numpy as jnp
    cur = toks
    outs = []
    for j in range(n_sub):
        x = (params["embed"][cur][:, None, :]
             + params["pos"][pos + j][:, None, :])
        new_ks, new_vs = [], []
        for p, ck, cv in zip(params["blocks"], ks, vs):
            x, ck, cv = _slot_block_step(p, x, ck, cv, pos + j,
                                         nh, ga_s)
            new_ks.append(ck)
            new_vs.append(cv)
        ks, vs = new_ks, new_vs
        x = _pure_ln(x, params["lnf_g"], params["lnf_b"], ga_s[1])
        logits = x[:, 0, :] @ params["embed"].T

        def _mixed(lg, _j=j):
            return _sample_tokens(lg, seeds, (pos + _j) - bases,
                                  temps, topks, topps, methods)

        def _greedy(lg):
            return jnp.argmax(lg, axis=-1).astype(jnp.int32)

        cur = lax.cond(jnp.any(methods != 0), _mixed, _greedy, logits)
        outs.append(cur)
    return jnp.stack(outs, axis=1), ks, vs


class DraftModel:
    """The engine-facing draft protocol.

    A draft owns whatever state its proposals need; the engine drives
    it with slot-parallel calls mirroring its own lifecycle:

    * :meth:`admit` / :meth:`release` bracket a speculative request's
      residency in ``slot``.
    * :meth:`propose` returns an ``(S, k)`` int32 proposal matrix for
      every slot (garbage rows for non-speculative slots are fine —
      the engine discards them).
    * :meth:`commit` tells the draft the slot's post-acceptance
      position so cache-bearing drafts can truncate their own rows.
    * :meth:`evacuate` / :meth:`reset` / :meth:`reset_if_empty` mirror
      the engine's failure/idle paths; :meth:`warmup` pre-compiles the
      draft's programs so steady-state traffic stays at zero compiles.
    """

    mode = "?"
    k = 0

    def admit(self, slot: int, tokens: _np.ndarray,
              prompt_buckets: Sequence[int]) -> None:
        pass

    def release(self, slot: int) -> None:
        pass

    def commit(self, slot: int, position: int) -> None:
        pass

    def evacuate(self) -> None:
        pass

    def reset(self) -> None:
        pass

    def reset_if_empty(self) -> None:
        pass

    def warmup(self, prompt_buckets: Sequence[int]) -> int:
        return 0

    def propose(self, cache: Any, last_tok: _np.ndarray,
                positions: _np.ndarray,
                sampling: Optional[Sequence[Any]] = None) -> _np.ndarray:
        raise NotImplementedError

    def describe(self) -> dict:
        return {"mode": self.mode, "k": self.k}


class SelfSpeculativeDraft(DraftModel):
    """Truncated-layer self-speculation: the target's bottom ``layers``
    blocks + final norm + tied head propose the next ``k`` tokens.

    The chained draft steps read the TARGET cache's first ``layers``
    K/V buffers in place (not donated — XLA materializes the chain's
    scatters into temporaries that die with the call), so the draft
    adds no resident state and the engine's rollback story stays
    entirely about the verify pass's writes."""

    mode = "self"

    def __init__(self, model: Any, k: int, layers: int = 0) -> None:
        import jax
        from .. import compile_cache as _cc
        if not isinstance(model, DecodeModel):
            model = DecodeModel.from_block(model)
        self.model = model
        self.k = int(k)
        if self.k < 1:
            raise MXNetError(f"speculative k must be >= 1, got {k}")
        layers = int(layers)
        if layers == 0:
            layers = max(1, model.n_layers // 2)
        if not 1 <= layers <= model.n_layers:
            raise MXNetError(
                f"self-speculative draft wants 1..{model.n_layers} "
                f"target layers, got {layers}")
        self.layers = layers
        nh, ga_s = model.num_heads, model.ga
        n_sub = self.k

        def _propose(params, ks, vs, toks, pos, seeds, bases, temps,
                     topks, topps, methods):
            outs, _, _ = _chain_steps(params, ks, vs, toks, pos,
                                      seeds, bases, temps, topks,
                                      topps, methods, n_sub, nh, ga_s)
            return outs

        self._fn = _cc.persistently_cached(
            jax.jit(_propose), surface="serving.decode", pin=True)

    def _sub_params(self) -> dict:
        p = self.model.params
        return {"embed": p["embed"], "pos": p["pos"],
                "lnf_g": p["lnf_g"], "lnf_b": p["lnf_b"],
                "blocks": list(p["blocks"][:self.layers])}

    def propose(self, cache: Any, last_tok: _np.ndarray,
                positions: _np.ndarray,
                sampling: Optional[Sequence[Any]] = None) -> _np.ndarray:
        import jax
        import jax.numpy as jnp
        S = cache.max_slots
        if sampling is None:
            sampling = self.model.greedy_sampling(S)
        if not isinstance(sampling[0], jax.Array):
            sampling = self.model.device_sampling(sampling)
        self.model._account(f"draft:{S}x{cache.bucket}x{self.k}")
        t = time.perf_counter()
        outs = self._fn(
            self._sub_params(),
            list(cache._k[:self.layers]), list(cache._v[:self.layers]),
            jnp.asarray(_np.asarray(last_tok, _np.int32)),
            jnp.asarray(_np.asarray(positions, _np.int32)), *sampling)
        out = _np.asarray(outs)
        from .. import tracing as _tracing
        _metrics.GEN_STEP_SECONDS.labels(phase="draft").observe(
            time.perf_counter() - t,
            exemplar=_tracing.current_trace_id())
        return out

    def describe(self) -> dict:
        return {"mode": self.mode, "k": self.k, "layers": self.layers,
                "target_layers": self.model.n_layers}


class IndependentDraft(DraftModel):
    """A separate small GPT drafting against its own
    :class:`PagedKVCache` whose slot ids mirror the target's.

    The deficit-zero invariant: every :meth:`propose` runs ``k+1``
    chained sub-steps — the extra step exists purely to write the K/V
    row for the last proposal — so the draft cache always holds rows
    for exactly the positions the target holds once :meth:`commit`
    truncates both to the accepted boundary.  Admission prefills the
    draft cache from the same prompt (same tokenizer — the factory
    enforces matching vocab)."""

    mode = "draft"

    def __init__(self, model: Any, k: int, max_slots: int,
                 buckets: Optional[Sequence[int]] = None) -> None:
        import jax
        from .. import compile_cache as _cc
        if not isinstance(model, DecodeModel):
            model = DecodeModel.from_block(model)
        self.model = model
        self.k = int(k)
        if self.k < 1:
            raise MXNetError(f"speculative k must be >= 1, got {k}")
        self.cache = PagedKVCache(
            model.n_layers, model.num_heads, model.head_dim,
            int(max_slots), buckets=buckets, dtype=model.dtype,
            prefix_slots=0)
        if self.cache.grid[-1] > model.max_length:
            raise MXNetError(
                f"draft model context {model.max_length} is shorter "
                f"than the KV bucket grid top {self.cache.grid[-1]} — "
                "the draft could not follow a full-length sequence")
        nh, ga_s = model.num_heads, model.ga
        n_sub = self.k + 1

        def _propose(params, ks, vs, toks, pos, seeds, bases, temps,
                     topks, topps, methods):
            outs, ks, vs = _chain_steps(params, ks, vs, toks, pos,
                                        seeds, bases, temps, topks,
                                        topps, methods, n_sub, nh,
                                        ga_s)
            return outs, ks, vs

        # the draft cache's buffers are donated exactly like the
        # target step's: the chain updates them in place
        self._fn = _cc.persistently_cached(
            jax.jit(_propose, donate_argnums=(1, 2)),
            surface="serving.decode", pin=True)

    def admit(self, slot: int, tokens: _np.ndarray,
              prompt_buckets: Sequence[int]) -> None:
        toks = _np.asarray(tokens, _np.int32).reshape(-1)
        t0 = toks.shape[0]
        pb = round_up_bucket(t0, prompt_buckets)
        _, ks, vs = self.model.prefill(toks, pb)
        self.cache.write_prompt(slot, ks, vs, t0)

    def release(self, slot: int) -> None:
        self.cache.free(slot)

    def commit(self, slot: int, position: int) -> None:
        if self.cache.positions[slot] < 0:
            return
        dp = int(self.cache.positions[slot])
        # propose wrote rows dp..dp+k; adopt them, then rewind to the
        # target's accepted boundary (== dp+k+1 on full acceptance)
        self.cache.positions[slot] = dp + self.k + 1
        if position < dp + self.k + 1:
            self.cache.truncate(slot, position)

    def evacuate(self) -> None:
        self.cache.positions.fill(-1)
        self.cache.reset_buffers()

    def reset(self) -> None:
        self.cache.reset_buffers()

    def reset_if_empty(self) -> None:
        self.cache.reset_if_empty()

    def warmup(self, prompt_buckets: Sequence[int]) -> int:
        n = 0
        one = _np.zeros((1,), _np.int32)
        for pb in prompt_buckets:
            if pb > self.model.max_length:
                continue
            self.model.prefill(one, int(pb))
            n += 1
        n += self.cache.warmup_writes(prompt_buckets)
        S = self.cache.max_slots
        toks = _np.zeros((S,), _np.int32)
        for b in self.cache.grid:
            self.cache.bucket = int(b)
            self.cache._alloc_buffers(self.cache.bucket)
            self.propose(None, toks, None)
            n += 1
        self.cache.bucket = self.cache.grid[0]
        self.cache._alloc_buffers(self.cache.bucket)
        return n

    def propose(self, cache: Any, last_tok: _np.ndarray,
                positions: _np.ndarray = None,
                sampling: Optional[Sequence[Any]] = None) -> _np.ndarray:
        # ``cache``/``positions`` are the TARGET's — the draft follows
        # its own mirror (equal for every speculative slot by the
        # deficit-zero invariant; free/non-speculative slots ride at 0
        # and their proposals are discarded)
        import jax
        import jax.numpy as jnp
        S = self.cache.max_slots
        if sampling is None:
            sampling = self.model.greedy_sampling(S)
        if not isinstance(sampling[0], jax.Array):
            sampling = self.model.device_sampling(sampling)
        self.cache.ensure_capacity(
            min(self.cache.needed_capacity() + self.k,
                self.cache.grid[-1]))
        pos = _np.maximum(self.cache.positions, 0).astype(_np.int32)
        self.model._account(
            f"draft:{S}x{self.cache.bucket}x{self.k}")
        t = time.perf_counter()
        outs, new_ks, new_vs = self._fn(
            self.model.params, self.cache._k, self.cache._v,
            jnp.asarray(_np.asarray(last_tok, _np.int32)),
            jnp.asarray(pos), *sampling)
        self.cache.replace(new_ks, new_vs)
        out = _np.asarray(outs)[:, :self.k]
        from .. import tracing as _tracing
        _metrics.GEN_STEP_SECONDS.labels(phase="draft").observe(
            time.perf_counter() - t,
            exemplar=_tracing.current_trace_id())
        return out

    def describe(self) -> dict:
        return {"mode": self.mode, "k": self.k,
                "draft_model": self.model.describe(),
                "draft_cache": self.cache.describe()}


def make_draft(mode: Optional[str], target: DecodeModel, k: int,
               layers: int = 0, draft_model: Any = None,
               max_slots: int = 0,
               buckets: Optional[Sequence[int]] = None
               ) -> Optional[DraftModel]:
    """Build the draft the engine's spec config asks for (None when
    ``mode`` is off/empty)."""
    if mode in (None, "", "off"):
        return None
    if mode == "self":
        return SelfSpeculativeDraft(target, k, layers)
    if mode == "draft":
        if draft_model is None:
            raise MXNetError(
                "speculative mode 'draft' needs a draft model "
                "(pass draft_model= to the engine; MXNET_GEN_SPEC_MODE "
                "alone cannot conjure one)")
        d = IndependentDraft(draft_model, k, max_slots, buckets=buckets)
        if d.model.vocab_size != target.vocab_size:
            raise MXNetError(
                f"draft vocab {d.model.vocab_size} != target vocab "
                f"{target.vocab_size} — speculation requires a shared "
                "tokenizer")
        return d
    raise MXNetError(
        f"unknown speculative mode {mode!r} (want off|self|draft)")
