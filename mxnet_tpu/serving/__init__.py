"""Inference serving — the missing half of the deployment story.

Reference parity (leezu/mxnet): the reference pairs its training runtime
with a standalone predict path (``src/c_predict_api.cc`` — load a
symbol+params export, bind an inference-only executor, answer forwards
with no Python).  It never shipped a *server*; model serving was left to
MMS/TorchServe-era sidecars that called the predict API per request.

Design (tpu-first): on TPU the two costs that dominate request serving
are (1) per-request dispatch of tiny batches — the MXU is idle below
batch ~8 — and (2) recompiles: every distinct input shape traced through
XLA is a fresh multi-second compilation.  This subsystem addresses both
in-process, with no dependencies beyond the stdlib:

* :class:`~mxnet_tpu.serving.batching.BucketPolicy` — pad-to-bucket
  shape policy: request batches round UP to a configured batch bucket
  (and, opt-in, variable-length samples pad to a length bucket), so the
  number of distinct compiled executables is bounded by the bucket grid,
  not by traffic.
* :class:`~mxnet_tpu.serving.batching.DynamicBatcher` — bounded request
  queue + micro-batch assembly: flush on a full bucket or on the oldest
  request's batching timeout; overload (queue full / deadline passed)
  sheds requests with a structured :class:`OverloadError` instead of
  piling latency onto everyone behind them.
* :class:`~mxnet_tpu.serving.model.ServedModel` — the executable: an
  ``export()`` artifact (StableHLO, incl. the ``dynamic_batch``
  polymorphic form) or a live (Hybrid)Block/Module, behind one
  ``predict(arrays) -> arrays`` surface with per-bucket compile
  accounting and warmup.
* :class:`~mxnet_tpu.serving.server.ModelServer` — composition +
  lifecycle: worker thread, futures-based in-process API, metrics.
* :mod:`~mxnet_tpu.serving.http` — a stdlib ``http.server`` front end
  (``tools/serve.py``): POST /v1/inference, POST /v1/generate (chunked
  per-token streaming), GET /metrics (Prometheus text from the PR-1
  registry), GET /healthz.
* :class:`~mxnet_tpu.serving.generation.GenerationEngine` +
  :class:`~mxnet_tpu.serving.kv_cache.PagedKVCache` +
  :class:`~mxnet_tpu.serving.model.DecodeModel` — iteration-level
  CONTINUOUS BATCHING for autoregressive LLM generation: a resident,
  bucket-compiled decode step over a slot-based KV cache, admission
  between decode iterations, per-step EOS/max-token retirement, and
  per-token streaming (:class:`TokenStream`), hosted by
  :class:`~mxnet_tpu.serving.server.GenerationServer`.

* :mod:`~mxnet_tpu.serving.speculation` (ISSUE 17) — SPECULATIVE
  DECODING: a :class:`DraftModel` (either a truncated-layer
  :class:`SelfSpeculativeDraft` reusing the target's own weights and KV
  rows, or an :class:`IndependentDraft` wrapping a small same-tokenizer
  model with its own mirrored :class:`PagedKVCache`) proposes ``k``
  tokens per engine iteration; a single bucket-compiled ``verify_k``
  target pass scores all of them at once, the accept rule replays the
  per-slot counter-PRNG lanes so output stays **byte-identical** to
  non-speculative decoding at the same seed, and rejected rows roll
  back via ``PagedKVCache.truncate``.

* :mod:`~mxnet_tpu.serving.replica` + the server-side resilience layer
  (ISSUE 7): both servers host ``MXNET_SERVING_REPLICAS`` worker
  replicas behind a router — a dead worker's requests requeue (and
  in-flight generation streams resume **token-identical**, exactly-once
  at the :class:`TokenStream` index boundary) on the survivors while a
  :class:`~mxnet_tpu.serving.replica.ReplicaSupervisor` restarts it
  with jittered backoff behind a circuit breaker (explicit
  :class:`DegradedError` degraded mode past the budget); SIGTERM
  drains gracefully (:func:`serve_until_preempted`: 429 sheds,
  readiness 503 / liveness 200, bounded by
  ``MXNET_SERVING_DRAIN_DEADLINE_S``, exit 0).

Every stage publishes to :mod:`mxnet_tpu.metrics` (queue-depth gauge,
batch-size / queue-wait / inference-latency histograms, shed counter by
reason, per-bucket compile counter, recovery/restart/breaker/drain
families) — ``metrics_dump.py``-style observability works out of the
box.
"""
from .batching import (BucketPolicy, DynamicBatcher, OverloadError,
                       Request, SlotScheduler)
from .model import DecodeModel, ServedModel, load_served
from .kv_cache import PagedKVCache, PrefixCache
from .generation import GenerationEngine, StreamTimeout, TokenStream
from .replica import ReplicaSupervisor
from .server import (DegradedError, GenerationServer, ModelServer,
                     serve_until_preempted)
from .speculation import (DraftModel, IndependentDraft,
                          SelfSpeculativeDraft)
from .http import make_http_server

__all__ = [
    "BucketPolicy", "DynamicBatcher", "OverloadError", "Request",
    "SlotScheduler", "ServedModel", "DecodeModel", "PagedKVCache",
    "PrefixCache", "GenerationEngine", "StreamTimeout", "TokenStream",
    "GenerationServer", "load_served", "ModelServer", "DegradedError",
    "DraftModel", "SelfSpeculativeDraft", "IndependentDraft",
    "ReplicaSupervisor", "make_http_server", "serve_until_preempted",
]
